"""The ``tpu_binpack`` placement engine.

Replaces the reference's per-node iterator chain
(GenericScheduler.computePlacements -> GenericStack.Select -> BinPackIterator,
scheduler/generic_sched.go:426 / rank.go:176) with ONE ``jax.jit``'d
``lax.scan`` over the evaluation's placement sequence. Each scan step scores
every node at once:

  feasibility  = class-mask  &  capacity-fit  &  distinct-hosts   (vector ops)
  score terms  = binpack (BestFit-v3) + job-anti-affinity + reschedule
                 penalty + node affinity + spread                  (vector ops)
  selection    = exact emulation of the ring-ordered LimitIterator
                 (log2 N window, skip<=3 below 0.0) + MaxScore     (cumsums,
                 masked argmax)

and the carry threads the intra-eval mutation the reference gets from
ProposedAllocs (context.go:120): used capacity, per-TG/job alloc counts,
spread value counts, the source-iterator ring offset, and failed-TG
coalescing. In deterministic mode the engine is plan-for-plan identical to
the host pipeline; tests/test_tpu_parity.py fuzzes that equivalence.

The node axis is the scale axis: all [N]-shaped arrays may be sharded over a
``jax.sharding.Mesh`` (see nomad_tpu/parallel/), with XLA inserting the
all-reduce/argmax collectives.
"""
from __future__ import annotations

import logging
import time as _time
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs.structs import (
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
)
from ..structs.network import NetworkIndex
from .encode import (
    DIM_CPU,
    DIM_MBITS,
    DIM_MEM,
    MAX_PENALTY_NODES,
    NodeTable,
    TGSpec,
    UnsupportedByEngine,
    _distinct_property_arrays,
    build_node_table,
    build_tg_spec,
    job_device_dims,
)
from ..utils.lock_witness import witness_lock

logger = logging.getLogger("nomad_tpu.tpu.engine")

MAX_SKIP = 3

# Deterministic sampler for the chunked tier's parity spot checks: tests
# reseed it to make the sampling decision reproducible. The chunked tier
# only runs on float-mode (non-deterministic) encodes, so the RNG never
# influences a deterministic-mode plan.
import random as _random

_PARITY_SAMPLE_RNG = _random.Random(0xC47A)

# Partial OCC retries below device_min_placements still ride the device
# when their compile bucket is already warm (see compute_placements) —
# but only above this floor; 1-2 placement stragglers stay on the host.
RETRY_DEVICE_FLOOR = 4

# GIL convoy guard shared with the scheduler's other host phases
# (utils/hostwork.py): encode/apply are pure-Python, so letting hundreds
# of worker threads enter them at once only buys context-switch thrash.
from ..utils.hostwork import HOST_WORK_SEM as _HOST_WORK_SEM


class EncodedEval:
    """One evaluation's placement problem as dense numpy arrays, plus the
    host-side context needed to materialize results into a Plan. Produced
    by ``TpuPlacementEngine.encode_eval``; consumed by the single-eval scan
    or stacked with other evals by the DeviceBatcher."""

    __slots__ = (
        "n_real", "n_pad", "g", "s", "v", "p", "dtype",
        "static", "carry", "xs",
        "missing_list", "nodes", "table", "start_ns", "dense_ok",
        "pre_allocs",
    )

    def __init__(self, *, n_real, n_pad, g, s, v, p, dtype,
                 static, carry, xs, missing_list, nodes, table, start_ns,
                 dense_ok=False, pre_allocs=None):
        self.n_real = n_real
        self.n_pad = n_pad
        self.g = g
        self.s = s
        self.v = v
        self.p = p
        self.dtype = dtype
        self.static = static
        self.carry = carry
        self.xs = xs
        self.missing_list = missing_list
        self.nodes = nodes
        self.table = table
        self.start_ns = start_ns
        # True when every placement qualifies for the dense plan->FSM
        # path (fresh, no networks/devices/canaries): results stay as
        # arrays end to end (structs.DenseTGPlacements)
        self.dense_ok = dense_ok
        # Device-side preemption (tpu/preempt.py): per-node candidate
        # Allocation lists parallel to the encoded candidate slots, for
        # mapping eviction-set output columns back to real allocs. None
        # when the eval encodes no preemption.
        self.pre_allocs = pre_allocs


def _pad_preempt_arrays(pre_tables, n_pad, n_real, node_c2):
    """Pad one eval's PreemptTables (encode.build_preempt_tables) to the
    node grid and derive the Q27 eviction-free factors. ``None`` tables
    yield width-0 arrays — the step's whole eviction block compiles away
    (``has_pre`` is a shape test). Returns the 6 static entries followed
    by the 3 carry seeds."""
    if pre_tables is None:
        return (
            np.zeros((n_pad, 0, 4), np.int32), np.zeros((n_pad, 0), np.int32),
            np.zeros((n_pad, 0), bool), np.zeros((n_pad, 0), np.int32),
            np.zeros((n_pad, 0), np.int32), np.zeros((n_pad, 0, 2), np.int32),
            np.zeros((n_pad, 0), bool), np.zeros((0, 3), np.int64),
            np.zeros(0, np.int32),
        )
    from .intscore import E27_ONE, e27_np, xq_np

    c_w = pre_tables.c
    pre_res = np.zeros((n_pad, c_w, 4), np.int32)
    pre_res[:n_real] = pre_tables.res4
    pre_prio = np.zeros((n_pad, c_w), np.int32)
    pre_prio[:n_real] = pre_tables.prio
    pre_elig = np.zeros((n_pad, c_w), bool)
    pre_elig[:n_real] = pre_tables.elig
    pre_mp = np.zeros((n_pad, c_w), np.int32)
    pre_mp[:n_real] = pre_tables.mp
    pre_gid = np.zeros((n_pad, c_w), np.int32)
    pre_gid[:n_real] = pre_tables.gid
    # Eviction FREES capacity: Q27 factor e27(+res/cap) per candidate on
    # cpu/mem — same convention as the destructive-update ev_factor.
    # Padded nodes / empty slots hold the neutral factor.
    pre_evf = np.full((n_pad, c_w, 2), E27_ONE, np.int32)
    for d in (0, 1):
        pre_evf[:, :, d] = e27_np(
            xq_np(pre_res[:, :, d].astype(np.int64),
                  np.maximum(node_c2[:, d], 1)[:, None])
        ).astype(np.int32)
    pre_alive0 = np.ones((n_pad, c_w), bool)
    pre_remaining0 = np.zeros((n_pad, 3), np.int64)
    pre_remaining0[:n_real] = pre_tables.remaining3
    pre_counts0 = pre_tables.counts0.astype(np.int32)
    return (pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf,
            pre_alive0, pre_remaining0, pre_counts0)


_cache_enabled = False


def _enable_persistent_compile_cache() -> None:
    """Persistent XLA compilation cache: scan compiles are tens of seconds
    per shape bucket, and the server process restarts far more often than
    the bucket set changes. Opt out with NOMAD_TPU_XLA_CACHE=0 or point
    NOMAD_TPU_XLA_CACHE at a directory."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    path = os.environ.get("NOMAD_TPU_XLA_CACHE")
    if path == "0":
        return
    if not path:
        path = os.path.join(
            os.path.expanduser("~"), ".cache", "nomad_tpu", "xla"
        )
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # cache is an optimization; never fail the engine
        logger.debug("persistent compile cache unavailable", exc_info=True)


def _round_up(n: int, multiple: int = 128) -> int:
    if n <= multiple:
        # small clusters: pad to next power of two to bound recompiles
        p = 8
        while p < n:
            p *= 2
        return p
    return ((n + multiple - 1) // multiple) * multiple


# ---------------------------------------------------------------------------
# The jit'd scan (pure function of arrays)
# ---------------------------------------------------------------------------


def _make_step():
    """The per-placement scan body, shared by the single-eval scan, the
    eval-batched scan (vmapped over independent evals — the production
    multi-eval path) and the dryrun. Pure function of arrays.

    TPU-shaped by construction (empirically profiled on the real chip):
      - NO gathers/scatters: dynamic row-selects (``asks[g]``-style) and
        carry updates become one-hot ``where``+``sum``/outer-product adds —
        batched gathers cost ~ms each on TPU while the one-hot forms fuse
        into elementwise kernels.
      - NO dot_general: f64 has no MXU path, so one-hot einsums would
        lower to sequential while-loops; ``where``+``sum`` reduces stay on
        the VPU.
      - NO permutation: the ring-ordered LimitIterator emulation uses
        offset-adjusted NATURAL cumsums (ring prefix at natural index i is
        an elementwise function of one natural cumsum and two scalars),
        and tie-breaks select via rank equality, never ``perm[idx]``.
    All transformations are exact (integer adds / one-hot sums with a
    single non-zero term), so outputs are bit-identical to the direct
    indexed formulation — fuzz-asserted against the host pipeline in
    tests/test_tpu_parity.py."""
    import jax.numpy as jnp
    from jax import lax as jlax

    from .intscore import (
        FEAT_AFF_BIT,
        FEAT_FEAS_BIT,
        PACK_COUNT_MAX,
        pack_count_lanes,
        pack_presence_lanes,
        unpack_count_hi,
        unpack_count_lo,
        unpack_feat_lane,
    )

    def step(static, carry, x):
        (totals, reserved, asks, feat_packed, aff_score, desired_counts,
         dh_job, dh_tg, limits, spread_vids, spread_desired, spread_weights,
         spread_has_targets, spread_active, sum_spread_weights, n_real,
         e_ask, dp_vids, dp_limit, dp_applies,
         pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf) = static
        (used, tg_counts, job_counts, spread_counts, spread_entry, offset,
         failed, e_base, dp_counts, pre_alive, pre_remaining, pre_counts) = carry
        (tg_idx, penalty_idx, evict_node, evict_res, evict_tg, limit_p,
         sum_sw_p, ev_factor, rev_factor, forced_node) = x

        n_pad = totals.shape[0]
        g_count = asks.shape[0]
        v_plus = spread_desired.shape[-1]
        fdt = totals.dtype
        # int mode (deterministic/parity): the exact integer spec of
        # tpu/intscore.py. e_base/e_ask carry the Q27 incremental
        # exponentials; float (throughput) mode passes them zero-sized.
        int_mode = jnp.issubdtype(fdt, jnp.integer)
        i64 = jnp.int64
        g = tg_idx

        iota_g = jnp.arange(g_count, dtype=jnp.int32)
        sel_g = (iota_g == g)                       # [G] one-hot of the TG
        iota = jnp.arange(n_pad, dtype=jnp.int32)
        iota_v = jnp.arange(v_plus, dtype=jnp.int32)

        def pick_g(arr, fill=0):
            # arr[g] without gather/dot: one-hot mask + sum (exactly one
            # non-zero term, so float results are exact). Sum-promotion
            # (int32 -> int64 under x64) is cast back so carries keep
            # their dtypes.
            shape = (g_count,) + (1,) * (arr.ndim - 1)
            out = jnp.sum(jnp.where(sel_g.reshape(shape), arr, fill), axis=0)
            return out.astype(arr.dtype)

        skip_step = jnp.any(sel_g & failed)

        # -- eviction of the previous alloc (one-hot adds) -----------------
        # shape specialization: an eval with NO destructive updates (the
        # common case — every fresh placement) encodes evict_res with a
        # ZERO trailing axis, and the entire eviction/revert machinery
        # (~15 array passes per step) compiles away.
        has_evict = evict_res.shape[-1] > 0
        if has_evict:
            do_evict = (evict_node >= 0) & (~skip_step)
            ev_node = jnp.maximum(evict_node, 0)
            ev_tg = jnp.maximum(evict_tg, 0)
            oh_ev_node = (iota == ev_node)              # [N]
            oh_ev_nodef = oh_ev_node.astype(fdt)
            sel_evg = (iota_g == ev_tg)                 # [G]

            def pick_evg(arr, fill=0):
                shape = (g_count,) + (1,) * (arr.ndim - 1)
                out = jnp.sum(jnp.where(sel_evg.reshape(shape), arr, fill), axis=0)
                return out.astype(arr.dtype)

            evict_vec = jnp.where(do_evict, evict_res, 0)  # [D]
            used = used - oh_ev_nodef[:, None] * evict_vec[None, :]
            dec_tg = jnp.where(do_evict & (evict_tg >= 0), 1, 0)
            tg_counts = tg_counts - (sel_evg[:, None] & oh_ev_node[None, :]) * dec_tg
            job_counts = job_counts - oh_ev_node * jnp.where(do_evict, 1, 0)
            # The evicted alloc's spread usage clears too (host: propertyset
            # cleared_values from plan.node_update; floor-at-zero at read).
            ev_active = pick_evg(spread_active, False)       # [S]
            ev_dec = jnp.where(do_evict & (evict_tg >= 0) & ev_active, 1, 0).astype(fdt)
            vids_evg = pick_evg(spread_vids)                 # [S, N]
            ev_vid = jnp.sum(jnp.where(oh_ev_node[None, :], vids_evg, 0), axis=1)
            oh_ev_vid = (iota_v[None, :] == ev_vid[:, None]).astype(fdt)  # [S, V]
            spread_counts = spread_counts - jnp.where(
                sel_evg[:, None, None], (oh_ev_vid * ev_dec[:, None])[None, :, :], 0
            )
            # eviction frees capacity -> multiply the node's Q27
            # exponential by the precomputed per-placement factor
            if e_base.shape[0]:
                from .intscore import E27_BITS, E27_ONE

                ev_f = jnp.where(do_evict, ev_factor, E27_ONE).astype(i64)  # [2]
                eb_ev = (e_base.astype(i64) * ev_f[None, :]) >> E27_BITS
                e_base = jnp.where(
                    oh_ev_node[:, None], eb_ev, e_base.astype(i64)
                ).astype(jnp.int32)
            # (distinct_property + in-eval evictions never encode together
            # — the host PropertySet cleared-refund quirk can't be
            # replayed by exact counters; encode gates that combination)

        # -- row selects ---------------------------------------------------
        ask = pick_g(asks)                               # [D]
        # ONE packed uint8 feature plane carries feasibility and affinity
        # presence (intscore.pack_feat_planes): one pick_g pass where the
        # unpacked layout needed two
        feat_g = pick_g(feat_packed)                     # [N] uint8
        feas_g = unpack_feat_lane(feat_g, FEAT_FEAS_BIT)
        tg_counts_g = pick_g(tg_counts)                  # [N]
        desired_g = pick_g(desired_counts).astype(fdt)
        dh_job_g = jnp.any(sel_g & dh_job)
        dh_tg_g = jnp.any(sel_g & dh_tg)
        # shape specialization (compile-time): a job without affinities
        # encodes aff_score with a ZERO G axis, so the f64 pick and the
        # score term vanish from the compiled step entirely (the packed
        # plane's affinity lane is all-zero and never read)
        if aff_score.shape[0] == 0:
            aff = jnp.zeros(n_pad, fdt)
            aff_p = jnp.zeros(n_pad, bool)
        else:
            aff = pick_g(aff_score)
            aff_p = unpack_feat_lane(feat_g, FEAT_AFF_BIT)

        # -- feasibility ---------------------------------------------------
        # int mode folds reserved into totals at encode (the scoring
        # exponentials are precomputed factors, so nothing else needs the
        # split) and passes a ZERO-height reserved — one [N, D] add less
        # per step
        if reserved.shape[0]:
            util = used + reserved + ask[None, :]  # [N, D]
        else:
            util = used + ask[None, :]
        fits = jnp.all(util <= totals, axis=-1)  # superset + bandwidth check

        # job-level distinct_hosts: any co-located alloc of the job rejects;
        # tg-level requires both a job and task-group collision
        dh_mask = jnp.where(
            dh_job_g,
            job_counts == 0,
            jnp.where(dh_tg_g, ~((tg_counts_g > 0) & (job_counts > 0)), True),
        )

        # -- device-side preemption (tpu/preempt.py) -----------------------
        # shape specialization: non-preempting evals encode the candidate
        # axis C as ZERO width and the whole greedy sweep compiles away.
        # When present, a node whose capacity check fails may be rescued
        # by an eviction set of lower-priority allocs (the reference's
        # PreemptForTaskGroup): cap_ok = fits | pre_met. Preemption never
        # rescues class/constraint/distinct-hosts infeasibility — those
        # masks still AND in below, matching the host stack ordering.
        has_pre = pre_res.shape[1] > 0
        if has_pre:
            from .preempt import CQ_BITS, PENALTY_UNIT, greedy_select_jnp

            gp_w = pre_counts.shape[0]
            iota_gp = jnp.arange(gp_w, dtype=jnp.int32)
            # num preemptions already planned for each candidate's
            # (job, ns, tg) group — the reference's maxParallel penalty
            oh_gid = pre_gid[:, :, None] == iota_gp[None, None, :]
            num_pre = jnp.sum(
                jnp.where(oh_gid, pre_counts[None, None, :], 0), axis=-1
            ).astype(jnp.int32)                                    # [N, C]
            pen = jnp.where(
                (pre_mp > 0) & (num_pre >= pre_mp),
                (((num_pre + 1) - pre_mp).astype(i64) * PENALTY_UNIT)
                << CQ_BITS,
                i64(0),
            )
            ask3 = ask[:3].astype(i64)                             # cpu/mem/disk
            pre_res3 = pre_res[:, :, :3].astype(i64)
            sel_ord, pre_met = greedy_select_jnp(
                ask3, pre_res3, pre_prio, pen,
                pre_alive & pre_elig, pre_remaining,
            )
            cap_ok = fits | pre_met
        else:
            cap_ok = fits

        feasible = feas_g & cap_ok & dh_mask  # [N]
        # system-scheduler mode: the candidate node is FIXED per placement
        # (one alloc per eligible node, system_sched.go:268-286); a
        # zero-width axis (generic evals) compiles the restriction away
        if forced_node.shape[-1]:
            fnode = forced_node[0]
            feasible = feasible & ((fnode < 0) | (iota == fnode))

        # distinct_property (feasible.go:353): per-constraint value-count
        # carry, same mechanism as spread counts but FILTERING — a node is
        # infeasible when its value's count reached the allowed limit or
        # the property is missing. D == 0 compiles all of this away.
        if dp_vids.shape[0]:
            v2 = dp_counts.shape[-1]
            iota_v2 = jnp.arange(v2, dtype=jnp.int32)
            oh_dpv = dp_vids[:, None, :] == iota_v2[None, :, None]  # [D, V2, N]
            dp_cnts = jnp.maximum(dp_counts, 0)  # cleared-value floor
            dp_cnt_n = jnp.sum(
                jnp.where(oh_dpv, dp_cnts[:, :, None], 0), axis=1
            )  # [D, N]
            dp_applies_g = pick_g(dp_applies, False)  # [D]
            dp_missing = dp_vids == (v2 - 1)
            dp_ok = (~dp_applies_g[:, None]) | (
                (~dp_missing) & (dp_cnt_n < dp_limit[:, None])
            )
            feasible = feasible & jnp.all(dp_ok, axis=0)

        # -- score terms ---------------------------------------------------
        # Two compile-time modes sharing one structure:
        #   int  (deterministic/parity): the exact integer spec of
        #        tpu/intscore.py — Q30 terms, Q27 incremental-multiplicative
        #        exponentials, score60 selection. Bit-identical on every
        #        backend, so plan parity holds ON the real TPU.
        #   float (throughput): f32 arithmetic, non-parity.
        # same specialization: no reschedule history -> penalty_idx has a
        # zero K axis and the [N, K] compare disappears
        if penalty_idx.shape[-1] == 0:
            pmask = jnp.zeros(n_pad, bool)
        else:
            pmask = jnp.any(iota[:, None] == penalty_idx[None, :], axis=-1)

        anti_present = tg_counts_g > 0

        # spread row selects (shared) — value-id lookups as one-hot sums
        vids = pick_g(spread_vids)                       # [S, N]
        # floor-at-zero matches the host's cleared-value clamping
        s_counts = jnp.maximum(pick_g(spread_counts), 0)    # [S, V]
        s_entry = pick_g(spread_entry, False)            # [S, V]
        desired_sv = pick_g(spread_desired)              # [S, V]
        weights_s = pick_g(spread_weights)
        has_targets_s = pick_g(spread_has_targets, False)
        active_s = pick_g(spread_active, False)

        invalid_bucket = v_plus - 1
        oh_vids = vids[:, None, :] == iota_v[None, :, None]  # [S, V, N]
        current = jnp.sum(jnp.where(oh_vids, s_counts[:, :, None], 0), axis=1)
        d = jnp.sum(jnp.where(oh_vids, desired_sv[:, :, None], 0), axis=1)
        missing = vids == invalid_bucket
        has_entries = jnp.any(s_entry[:, :invalid_bucket], axis=-1)  # [S]

        if int_mode:
            from .intscore import (
                BIG_FP,
                E27_BITS,
                E27_ONE,
                RECIP_BITS,
                TERM_BITS,
                TERM_ONE,
            )

            # selection-time exponentials: e_base (running product in the
            # carry) times the static per-TG ask factor — 10**(free - ask/cap)
            ea = pick_g(e_ask)                                 # [N, 2] int32
            e_sel = (e_base.astype(i64) * ea.astype(i64)) >> E27_BITS
            e_sel_i32 = e_sel.astype(jnp.int32)                # placement update
            fit = i64(20 * E27_ONE) - e_sel[:, 0] - e_sel[:, 1]
            fit = jnp.clip(fit, 0, 18 * E27_ONE)
            # Q30 = fit * 2**30 / (18 * 2**27) = (fit*4)//9 (const divisor)
            binpack = (fit * 4) // 9

            rsh = RECIP_BITS - TERM_BITS
            # -(c+1)/desired via the Q45 reciprocal of the (small, per-step
            # scalar) desired count — error < 4 Q30-ulp
            q_d = jnp.floor_divide(
                i64(1 << RECIP_BITS), jnp.maximum(desired_g.astype(i64), 1)
            )
            anti = jnp.where(
                anti_present,
                -(((tg_counts_g.astype(i64) + 1) * q_d) >> rsh),
                0,
            )
            resched = jnp.where(pmask, i64(-TERM_ONE), i64(0))

            d64 = d.astype(i64)
            u64 = current.astype(i64) + 1
            w64 = weights_s.astype(i64)[:, None]
            sw64 = jnp.maximum(sum_sw_p.astype(i64), 1)
            # targeted boost: ((d - u)/d)*(w/sum_w) as ONE fused Q30
            # rational, floor-rounded (d in hundredths: d = pct*count)
            t_num = (d64 - 100 * u64) * w64 * TERM_ONE
            t_den = jnp.maximum(d64, 1) * sw64
            targeted_raw = jnp.where(
                d64 > 0,
                jnp.floor_divide(t_num, t_den),
                jnp.where(d64 == 0, i64(-BIG_FP), i64(-TERM_ONE)),
            )

            # even-spread boost (same branch structure as the host);
            # divisions by min_c (a count) via its Q45 reciprocal — [S]-
            # shaped, so the division is off the hot [N] axis
            LARGE = i64(1) << 40
            sc64 = s_counts.astype(i64)[:, :invalid_bucket]
            se = s_entry[:, :invalid_bucket]
            min_c = jnp.where(
                has_entries, jnp.min(jnp.where(se, sc64, LARGE), axis=-1), 0
            )  # [S]
            max_c = jnp.where(
                has_entries, jnp.max(jnp.where(se, sc64, -LARGE), axis=-1), 0
            )
            r_min = jnp.floor_divide(
                i64(1 << RECIP_BITS), jnp.maximum(min_c, 1)
            )  # [S]
            min_cn = min_c[:, None]
            cur64 = current.astype(i64)
            delta_boost = jnp.where(
                min_cn == 0,
                i64(-TERM_ONE),
                ((min_cn - cur64) * r_min[:, None]) >> rsh,
            )
            even = jnp.where(
                cur64 != min_cn,
                delta_boost,
                jnp.where(
                    min_cn == max_c[:, None],
                    i64(-TERM_ONE),
                    jnp.where(
                        min_cn == 0,
                        i64(TERM_ONE),
                        ((max_c[:, None] - min_cn) * r_min[:, None]) >> rsh,
                    ),
                ),
            )
            even = jnp.where(has_entries[:, None], even, 0)

            per_spread = jnp.where(has_targets_s[:, None], targeted_raw, even)
            per_spread = jnp.where(missing, i64(-TERM_ONE), per_spread)
            per_spread = jnp.where(active_s[:, None], per_spread, 0)
            spread_total = jnp.sum(per_spread, axis=0)  # [N] int64
            spread_p = spread_total != 0

            # term-presence bits packed into ONE uint8 plane: num_terms is
            # 1 + popcount instead of four astype(int32) planes and adds —
            # the whole (presence -> factor -> final) chain is a single
            # fused elementwise expression over [N]
            presence = pack_presence_lanes(anti_present, pmask, aff_p, spread_p)
            num_terms = 1 + jlax.population_count(presence).astype(jnp.int32)
            # mean of terms via EXACT scale-by-60 (all of 1..5 divide 60)
            factor = jnp.floor_divide(60, num_terms).astype(i64)
            final = (
                binpack + anti + resched
                + jnp.where(aff_p, aff.astype(i64), 0) + spread_total
            ) * factor
            neg_inf = jnp.iinfo(jnp.int64).min // 4
            score_zero = i64(0)
        else:
            node_cpu = totals[:, DIM_CPU] - reserved[:, DIM_CPU]
            node_mem = totals[:, DIM_MEM] - reserved[:, DIM_MEM]
            free_cpu = 1.0 - util[:, DIM_CPU] / jnp.maximum(node_cpu, 1e-9)
            free_mem = 1.0 - util[:, DIM_MEM] / jnp.maximum(node_mem, 1e-9)
            fitness = 20.0 - (jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem))
            binpack = jnp.clip(fitness, 0.0, 18.0) / 18.0

            collisions = tg_counts_g.astype(fdt)
            anti = jnp.where(anti_present, -(collisions + 1.0) / desired_g.astype(fdt), 0.0)
            resched = jnp.where(pmask, -1.0, 0.0)

            big = jnp.finfo(fdt).max / 16.0
            used_count = current.astype(fdt) + 1.0           # [S, N]
            df = d.astype(fdt)
            # divisor: the host SpreadIterator's weight sum accumulates
            # across visited task groups -> passed per placement (sum_sw_p)
            weight_frac = weights_s[:, None] / jnp.maximum(sum_sw_p, 1e-9)
            # Go float semantics: d == 0 -> -Inf boost (clamped large neg)
            targeted_raw = jnp.where(
                df > 0.0,
                (df - used_count) / jnp.where(df > 0.0, df, 1.0) * weight_frac,
                jnp.where(df == 0.0, -big, -1.0),  # d<0: no target -> -1
            )

            # even-spread boost
            scf = s_counts.astype(fdt)[:, :invalid_bucket]
            entry_counts = jnp.where(s_entry[:, :invalid_bucket], scf, jnp.inf)
            min_c = jnp.where(has_entries, jnp.min(entry_counts, axis=-1), 0.0)  # [S]
            max_counts = jnp.where(s_entry[:, :invalid_bucket], scf, -jnp.inf)
            max_c = jnp.where(has_entries, jnp.max(max_counts, axis=-1), 0.0)
            currentf = current.astype(fdt)
            delta_boost = jnp.where(
                min_c[:, None] == 0.0, -1.0,
                (min_c[:, None] - currentf) / jnp.maximum(min_c[:, None], 1e-9)
            )
            even = jnp.where(
                currentf != min_c[:, None],
                delta_boost,
                jnp.where(
                    min_c[:, None] == max_c[:, None],
                    -1.0,
                    jnp.where(
                        min_c[:, None] == 0.0,
                        1.0,
                        (max_c[:, None] - min_c[:, None]) / jnp.maximum(min_c[:, None], 1e-9),
                    ),
                ),
            )
            even = jnp.where(has_entries[:, None], even, 0.0)

            per_spread = jnp.where(has_targets_s[:, None], targeted_raw, even)
            per_spread = jnp.where(missing, -1.0, per_spread)
            per_spread = jnp.where(active_s[:, None], per_spread, 0.0)
            spread_total = jnp.sum(per_spread, axis=0)  # [N]
            spread_p = spread_total != 0.0

            # same popcount fusion as int mode (small counts are exact in
            # any float dtype, so the quotient is bit-identical to the
            # astype-chain form)
            presence = pack_presence_lanes(anti_present, pmask, aff_p, spread_p)
            num_terms = (1 + jlax.population_count(presence)).astype(fdt)
            final = (binpack + anti + resched + jnp.where(aff_p, aff, 0.0) + spread_total) / num_terms
            neg_inf = -jnp.inf
            score_zero = jnp.asarray(0.0, fdt)

        # -- ring-ordered limit + max-score selection (no permutation) -----
        # Ring prefix sums at natural index i: with S = natural inclusive
        # cumsum, T = total, o = offset, the ring-order cumsum is
        # S(i) - S(o-1) for i >= o and S(i) + (T - S(o-1)) for i < o —
        # elementwise, so the LimitIterator emulation needs no gathers.
        #
        # ONE packed int32 ring cumsum carries everything: the low-score
        # and feasible count planes ride 16-bit lanes of one int32 plane
        # (intscore.pack_count_lanes). Lane exactness: both totals are
        # bounded by n_pad < 2**15, so the low lane never carries into the
        # high lane, and every SELECTED ring branch is lane-wise
        # non-negative (i >= o selects S(i) - S(o-1) with [0..o-1] a
        # subset of [0..i]; i < o selects S(i) + the suffix sum — both
        # >= 0 per lane), so no borrow crosses lanes either. The skip
        # prefix is then min(low_cum, MAX_SKIP) (skipped = the first
        # MAX_SKIP low entries in ring order) and the source prefix is
        # feas_cum - skip_cum. (int64 field-packing would lift the 2**15
        # bound, but int64 prefix sums are pathologically slow on this
        # backend — int32 lanes are free.)
        valid = iota < n_real
        nr = jnp.maximum(n_real, 1)

        feas_v = feasible & valid
        # threshold 0 is exact in both modes (int: score60 <= 0 iff the
        # rational score <= 0; float: the host's 0.0 skip threshold)
        low = feas_v & (final <= 0)

        def ring_cumsum(a_int):
            s_nat = jnp.cumsum(a_int)
            total = s_nat[-1]
            before = jnp.sum(jnp.where(iota < offset, a_int, 0),
                             dtype=jnp.int32)
            ring = jnp.where(
                iota >= offset, s_nat - before, s_nat + (total - before)
            )
            return ring, total

        if n_pad < PACK_COUNT_MAX:
            packed_cum, packed_total = ring_cumsum(pack_count_lanes(low, feas_v))
            low_cum = unpack_count_lo(packed_cum)
            feas_cum = unpack_count_hi(packed_cum)
            low_total = unpack_count_lo(packed_total)
            feas_total = unpack_count_hi(packed_total)
        else:
            # lanes would overflow on a >32K-node pad: two plain cumsums
            low_cum, low_total = ring_cumsum(low.astype(jnp.int32))
            feas_cum, feas_total = ring_cumsum(feas_v.astype(jnp.int32))

        skipped = low & (low_cum <= MAX_SKIP)
        skip_cum = jnp.minimum(low_cum, MAX_SKIP)
        ret = feas_v & ~skipped
        ret_i = ret.astype(jnp.int32)
        ret_cum = feas_cum - skip_cum
        ret_excl = ret_cum - ret_i

        limit = limit_p
        pulled = valid & (ret_excl < limit)
        src_cand = ret & pulled
        ret_total = feas_total - jnp.minimum(low_total, MAX_SKIP)
        backlog_n = jnp.maximum(limit - ret_total, 0)
        skip_i = skipped.astype(jnp.int32)
        skip_excl = skip_cum - skip_i
        backlog_cand = skipped & (skip_excl < backlog_n)
        cand = src_cand | backlog_cand

        # ranks are unique across candidates (source ranks < ret_total <=
        # backlog ranks), so (max score, min rank) names one node exactly
        rank = jnp.where(src_cand, ret_excl, ret_total + skip_excl)

        cand_scores = jnp.where(cand, final, neg_inf)
        best_score = jnp.max(cand_scores)
        winners = cand & (cand_scores == best_score)
        winner_rank = jnp.where(winners, rank, jnp.int32(2**31 - 1))
        best_rank = jnp.min(winner_rank)
        any_cand = jnp.any(cand)
        chosen = jnp.where(
            any_cand & (~skip_step),
            jnp.argmax(winners & (rank == best_rank)).astype(jnp.int32),
            -1,
        )

        pulls = jnp.where(skip_step, 0, jnp.sum(pulled.astype(jnp.int32))).astype(jnp.int32)
        offset = jnp.where(skip_step, offset, (offset + pulls) % nr).astype(jnp.int32)

        # -- apply placement / revert eviction (one-hot adds) --------------
        success = chosen >= 0
        ch = jnp.maximum(chosen, 0)
        oh_ch = (iota == ch)
        oh_chf = oh_ch.astype(fdt)
        add_vec = jnp.where(success, ask, 0)
        used = used + oh_chf[:, None] * add_vec[None, :]
        inc_i = jnp.where(success, 1, 0)
        tg_counts = tg_counts + (sel_g[:, None] & oh_ch[None, :]) * inc_i
        job_counts = job_counts + oh_ch * inc_i

        ch_vid = jnp.sum(jnp.where(oh_ch[None, :], vids, 0), axis=1)  # [S]
        oh_ch_vid = (iota_v[None, :] == ch_vid[:, None])              # [S, V]
        inc = jnp.where(success & active_s, 1, 0).astype(fdt)
        spread_counts = spread_counts + jnp.where(
            sel_g[:, None, None], (oh_ch_vid.astype(fdt) * inc[:, None])[None, :, :], 0
        )
        entry_set = sel_g[:, None, None] & (oh_ch_vid & (inc > 0)[:, None])[None, :, :]
        spread_entry = spread_entry | entry_set

        # placement commits the chosen node's new exponential — EXACTLY the
        # already-computed selection value (running-product spec)
        if e_base.shape[0]:
            e_base = jnp.where((oh_ch & success)[:, None], e_sel_i32, e_base)
        if dp_vids.shape[0]:
            ch_vid_dp = jnp.sum(jnp.where(oh_ch[None, :], dp_vids, 0), axis=1)  # [D]
            inc_dp = dp_applies_g & success
            dp_counts = dp_counts + (
                (iota_v2[None, :] == ch_vid_dp[:, None]) & inc_dp[:, None]
            ).astype(jnp.int32)

        # -- commit the eviction set on the chosen node --------------------
        # Host ordering: preemption fires only when the node did NOT fit
        # outright. The greedy set is filtered by the reference's second
        # pass (distance vs the FRESH ask, descending) on the chosen
        # node's extracted [C] row — off the hot [N] axis.
        if has_pre:
            c_w = pre_res.shape[1]
            from .preempt import second_pass_jnp

            fits_ch = jnp.any(oh_ch & fits)
            use_pre = success & (~fits_ch) & (~skip_step)

            def row_c(arr):
                # arr[ch] without gather: one-hot sum over N (exactly one
                # non-zero term, so negative fills survive intact)
                shape = (n_pad,) + (1,) * (arr.ndim - 1)
                out = jnp.sum(jnp.where(oh_ch.reshape(shape), arr, 0), axis=0)
                return out.astype(arr.dtype)

            sel_ord_ch = row_c(sel_ord)                        # [C]
            res3_ch = row_c(pre_res3)                          # [C, 3] i64
            rem_ch = row_c(pre_remaining)                      # [3] i64
            keep, p_rank = second_pass_jnp(ask3, res3_ch, sel_ord_ch, rem_ch)
            keep = keep & use_pre                              # [C]

            # freed capacity credits `used` (the alloc itself stays
            # overcommitted for SCORING, matching the host's allocs_fit
            # used — the credit lands after the score terms above)
            res4_ch = row_c(pre_res)                           # [C, 4] i32
            freed4 = jnp.sum(
                jnp.where(keep[:, None], res4_ch.astype(fdt), 0), axis=0,
                dtype=fdt,
            )                                                  # [4]
            d_dims = totals.shape[1]
            if d_dims > 4:
                # batch padding may widen D past the gate's 4 dims; the
                # extra (device) dims free nothing
                freed_vec = jnp.concatenate(
                    [freed4, jnp.zeros(d_dims - 4, freed4.dtype)]
                )
            else:
                freed_vec = freed4[:d_dims]
            used = used - oh_chf[:, None] * freed_vec[None, :]

            # running Q27 exponential: multiply the just-committed chosen
            # row by each kept candidate's eviction factor (slot-ascending
            # product order is fixed, so the result is deterministic)
            if e_base.shape[0]:
                from .intscore import E27_BITS as _PB, E27_ONE as _PO

                eb_ch = row_c(e_base).astype(i64)              # [2]
                evf_ch = row_c(pre_evf)                        # [C, 2] i32
                for ci in range(c_w):
                    f = jnp.where(keep[ci], evf_ch[ci].astype(i64), i64(_PO))
                    eb_ch = (eb_ch * f) >> _PB
                e_base = jnp.where(
                    (oh_ch & use_pre)[:, None], eb_ch.astype(jnp.int32), e_base
                )

            evicted = oh_ch[:, None] & keep[None, :]           # [N, C]
            pre_alive = pre_alive & ~evicted
            freed3 = jnp.sum(jnp.where(keep[:, None], res3_ch, 0), axis=0)
            pre_remaining = pre_remaining + jnp.where(
                oh_ch[:, None], freed3[None, :], 0
            )
            gid_ch = row_c(pre_gid)                            # [C]
            pre_counts = pre_counts + jnp.sum(
                ((gid_ch[:, None] == iota_gp[None, :]) & keep[:, None])
                .astype(jnp.int32),
                axis=0,
                dtype=jnp.int32,
            )
            # output column: second-pass rank per evicted slot (ascending
            # rank = final eviction order), -1 for untouched slots
            evict_out = jnp.where(keep, p_rank, jnp.int32(-1))  # [C]
        else:
            evict_out = jnp.zeros((0,), jnp.int32)

        # failed placement: revert eviction, mark TG failed
        if has_evict:
            revert = do_evict & (~success)
            used = used + oh_ev_nodef[:, None] * jnp.where(revert, evict_res, 0)[None, :]
            rev_i = jnp.where(revert & (evict_tg >= 0), 1, 0)
            tg_counts = tg_counts + (sel_evg[:, None] & oh_ev_node[None, :]) * rev_i
            job_counts = job_counts + oh_ev_node * jnp.where(revert, 1, 0)
            spread_counts = spread_counts + jnp.where(
                sel_evg[:, None, None],
                (oh_ev_vid * jnp.where(revert, ev_dec, 0).astype(fdt)[:, None])[None, :, :],
                0,
            )
            if e_base.shape[0]:
                from .intscore import E27_BITS as _E27B, E27_ONE as _E27O

                rev_f = jnp.where(revert, rev_factor, _E27O).astype(i64)  # [2]
                eb_rev = (e_base.astype(i64) * rev_f[None, :]) >> _E27B
                e_base = jnp.where(
                    oh_ev_node[:, None], eb_rev, e_base.astype(i64)
                ).astype(jnp.int32)
        # forced-node (system) placements are independent per-node
        # decisions: a failure must NOT poison the TG for later nodes
        unforced = (forced_node[0] < 0) if forced_node.shape[-1] else True
        failed = failed | (sel_g & ((~success) & (~skip_step) & unforced))

        new_carry = (used, tg_counts, job_counts, spread_counts, spread_entry,
                     offset, failed, e_base, dp_counts,
                     pre_alive, pre_remaining, pre_counts)
        out = (chosen, jnp.where(success, best_score, score_zero), pulls,
               skip_step, evict_out)
        return new_carry, out

    return step


def _build_place_scan():
    import jax

    # x64 for the int64 score intermediates of the exact integer spec
    # (intscore.py). Parity mode carries int32 arrays and compares int64
    # score60s — bit-identical on every backend, including the real TPU.
    jax.config.update("jax_enable_x64", True)
    _enable_persistent_compile_cache()
    step = _make_step()

    @partial(jax.jit, static_argnames=("n_pad",))
    def place_scan(n_pad, static, init_carry, xs):
        import jax.lax as lax

        return lax.scan(lambda c, x: step(static, c, x), init_carry, xs)

    return place_scan


def _build_forced_kernel():
    """Scan-free system-eval kernel: when every placement names a DISTINCT
    forced node (single-TG system jobs — one alloc per eligible node,
    system_sched.go:268-286) and the eval carries no evictions, spreads,
    affinities, reschedule penalties or distinct_property (the system
    encoder emits exactly this shape), the scan steps are independent
    given the initial carry: no step's placement touches another step's
    node, spread counts are inert, and the ring offset cannot change any
    output (each step has at most ONE candidate — selected whether it
    lands in the source or the backlog window). So the whole eval
    collapses to ONE vectorized pass over the placement axis — identical
    arithmetic to the scan step restricted to that shape, bit-identical
    outputs (asserted by tests/test_system_engine.py host-parity and the
    scan-equivalence fuzz), at O(1) dispatch instead of O(P) sequential
    steps."""
    import jax

    jax.config.update("jax_enable_x64", True)
    _enable_persistent_compile_cache()
    import jax.numpy as jnp

    from .intscore import FEAT_FEAS_BIT, unpack_feat_lane

    def forced_eval(static, carry, xs):
        (totals, reserved, asks, feat_packed, _aff_score,
         desired_counts, dh_job, dh_tg, _limits, _spread_vids,
         _spread_desired, _spread_weights, _spread_has_targets,
         _spread_active, _sum_spread_weights, n_real, e_ask,
         _dp_vids, _dp_limit, _dp_applies,
         _pre_res, _pre_prio, _pre_elig, _pre_mp, _pre_gid,
         _pre_evf) = static
        (used0, tg_counts0, job_counts0, _sc0, _se0, _off0, failed0,
         e_base0, _dpc0, _pre_alive0, _pre_rem0, _pre_counts0) = carry
        (tg_idx, _penalty_idx, _evict_node, _evict_res, _evict_tg,
         _limit_p, _sum_sw_p, _ev_factor, _rev_factor, forced_node) = xs

        fdt = totals.dtype
        int_mode = jnp.issubdtype(fdt, jnp.integer)
        i64 = jnp.int64
        j = forced_node[:, 0]                          # [P] node per step
        g = tg_idx                                     # [P] TG per step

        ask = asks[g]                                  # [P, D]
        used_j = used0[j]                              # [P, D]
        totals_j = totals[j]
        if reserved.shape[0]:
            util = used_j + reserved[j] + ask
        else:
            util = used_j + ask
        fits = jnp.all(util <= totals_j, axis=-1)

        jc = job_counts0[j]                            # [P]
        tgc = tg_counts0[g, j]                         # [P]
        dh_mask = jnp.where(
            dh_job[g],
            jc == 0,
            jnp.where(dh_tg[g], ~((tgc > 0) & (jc > 0)), True),
        )
        feasible = (
            unpack_feat_lane(feat_packed[g, j], FEAT_FEAS_BIT)
            & fits & dh_mask & (j >= 0) & (j < n_real)
            & ~failed0[g]
        )

        anti_present = tgc > 0
        if int_mode:
            from .intscore import E27_BITS, E27_ONE, RECIP_BITS, TERM_BITS

            e_sel = (e_base0[j].astype(i64) * e_ask[g, j].astype(i64)) \
                >> E27_BITS                            # [P, 2]
            fit = i64(20 * E27_ONE) - e_sel[:, 0] - e_sel[:, 1]
            fit = jnp.clip(fit, 0, 18 * E27_ONE)
            binpack = (fit * 4) // 9
            rsh = RECIP_BITS - TERM_BITS
            q_d = jnp.floor_divide(
                i64(1 << RECIP_BITS),
                jnp.maximum(desired_counts[g].astype(i64), 1),
            )
            anti = jnp.where(
                anti_present, -(((tgc.astype(i64) + 1) * q_d) >> rsh), 0
            )
            num_terms = 1 + anti_present.astype(jnp.int32)
            factor = jnp.floor_divide(60, num_terms).astype(i64)
            final = (binpack + anti) * factor
            score_zero = i64(0)
        else:
            node_cpu = totals_j[:, DIM_CPU] - reserved[j][:, DIM_CPU]
            node_mem = totals_j[:, DIM_MEM] - reserved[j][:, DIM_MEM]
            free_cpu = 1.0 - util[:, DIM_CPU] / jnp.maximum(node_cpu, 1e-9)
            free_mem = 1.0 - util[:, DIM_MEM] / jnp.maximum(node_mem, 1e-9)
            fitness = 20.0 - (jnp.power(10.0, free_cpu)
                              + jnp.power(10.0, free_mem))
            binpack = jnp.clip(fitness, 0.0, 18.0) / 18.0
            anti = jnp.where(
                anti_present,
                -(tgc.astype(fdt) + 1.0) / desired_counts[g].astype(fdt),
                0.0,
            )
            num_terms = 1.0 + anti_present.astype(fdt)
            final = (binpack + anti) / num_terms
            score_zero = jnp.asarray(0.0, fdt)

        chosen = jnp.where(feasible, j, -1).astype(jnp.int32)
        scores = jnp.where(feasible, final, score_zero)
        p = tg_idx.shape[0]
        # the forced fast path never encodes preemption -> empty column
        return (chosen, scores, jnp.zeros(p, jnp.int32),
                jnp.zeros(p, bool), jnp.zeros((p, 0), jnp.int32))

    return jax.jit(forced_eval)


def _build_batched_scan(in_shardings=None):
    """Eval-batched scan: vmap the per-eval scan over a leading batch axis.

    EVERYTHING is batched — node tables included — because concurrent evals
    see different snapshots, different datacenter-filtered node sets and
    different jobs. Each eval keeps the exact sequential parity semantics of
    the single scan; the batch axis is pure data parallelism over
    independent evaluations (the device analog of the reference's
    N-scheduler-workers-per-server, nomad/server.go:1307).

    ``in_shardings``: optional (static, carry, xs) NamedSharding tuples
    (parallel.sharding.batched_scan_shardings) to shard the dispatch over
    an ("evals", "nodes") mesh — the ONE builder both the unsharded and
    mesh production paths share."""
    import jax

    jax.config.update("jax_enable_x64", True)
    _enable_persistent_compile_cache()
    step = _make_step()

    def body(static_b, carry_b, xs_b):
        import jax.lax as lax

        def one(static, carry, xs):
            return lax.scan(lambda c, x: step(static, c, x), carry, xs)

        return jax.vmap(one)(static_b, carry_b, xs_b)

    if in_shardings is not None:
        return jax.jit(body, in_shardings=in_shardings)
    return jax.jit(body)


class _ResourceAssigner:
    """Host-side port and device-instance assignment for scan-chosen
    placements — the discrete half of the capacity dims the device
    pre-checked. NetworkIndex/DeviceAllocator mirrors are built lazily
    per node: network- and device-free task groups (the C1M-common case)
    never pay the per-node alloc walk."""

    def __init__(self, ctx, nodes) -> None:
        self.ctx = ctx
        self.nodes = nodes
        self._net: Dict[int, NetworkIndex] = {}
        self._dev: Dict[int, object] = {}

    def net_index(self, idx: int) -> NetworkIndex:
        ni = self._net.get(idx)
        if ni is None:
            ni = NetworkIndex(deterministic=self.ctx.deterministic)
            ni.set_node(self.nodes[idx])
            ni.add_allocs(self.ctx.proposed_allocs(self.nodes[idx].id))
            self._net[idx] = ni
        return ni

    def dev_allocator(self, idx: int):
        da = self._dev.get(idx)
        if da is None:
            from ..scheduler.device import DeviceAllocator

            da = DeviceAllocator(self.ctx, self.nodes[idx])
            da.add_allocs(self.ctx.proposed_allocs(self.nodes[idx].id))
            self._dev[idx] = da
        return da

    def build(self, node_idx: int, tg):
        """(task_resources, shared_networks, ok) for placing ``tg`` on the
        node; ok=False on a port/device-instance collision the dense
        capacity model missed (rare — the plan applier would reject it)."""
        task_resources: Dict[str, AllocatedTaskResources] = {}
        shared_networks = []
        ok = True
        if tg.networks:
            ni = self.net_index(node_idx)
            offer, _err = ni.assign_network(tg.networks[0].copy())
            if offer is None:
                ok = False
            else:
                ni.add_reserved(offer)
                shared_networks = [offer]
        for task in tg.tasks:
            tr = AllocatedTaskResources(
                cpu_shares=task.resources.cpu, memory_mb=task.resources.memory_mb
            )
            if task.resources.networks:
                ni = self.net_index(node_idx)
                offer, _err = ni.assign_network(task.resources.networks[0].copy())
                if offer is None:
                    ok = False
                    break
                ni.add_reserved(offer)
                tr.networks = [offer]
            for req in task.resources.devices:
                da = self.dev_allocator(node_idx)
                offer, _aff, _err = da.assign_device(req)
                if offer is None:
                    ok = False
                    break
                da.add_reserved(offer)
                tr.devices.append(offer)
            if not ok:
                break
            task_resources[task.name] = tr
        return task_resources, shared_networks, ok


def _int_spec_gate_reason(table, tg_specs, job):
    """Magnitude gates keeping every int64 intermediate of the integer
    scoring spec exact (intscore.py module doc). None = all clear."""
    from .intscore import MAX_TOTAL_COUNT

    caps = table.totals[:, :2]
    node_c = caps - table.reserved[:, :2]
    if caps.size and (
        caps.max() > (1 << 24)
        or node_c.min() < 1
        or (table.reserved[:, :2] > 2 * node_c).any()
    ):
        return "int-spec cpu/mem magnitude gate"
    if table.totals.size and table.totals.max() > (1 << 28):
        return "int-spec capacity magnitude gate"
    if sum(g.count for g in job.task_groups) > MAX_TOTAL_COUNT:
        return "int-spec job count gate"
    if any(spec.ask.max(initial=0) > (1 << 28) for spec in tg_specs.values()):
        return "int-spec ask magnitude gate"
    return None


def _release_enc_claim(claim_cell: Dict[str, object]) -> None:
    """Release an owned single-flight encode claim: drop the claim Event
    from the enc_cache (if it is still the parked entry) and wake every
    waiter so one of them can re-claim. Idempotent — the success path
    pops "ev" when it publishes, making later calls no-ops."""
    ev = claim_cell.pop("ev", None)
    if ev is None:
        return
    cache = claim_cell.pop("cache", None)
    key = claim_cell.pop("key", None)
    if cache is not None and cache.get(key) is ev:
        cache.pop(key, None)
    ev.set()


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class TpuPlacementEngine:
    _shared: Optional["TpuPlacementEngine"] = None
    _atexit_registered = False

    def __init__(self) -> None:
        self._place_scan = None
        self._forced_kernel = None
        # chunked throughput tier: compiled chunk scans keyed by chunk
        # size, plus the sampled-parity divergence tally every bench /
        # server artifact reads (parity_sample_stats)
        self._chunk_scans: Dict[int, object] = {}
        import threading as _threading

        self._parity_lock = witness_lock("engine.TpuPlacementEngine._parity_lock")
        self._parity_samples = {
            "evals_sampled": 0,
            "placements_checked": 0,
            "placements_diverged": 0,
        }

    @classmethod
    def shared(cls) -> "TpuPlacementEngine":
        if cls._shared is None:
            cls._shared = TpuPlacementEngine()
            if not cls._atexit_registered:
                # deterministic teardown: interpreter exit with a
                # dispatcher or warm-compile thread still inside the
                # runtime segfaults (the multichip dryrun's rc 139);
                # atexit runs BEFORE daemon threads are killed
                import atexit

                atexit.register(cls.shutdown)
                cls._atexit_registered = True
        return cls._shared

    @classmethod
    def shutdown(cls) -> None:
        """Stop every live DeviceBatcher (dispatcher thread joined, warm
        compiles joined, parked workers released) and drop the shared
        engine's compiled-callable references. Idempotent; registered
        via atexit by shared() and callable explicitly by benches/tests
        that want the TPU stack quiesced inside their own lifetime."""
        from .batcher import shutdown_all

        shutdown_all()
        eng = cls._shared
        if eng is not None:
            eng._place_scan = None
            eng._forced_kernel = None
            eng._chunk_scans.clear()

    def _scan_fn(self):
        if self._place_scan is None:
            self._place_scan = _build_place_scan()  # race-ok: idempotent compile cache; duplicate builds are equal, ref swap atomic
        return self._place_scan

    def _forced_fn(self):
        if self._forced_kernel is None:
            self._forced_kernel = _build_forced_kernel()  # race-ok: idempotent compile cache; duplicate builds are equal, ref swap atomic
        return self._forced_kernel

    def run_forced(self, enc: "EncodedEval"):
        """Run one all-distinct forced-node eval through the scan-free
        kernel (see _build_forced_kernel). The placement axis pads to a
        pow2 bucket so partial retries (plan-rejection re-evals with
        fewer placements) reuse the compiled executable: padded entries
        carry forced_node=-1, which the kernel maps to chosen=-1, and
        callers only read the first ``enc.p`` slots."""
        kernel = self._forced_fn()
        import jax.numpy as jnp

        from ..utils import phases as _phases

        p = enc.p
        p_pad = _round_up(max(p, 1))
        xs = enc.xs
        if p_pad != p:
            def padp(arr, fill):
                widths = ((0, p_pad - p),) + ((0, 0),) * (arr.ndim - 1)
                return np.pad(arr, widths, constant_values=fill)

            (tg_idx, penalty_idx, evict_node, evict_res, evict_tg,
             limit_p, sum_sw_p, ev_factor, rev_factor, forced_node) = xs
            xs = (
                padp(tg_idx, 0), padp(penalty_idx, -1),
                padp(evict_node, -1), padp(evict_res, 0),
                padp(evict_tg, -1), padp(limit_p, 0), padp(sum_sw_p, 0),
                padp(ev_factor, 0), padp(rev_factor, 0),
                padp(forced_node, -1),
            )
        static = tuple(jnp.asarray(a) for a in enc.static)
        init_carry = tuple(jnp.asarray(a) for a in enc.carry)
        xs = tuple(jnp.asarray(a) for a in xs)
        with _phases.track("device"):
            chosen, scores, pulls, skipped, evict = kernel(static, init_carry, xs)
            chosen = np.asarray(chosen)
        return (
            chosen[:p], np.asarray(scores)[:p],
            np.asarray(pulls)[:p], np.asarray(skipped)[:p],
            np.asarray(evict)[:p],
        )

    # -- chunked throughput tier ---------------------------------------

    @staticmethod
    def _chunk_eligible(enc: "EncodedEval") -> Optional[str]:
        """None when the encode may run on the chunked top-K tier; else
        the reason it must take the bit-parity scan. The chunk step
        models fresh, non-destructive, float-mode placements only — in
        particular it has NO eviction scoring, so preempting evals (the
        deficit-carry / preemption interaction) are hard-gated here and
        re-asserted at dispatch (batcher.assert_chunk_gate)."""
        if np.dtype(enc.dtype).kind != "f":
            return "int mode"  # deterministic encodes carry score60s
        if not enc.dense_ok:
            return "not dense"
        if enc.pre_allocs is not None:
            return "preemption tables"
        if enc.static[1].shape[0] != enc.n_pad:
            return "folded reserved"  # chunk util needs full-height reserved
        if enc.xs[1].shape[1] > 0 and bool((np.asarray(enc.xs[1]) >= 0).any()):
            return "reschedule penalties"
        if bool((np.asarray(enc.xs[2]) >= 0).any()):
            return "eviction axis"
        if enc.xs[9].ndim == 2 and enc.xs[9].shape[1] > 0:
            return "forced nodes"
        return None

    def _chunk_fn(self, chunk: int):
        fn = self._chunk_scans.get(chunk)
        if fn is None:
            fn = _build_chunk_scan(chunk)
            self._chunk_scans[chunk] = fn  # race-ok: idempotent compile cache; duplicate builds are equal, ref swap atomic
        return fn

    def run_chunked(self, enc: "EncodedEval", chunk_k: int = 128,
                    retry_rounds: int = 2):
        """Run one chunk-eligible eval through the top-K throughput scan
        and expand the per-chunk outputs back to per-placement arrays of
        the parity scan's result shape (chosen, scores, pulls, skipped,
        evict) so both tiers share the apply path.

        Placements of one task group are interchangeable here — the
        eligibility gate rejects every per-row feature (penalties,
        evictions, forced nodes) — so each TG's rows fill in order from
        its chunks' valid picks; rows left unfilled after the retry
        rounds come back as chosen = -1 (recorded as failed placements,
        never silently dropped).
        """
        from .batcher import assert_chunk_gate

        assert_chunk_gate(enc)
        import jax.numpy as jnp

        from ..utils import phases as _phases

        tg_idx_p = np.asarray(enc.xs[0])[: enc.p]
        counts: Dict[int, int] = {}
        for gi in tg_idx_p.tolist():
            counts[int(gi)] = counts.get(int(gi), 0) + 1
        counts_by_tg = list(counts.items())
        chunk = int(max(1, min(chunk_k, enc.n_pad)))
        steps_tg, want = chunk_schedule(counts_by_tg, chunk,
                                        retry_rounds=retry_rounds)
        fn = self._chunk_fn(chunk)
        static = tuple(jnp.asarray(a) for a in enc.static)
        carry = tuple(jnp.asarray(a) for a in enc.carry)
        xs = (jnp.asarray(steps_tg), jnp.asarray(want))
        with _phases.track("device"):
            _carry, _deficit, (top_idx, top_scores, valid, _placed) = fn(
                enc.n_pad, static, carry, xs)
            top_idx = np.asarray(top_idx)
        top_scores = np.asarray(top_scores)
        valid = np.asarray(valid)

        # per-TG FIFO of the picked (node, score) pairs, chunk order
        picked: Dict[int, list] = {gi: [] for gi, _ in counts_by_tg}
        for si in range(steps_tg.shape[0]):
            vs = np.nonzero(valid[si])[0]
            if vs.size:
                picked[int(steps_tg[si])].append(
                    (top_idx[si, vs], top_scores[si, vs]))
        p = enc.p
        chosen = np.full(p, -1, np.int32)
        scores = np.zeros(p, np.float32)
        heads = {gi: 0 for gi in picked}
        queues = {
            gi: (
                np.concatenate([n for n, _ in lst])
                if lst else np.empty(0, np.int32),
                np.concatenate([s for _, s in lst])
                if lst else np.empty(0, np.float32),
            )
            for gi, lst in picked.items()
        }
        for pi in range(p):
            gi = int(tg_idx_p[pi])
            nodes_q, scores_q = queues[gi]
            h = heads[gi]
            if h < nodes_q.shape[0]:
                chosen[pi] = nodes_q[h]
                scores[pi] = scores_q[h]
                heads[gi] = h + 1
        # every chunk scores the full real node axis — report it, unlike
        # the ring-limited parity scan's per-placement pull counts
        pulls = np.full(p, int(enc.n_real), np.int32)
        skipped = np.zeros(p, bool)
        evict = np.zeros((p, 0), np.int32)
        return chosen, scores, pulls, skipped, evict

    def _maybe_sample_parity(self, enc: "EncodedEval", chosen,
                             rate: float) -> None:
        """Sampled-parity spot check for the chunked tier: with
        probability ``rate`` re-run the eval through the bit-parity scan
        and tally per-TG multiset divergence of the chosen nodes. The
        chunked tier is NOT bit-identical by design; this bounds the
        drift and surfaces regressions in every bench/server artifact
        (parity_sample_stats)."""
        from ..utils import metrics as _metrics

        if rate <= 0.0 or _PARITY_SAMPLE_RNG.random() >= rate:
            return
        try:
            ref_chosen = np.asarray(self.run_scan_single(enc)[0])[: enc.p]
        except Exception:  # noqa: BLE001 — a failed spot check never
            # fails the eval; the chunked plan already applied
            logger.exception("sampled-parity reference scan failed")
            return
        got = np.asarray(chosen)[: enc.p]
        tg_idx = np.asarray(enc.xs[0])[: enc.p]
        from collections import Counter

        diverged = 0
        for gi in np.unique(tg_idx):
            sel = tg_idx == gi
            diverged += sum(
                (Counter(got[sel].tolist())
                 - Counter(ref_chosen[sel].tolist())).values()
            )
        with self._parity_lock:
            self._parity_samples["evals_sampled"] += 1
            self._parity_samples["placements_checked"] += int(enc.p)
            self._parity_samples["placements_diverged"] += int(diverged)
        _metrics.incr_counter("nomad.tpu_engine.parity_sampled")
        if diverged:
            _metrics.incr_counter("nomad.tpu_engine.parity_diverged",
                                  float(diverged))

    def parity_sample_stats(self) -> Dict[str, float]:
        """Snapshot of the chunked tier's sampled-parity tally, with the
        derived divergence rate. Recorded into every bench artifact that
        exercises the chunked tier."""
        with self._parity_lock:
            out = dict(self._parity_samples)
        checked = out["placements_checked"]
        out["divergence_rate"] = (
            out["placements_diverged"] / checked if checked else 0.0
        )
        return out

    def reset_parity_samples(self) -> None:
        with self._parity_lock:
            for k in self._parity_samples:
                self._parity_samples[k] = 0

    # ------------------------------------------------------------------

    def select(self, sched, tg, select_options):
        """Single-select path: not used — batching happens at
        compute_placements; always defer to the host stack."""
        return NotImplemented

    def compute_placements(self, sched, destructive: List, place: List):
        """Batch the eval's whole placement list through one device scan.

        Returns True when handled; NotImplemented to fall back to the host
        iterator path (unsupported features). When the scheduler's planner
        carries a ``device_batcher`` (the production server does —
        server.go:1307's N-workers analog), the encoded eval is submitted
        there so concurrent evals share ONE eval-batched device dispatch;
        otherwise it runs as a single-eval scan.
        """
        from ..utils import metrics as _metrics

        # Small evals don't amortize a device dispatch (~100ms+ on a
        # tunneled chip): the host stack places them in low milliseconds,
        # exactly like the reference's per-placement iterators
        # (generic_sched.go:426). Threshold 0 = always use the device
        # (the parity harness's frame); the production server sets it.
        n_min = getattr(sched, "device_min_placements", 0)
        if n_min and len(destructive) + len(place) < n_min:
            # Warm-bucket retry ride-along: a partial OCC retry (the tail
            # of a plan-rejected eval) is usually a few placements of a
            # job shape whose compile bucket is ALREADY warm from the
            # first pass — padding it into that bucket costs nothing,
            # while the host fallback re-walks the ranking iterators per
            # placement. Only reroute when the batcher has completed at
            # least one batch (so buckets exist) and the retry isn't
            # trivially small.
            batcher = getattr(sched.planner, "device_batcher", None)
            total = len(destructive) + len(place)
            if (
                batcher is None
                or total < RETRY_DEVICE_FLOOR
                or not batcher.has_warmed()
            ):
                _metrics.incr_counter("nomad.tpu_engine.small_eval_host")
                return NotImplemented
            _metrics.incr_counter("nomad.tpu_engine.small_eval_device_retry")

        from ..trace import lifecycle as _tlc
        from ..utils import phases as _phases

        wave_id = sched.eval.id
        batcher = getattr(sched.planner, "device_batcher", None)
        # Demand announcement: tell the batcher an encode destined for it
        # is in flight BEFORE the encode starts, so the gather window
        # stays open for this eval's cohort instead of closing on an
        # arrival gap (the r05 wave-fragmentation bug: 328 evals over 21
        # dispatches against a 64 cap). Balanced in the finally / by
        # run(expected=True) on every path out of this function.
        expected_held = False
        if batcher is not None:
            batcher.expect()
            expected_held = True
        try:
            t0 = _metrics.now()
            with _HOST_WORK_SEM:
                t1 = _metrics.now()
                with _phases.track("encode"), _tlc.pipeline_stage("encode", wave_id):
                    enc = self.encode_eval(sched, destructive, place)
                _metrics.measure_since("nomad.tpu_engine.encode_work", t1)
            _metrics.measure_since("nomad.tpu_engine.encode", t0)
            if enc is NotImplemented:
                return NotImplemented
            if enc is True:
                return True
            self._pipeline_remember(sched, enc)
            t0 = _metrics.now()
            # tpu_binpack_chunked: chunk-eligible evals take the top-K
            # throughput scan; everything else — preempting, destructive,
            # int-mode, penalized — falls back to the bit-parity dispatch
            # below exactly as under tpu_binpack
            use_chunked = False
            if getattr(sched, "chunked_tier", False):
                chunk_reason = self._chunk_eligible(enc)
                use_chunked = chunk_reason is None
                if not use_chunked:
                    _metrics.incr_counter("nomad.tpu_engine.chunk_fallback")
                    logger.debug("chunked tier ineligible (%s): %s",
                                 wave_id[:8], chunk_reason)
            if use_chunked and expected_held:
                # withdraw BEFORE the long chunked scan: a phantom
                # expectation would hold concurrent gathers open for it
                batcher.cancel_expected()
                expected_held = False
            try:
                with _phases.track("device_wait"), \
                        _tlc.pipeline_stage("dispatch", wave_id):
                    if use_chunked:
                        chosen, scores, pulls, skipped_steps, evict = self.run_chunked(
                            enc, chunk_k=int(getattr(sched, "chunk_k", 128)))
                    elif batcher is not None:
                        expected_held = False  # run() consumes the token
                        chosen, scores, pulls, skipped_steps, evict = batcher.run(
                            enc, expected=True)
                    else:
                        chosen, scores, pulls, skipped_steps, evict = self.run_scan_single(enc)
            except Exception:  # noqa: BLE001 — device dispatch failed
                # A failed/poisoned device round trip must not fail the eval:
                # the host iterator stack computes the identical placements
                # (bit-parity contract), so degrade this eval to the host
                # path and let the caller's fall-through handle it.
                logger.warning("device dispatch failed for %s; host fallback",
                               wave_id[:8], exc_info=True)
                _metrics.incr_counter("nomad.tpu_engine.dispatch_fallback_host")
                self._pipeline_forget(sched)
                return NotImplemented
        finally:
            if expected_held:
                batcher.cancel_expected()
        _metrics.measure_since("nomad.tpu_engine.device_wait", t0)
        if use_chunked:
            _metrics.incr_counter("nomad.tpu_engine.chunk_dispatch")
            self._maybe_sample_parity(
                enc, chosen,
                float(getattr(sched, "parity_sample_rate", 0.0)),
            )
        t0 = _metrics.now()
        with _HOST_WORK_SEM:
            t1 = _metrics.now()
            with _phases.track("apply"):
                chosen = np.asarray(chosen)
                skipped_steps = np.asarray(skipped_steps)
                evict = np.asarray(evict)
                if enc.dense_ok and (chosen >= 0).all() and not skipped_steps.any():
                    # every placement succeeded and qualifies: results stay
                    # dense (no per-alloc objects) all the way to the FSM
                    self._apply_results_dense(sched, enc, chosen, scores, pulls,
                                              evict)
                else:
                    self._apply_results(
                        sched, enc.missing_list, enc.nodes, enc.table, chosen,
                        scores, pulls, skipped_steps, enc.start_ns,
                        enc=enc, evict=evict,
                    )
            _metrics.measure_since("nomad.tpu_engine.apply_work", t1)
        _metrics.measure_since("nomad.tpu_engine.apply", t0)
        return True

    def encode_eval(self, sched, destructive: List, place: List):
        """Encode one eval's placement problem into dense numpy arrays.

        Returns an EncodedEval, True (nothing to place) or NotImplemented
        (unsupported feature — host fallback).

        try/finally wrapper: the impl may claim a single-flight encode
        slot (an Event parked in the fleet's enc_cache). Success and the
        UnsupportedByEngine fallbacks release it themselves, but an
        UNEXPECTED exception must too — an abandoned claim stalls every
        same-key eval for the full 10s waiter grace period, each holding
        a HOST_WORK_SEM slot while it waits."""
        claim_cell: Dict[str, object] = {}
        try:
            return self._encode_eval_impl(sched, destructive, place, claim_cell)
        finally:
            # no-op when the claim was already published or released
            _release_enc_claim(claim_cell)

    def _encode_eval_impl(self, sched, destructive: List, place: List,
                          claim_cell: Dict[str, object]):
        try:
            import jax  # noqa: F401 — device path requires jax
        except ImportError:
            return NotImplemented

        job = sched.job
        ctx = sched.ctx
        nodes = list(sched.stack.source.nodes)  # order set by stack.set_nodes
        n_real = len(nodes)

        missing_list = list(destructive) + list(place)
        if not missing_list:
            return True

        from ..utils import metrics as _metrics

        # single-flight claim state (see the enc_cache block below): any
        # exit path that abandons an owned claim must release it, or
        # same-key waiters stall out their grace period — encode_eval's
        # finally covers the unexpected-exception paths

        def fallback(reason: str):
            logger.debug("tpu engine fallback: %s", reason)
            _metrics.incr_counter("nomad.tpu_engine.fallback")
            _release_enc_claim(claim_cell)
            return NotImplemented

        # Sticky-disk preferred nodes use a different two-phase select; punt.
        # Simultaneously decide dense-path eligibility: every placement
        # fresh (no previous alloc), no canaries, and its TG free of
        # network/device asks — then results stay as arrays through plan
        # submit -> plan apply -> FSM (structs.DenseTGPlacements).
        dense_ok = not sched.eval.annotate_plan
        _dense_tg_cache: Dict[str, bool] = {}
        for missing in missing_list:
            prev = missing.get_previous_allocation()
            tg = missing.get_task_group()
            if prev is not None and tg.ephemeral_disk.sticky:
                return fallback("sticky ephemeral disk")
            if dense_ok:
                if prev is not None or missing.is_canary():
                    dense_ok = False
                    continue
                tg_ok = _dense_tg_cache.get(tg.name)
                if tg_ok is None:
                    tg_ok = not tg.networks and not any(
                        t.resources.networks or t.resources.devices
                        for t in tg.tasks
                    )
                    _dense_tg_cache[tg.name] = tg_ok
                dense_ok = tg_ok

        # Build TG specs (may refuse). The per-node NetworkIndex cache is
        # shared across this eval's TGs (port-feasibility masks); the
        # fleet-static cache (encode.fleet_static) shares totals/index/
        # class-group arrays across every eval between node writes.
        from .encode import fleet_static, job_sched_signature

        fleet = fleet_static(ctx, job, nodes)

        # Device-side preemption (tpu/preempt.py): does this eval's host
        # oracle preempt? Config-gated per job type — the SAME switch the
        # host stack consults (generic_sched.get_select_options), so the
        # two paths can never disagree on whether the eval may evict.
        from ..scheduler.preemption import preemption_enabled

        _, _sched_cfg = ctx.state.scheduler_config()
        preempt_on = preemption_enabled(_sched_cfg, job.type)

        # Whole-eval encode cache (VERDICT r4 #1/#4): a burst of
        # same-shaped fresh jobs (the C1M workload — hundreds of
        # identical service jobs) re-derives identical arrays per eval,
        # and that re-derivation is the dominant GIL-serialized phase.
        # When every per-eval input is provably default — all placements
        # fresh (dense_ok), empty plan, clean shared spread/limit state,
        # no existing allocs of this job — the encoded arrays depend
        # only on (job content, fleet, usage state); reuse them
        # wholesale, swapping the per-eval ring offset and host context.
        # Extends the reference's per-class eligibility memoization
        # (scheduler/context.go:191) to the whole encoding.
        enc_cache = None
        cache_key = None
        if fleet is not None and dense_ok and not destructive and not preempt_on:
            plan = ctx.plan
            spread_state = sched.stack.spread
            if (
                not plan.node_allocation and not plan.node_update
                and not plan.node_preemptions
                and not spread_state.tg_spread_info
                and float(spread_state.sum_spread_weights) == 0.0
                and not ctx.state.job_has_live_allocs(job.id)
            ):
                enc_cache = fleet.setdefault("enc_cache", {})
                # NOTE: the usage epoch is NOT part of the key — entries
                # store (epoch, enc), and a stale-epoch hit is PATCHED
                # in place of a full re-encode: for jobs satisfying the
                # preconditions above, the only epoch-dependent arrays
                # are the job-independent used0/e_base0 pair
                # (encode.epoch_usage_arrays). Without this, every
                # commit wave of a C1M ingest invalidated the whole
                # cache and the re-encode storm became the dominant
                # host phase.
                cache_key = (
                    job_sched_signature(job),
                    len(missing_list),
                    tuple(m.get_task_group().name for m in missing_list),
                )
                cur_epoch = getattr(ctx.state, "usage_epoch", -1)
                # SINGLE-FLIGHT: a same-key burst (the C1M registration
                # storm — hundreds of evals of identically-shaped jobs
                # dequeued at one snapshot) must not thundering-herd the
                # encode. The first encoder claims the key with an Event
                # and builds; the rest wait for its published arrays
                # instead of re-deriving them concurrently (which made
                # the cache 0%-hit exactly when it mattered most).
                import threading as _threading

                while True:
                    hit = enc_cache.get(cache_key)
                    if hit is None:
                        claim = _threading.Event()
                        cur = enc_cache.setdefault(cache_key, claim)
                        if cur is claim:
                            claim_cell["ev"] = claim
                            claim_cell["cache"] = enc_cache
                            claim_cell["key"] = cache_key
                            break  # we build and publish
                        hit = cur
                    if isinstance(hit, _threading.Event):
                        _metrics.incr_counter(
                            "nomad.tpu_engine.encode_cache_wait")
                        if not hit.wait(timeout=10.0):
                            # owner wedged or died mid-encode: clear the
                            # stuck claim so the key heals, build our own.
                            # Wake the REST of the waiter cohort too —
                            # they re-read the cache now (and one
                            # re-claims) instead of each burning its own
                            # full grace period on the dead Event.
                            if enc_cache.get(cache_key) is hit:
                                enc_cache.pop(cache_key, None)
                            hit.set()
                            break
                        continue  # re-read the published entry
                    hit_epoch, hit = hit
                    num_dims = hit.static[0].shape[1]
                    if hit_epoch != cur_epoch:
                        if num_dims != 4:
                            # device-dim jobs carry usage on job-shaped
                            # dims; no shared patch — full re-encode
                            break
                        from .encode import epoch_usage_arrays

                        used0, e_base0 = epoch_usage_arrays(
                            ctx, fleet, hit.n_pad,
                            hit.dtype == np.int32, hit.dtype,
                        )
                        carry = list(hit.carry)
                        carry[0] = used0
                        carry[7] = e_base0
                        hit = EncodedEval(
                            n_real=hit.n_real, n_pad=hit.n_pad, g=hit.g,
                            s=hit.s, v=hit.v, p=hit.p, dtype=hit.dtype,
                            static=hit.static, carry=tuple(carry),
                            xs=hit.xs, missing_list=hit.missing_list,
                            nodes=hit.nodes, table=hit.table,
                            start_ns=hit.start_ns, dense_ok=True,
                        )
                        # re-publish at the current epoch: the rest of
                        # this wave's evals hit the pure-clone path
                        enc_cache[cache_key] = (cur_epoch, hit)
                        _metrics.incr_counter(
                            "nomad.tpu_engine.encode_cache_patch")
                    else:
                        _metrics.incr_counter(
                            "nomad.tpu_engine.encode_cache_hit")
                    _metrics.incr_counter("nomad.tpu_engine.handled")
                    offset0 = (
                        int(getattr(sched.stack.source, "offset", 0))
                        % max(n_real, 1)
                    )
                    carry = list(hit.carry)
                    carry[5] = np.int32(offset0)
                    return EncodedEval(
                        n_real=hit.n_real, n_pad=hit.n_pad, g=hit.g,
                        s=hit.s, v=hit.v, p=hit.p, dtype=hit.dtype,
                        static=hit.static, carry=tuple(carry), xs=hit.xs,
                        missing_list=missing_list, nodes=nodes,
                        table=hit.table, start_ns=_time.monotonic_ns(),
                        dense_ok=True,
                    )

        # The capacity model tracks one aggregate bandwidth dimension; the
        # host checks per NIC. Gate multi-NIC nodes to keep parity.
        for node in nodes:
            if len({net.device for net in node.node_resources.networks if net.device}) > 1:
                return fallback("multi-NIC node")
        tg_specs: Dict[str, TGSpec] = {}
        port_cache: Dict[str, object] = {}
        try:
            for missing in missing_list:
                tg = missing.get_task_group()
                if tg.name not in tg_specs:
                    tg_specs[tg.name] = build_tg_spec(
                        ctx, job, tg, nodes, sched.batch, port_cache,
                        fleet=fleet,
                    )
            table = build_node_table(ctx, job, nodes, fleet=fleet)
        except UnsupportedByEngine as e:
            return fallback(str(e))
        device_dims = job_device_dims(job)  # validated above; never raises here
        num_dims = table.totals.shape[1]    # 4 + the job's device dims
        start = _time.monotonic_ns()

        # Deterministic (parity) mode: the exact INTEGER spec of
        # intscore.py — int32 arrays, int64 score60 selection, bit-exact
        # on every backend including the real TPU. Non-deterministic:
        # float32 throughput mode.
        int_mode = bool(ctx.deterministic)
        fdtype = np.int32 if int_mode else np.float32
        if int_mode:
            reason = _int_spec_gate_reason(table, tg_specs, job)
            if reason is not None:
                return fallback(reason)

        pre_tables = None
        if preempt_on:
            # PARITY-CRITICAL: a preemption-enabled host oracle may evict
            # on ANY node, so encoding this eval WITHOUT the candidate
            # tables would diverge from it — every gate below fails the
            # WHOLE eval back to the host stack, never a partial encode.
            if not int_mode:
                return fallback("preemption requires deterministic int mode")
            if destructive:
                return fallback("preemption with destructive updates")
            if device_dims:
                # host oracle would run preempt_for_device (float scoring,
                # instance-level assignment state) — host-only
                return fallback("preemption with device asks")
            if any(
                tg.networks or any(t.resources.networks for t in tg.tasks)
                for tg in (m.get_task_group() for m in missing_list)
            ):
                # host oracle runs preempt_for_network first (reservable
                # port / MBits walk) — host-only
                return fallback("preemption with network asks")
            from .encode import build_preempt_tables

            pre_tables, reason = build_preempt_tables(ctx, job, nodes)
            if reason is not None:
                return fallback(reason)
        _metrics.incr_counter("nomad.tpu_engine.handled")

        n_pad = _round_up(max(n_real, 1))
        g_count = len(job.task_groups)
        specs_by_gi = {spec.index: spec for spec in tg_specs.values()}
        s_max = max((spec.spread_vids.shape[0] for spec in tg_specs.values()), default=0)
        v_max = max((spec.spread_desired.shape[1] for spec in tg_specs.values()), default=1)

        def pad_n(arr, fill=0.0):
            if arr.shape[-1] == n_pad:
                return arr
            pad_width = [(0, 0)] * (arr.ndim - 1) + [(0, n_pad - arr.shape[-1])]
            return np.pad(arr, pad_width, constant_values=fill)

        totals = np.zeros((n_pad, num_dims), fdtype)
        totals[:n_real] = table.totals
        reserved = np.zeros((n_pad, num_dims), fdtype)
        reserved[:n_real] = table.reserved
        used0 = np.zeros((n_pad, num_dims), fdtype)
        used0[:n_real] = table.used

        # Q27 incremental exponentials (int mode): e_base0 per node from
        # the encode-time chain; e_ask static ask factors per TG
        if int_mode:
            from .intscore import E27_ONE, e27_np, xq_np

            node_c2 = (totals[:, :2] - reserved[:, :2]).astype(np.int64)  # [N,2]
            free0 = node_c2 - used0[:, :2] - reserved[:, :2]
            e_base0 = e27_np(xq_np(free0, node_c2)).astype(np.int32)
        else:
            e_base0 = np.zeros((0, 2), np.int32)
        tg_counts0 = np.zeros((g_count, n_pad), np.int32)
        tg_counts0[:, :n_real] = table.tg_counts
        job_counts0 = np.zeros(n_pad, np.int32)
        job_counts0[:n_real] = table.job_counts

        asks = np.zeros((g_count, num_dims), fdtype)
        feas = np.zeros((g_count, n_pad), bool)
        aff_score = np.zeros((g_count, n_pad), fdtype)
        aff_present = np.zeros((g_count, n_pad), bool)
        desired_counts = np.ones(g_count, np.int32)
        dh_job = np.zeros(g_count, bool)
        dh_tg = np.zeros(g_count, bool)
        limits = np.full(g_count, 2, np.int32)
        sv = s_max  # 0 when no TG has spreads: the step's [S,V,N]
        # spread passes become zero-sized and XLA elides them
        vv = max(v_max, 2)
        spread_vids = np.full((g_count, sv, n_pad), vv - 1, np.int32)
        spread_desired = np.full((g_count, sv, vv), -1.0, fdtype)
        spread_weights = np.zeros((g_count, sv), fdtype)
        spread_has_targets = np.zeros((g_count, sv), bool)
        spread_active = np.zeros((g_count, sv), bool)
        sum_spread_weights = np.zeros(g_count, fdtype)
        spread_counts0 = np.zeros((g_count, sv, vv), fdtype)
        spread_entry0 = np.zeros((g_count, sv, vv), bool)

        if int_mode:
            e_ask = np.full((g_count, n_pad, 2), E27_ONE, np.int32)
        else:
            e_ask = np.zeros((0, 0, 2), np.int32)

        # e_ask rows depend only on (fleet capacities, the TG's cpu/mem
        # ask): cache them on the fleet entry — recurring TG shapes (the
        # C1M case: every job identical) skip the two e27 passes per eval
        e_ask_cache = None if fleet is None else fleet.setdefault("e_ask", {})
        for gi, spec in specs_by_gi.items():
            asks[gi] = spec.ask
            if int_mode:
                key = (n_pad, int(spec.ask[0]), int(spec.ask[1]))
                row = None if e_ask_cache is None else e_ask_cache.get(key)
                if row is None:
                    row = np.empty((n_pad, 2), np.int32)
                    for d in (0, 1):
                        row[:, d] = e27_np(
                            xq_np(np.full(n_pad, -int(spec.ask[d]), np.int64),
                                  node_c2[:, d])
                        ).astype(np.int32)
                    if e_ask_cache is not None and len(e_ask_cache) < 64:
                        e_ask_cache[key] = row
                e_ask[gi] = row
            feas[gi, :n_real] = spec.feasible
            aff_score[gi, :n_real] = spec.affinity_score
            aff_present[gi, :n_real] = spec.affinity_present
            desired_counts[gi] = max(spec.desired_count, 1)
            dh_job[gi] = spec.distinct_hosts_job
            dh_tg[gi] = spec.distinct_hosts_tg
            limits[gi] = min(spec.limit, 2**31 - 1)
            s = spec.spread_vids.shape[0]
            if s:
                v_spec = spec.spread_desired.shape[1]
                # remap this spec's invalid bucket onto the shared one (vv-1)
                spread_vids[gi, :s, :n_real] = np.where(
                    spec.spread_vids >= v_spec - 1, vv - 1, spec.spread_vids
                )
                spread_desired[gi, :s, :v_spec] = spec.spread_desired[:, :v_spec]
                spread_weights[gi, :s] = spec.spread_weights
                spread_has_targets[gi, :s] = spec.spread_has_targets
                spread_active[gi, :s] = True
                sum_spread_weights[gi] = spec.sum_spread_weights
                spread_counts0[gi, :s, : spec.spread_counts0.shape[1]] = spec.spread_counts0
                spread_entry0[gi, :s] = spread_counts0[gi, :s] > 0

        # per-placement inputs
        p = len(missing_list)
        tg_idx = np.zeros(p, np.int32)
        penalty_idx = np.full((p, MAX_PENALTY_NODES), -1, np.int32)
        evict_node = np.full(p, -1, np.int32)
        evict_res = np.zeros((p, num_dims), fdtype)
        evict_tg = np.full(p, -1, np.int32)
        limit_p = np.zeros(p, np.int32)
        sum_sw_p = np.zeros(p, fdtype)
        _e27one = 1
        if int_mode:
            from .intscore import E27_ONE as _e27one  # noqa: N811
        ev_factor = np.full((p, 2), _e27one, np.int32)
        rev_factor = np.full((p, 2), _e27one, np.int32)

        # Sticky limit widening + cross-TG spread-weight accumulation,
        # replicating the shared SpreadIterator/LimitIterator state in the
        # host stack (which inplace-update selects may have pre-seeded).
        widened = False
        running_sw = float(sched.stack.spread.sum_spread_weights)
        visited_tgs = set(sched.stack.spread.tg_spread_info.keys())

        tg_name_to_gi = {g.name: i for i, g in enumerate(job.task_groups)}
        for pi, missing in enumerate(missing_list):
            tg = missing.get_task_group()
            gi = tg_name_to_gi[tg.name]
            tg_idx[pi] = gi
            spec = specs_by_gi[gi]
            if tg.name not in visited_tgs:
                visited_tgs.add(tg.name)
                running_sw += float(spec.sum_spread_weights)
            if spec.widens:
                widened = True
            limit_p[pi] = 2**31 - 1 if widened else spec.limit
            sum_sw_p[pi] = running_sw
            prev = missing.get_previous_allocation()
            if prev is not None:
                from ..structs.structs import ALLOC_CLIENT_FAILED

                pens: Dict[str, None] = {}  # ordered de-dup (host uses a set)
                if prev.client_status == ALLOC_CLIENT_FAILED:
                    pens[prev.node_id] = None
                if prev.reschedule_tracker is not None:
                    for ev in prev.reschedule_tracker.events:
                        pens[ev.prev_node_id] = None
                for k, node_id in enumerate(list(pens)[:MAX_PENALTY_NODES]):
                    idx = table.node_index.get(node_id, -1)
                    penalty_idx[pi, k] = idx
            stop_prev, _ = missing.stop_previous_alloc()
            if stop_prev and prev is not None:
                idx = table.node_index.get(prev.node_id, -1)
                if idx >= 0:
                    evict_node[pi] = idx
                    cr = prev.comparable_resources()
                    evict_res[pi, DIM_CPU] = cr.flattened.cpu_shares
                    evict_res[pi, DIM_MEM] = cr.flattened.memory_mb
                    evict_res[pi, 2] = cr.shared.disk_mb
                    mb = 0
                    if prev.allocated_resources is not None:
                        for net in prev.allocated_resources.shared.networks:
                            mb += net.mbits
                        for tr in prev.allocated_resources.tasks.values():
                            for net in tr.networks:
                                mb += net.mbits
                        # devices the eviction frees, on the job's dims
                        if device_dims:
                            for tr in prev.allocated_resources.tasks.values():
                                for dev in tr.devices:
                                    for ask_id, dim in device_dims.items():
                                        if dev.id().matches(ask_id):
                                            evict_res[pi, dim] += len(dev.device_ids)
                                            break
                    evict_res[pi, DIM_MBITS] = mb
                    if prev.job_id == job.id:
                        evict_tg[pi] = tg_name_to_gi.get(prev.task_group, -1)
                    if int_mode:
                        # eviction/revert Q27 factors (evicted node known
                        # at encode time; spec: e27(±evict_res/cap))
                        from .intscore import e27_py, xq_py

                        for d in (0, 1):
                            er = int(evict_res[pi, d])
                            nc = int(node_c2[idx, d])
                            ev_factor[pi, d] = e27_py(xq_py(er, nc))
                            rev_factor[pi, d] = e27_py(xq_py(-er, nc))

        # shape specialization: absent features collapse to zero axes so
        # the step compiles without their ops (see _make_step)
        if not aff_present.any():
            aff_score = aff_score[:0]
            aff_present = aff_present[:0]
        # pack feasibility + affinity presence into ONE uint8 plane,
        # emitted once per eval — cached-encode re-dispatches reuse it
        from .intscore import pack_feat_planes

        feat_packed = pack_feat_planes(feas, aff_present)
        if (penalty_idx == -1).all():
            penalty_idx = penalty_idx[:, :0]
        if (evict_node == -1).all():
            # no destructive updates: the step's eviction/revert machinery
            # compiles away entirely
            evict_res = evict_res[:, :0]
            ev_factor = ev_factor[:, :0]
            rev_factor = rev_factor[:, :0]
        if int_mode:
            # fold reserved into totals: the E factors above were computed
            # from the split, and the fits check is identical on the netted
            # capacities — the step saves one [N, D] add per placement
            totals = totals - reserved
            reserved = np.zeros((0, num_dims), fdtype)

        # distinct_property encoding (zero-D when absent). Pad the node
        # axis: padded nodes keep the MISSING bucket (v-1) and are
        # infeasible anyway.
        try:
            dp_vids_r, dp_limit, dp_applies, dp_counts0 = (
                _distinct_property_arrays(ctx, job, nodes)
            )
        except UnsupportedByEngine as e:
            return fallback(str(e))
        if dp_vids_r.shape[0] and (evict_node >= 0).any():
            # in-eval evictions interact with the host PropertySet's
            # cleared-value refund quirk (propertyset.py:97-105: at most
            # one refund per distinct re-used value) — the scan's exact
            # counters would diverge; host fallback keeps plan parity
            return fallback("distinct_property with in-eval evictions")
        if dp_vids_r.shape[0] and pre_tables is not None:
            # same PropertySet refund quirk, via preempted allocs
            return fallback("distinct_property with preemption")
        d_dp = dp_vids_r.shape[0]
        v_dp = dp_counts0.shape[1] if d_dp else 1
        dp_vids = np.full((d_dp, n_pad), v_dp - 1, np.int32)
        if d_dp:
            dp_vids[:, :n_real] = dp_vids_r

        (pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf,
         pre_alive0, pre_remaining0, pre_counts0) = _pad_preempt_arrays(
            pre_tables, n_pad, n_real, node_c2 if int_mode else None)

        static = (
            totals, reserved, asks, feat_packed, aff_score,
            desired_counts, dh_job, dh_tg, limits, spread_vids, spread_desired,
            spread_weights, spread_has_targets, spread_active,
            sum_spread_weights, np.int32(n_real), e_ask,
            dp_vids, dp_limit, dp_applies,
            pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf,
        )
        # Ring start mirrors the host source iterator's offset as
        # set_nodes left it — 0 in the classic deterministic frame, the
        # per-eval seed when ring decorrelation is on
        # (EvalContext.ring_seed) — so host fallback and device scan walk
        # the same ring.
        offset0 = int(getattr(sched.stack.source, "offset", 0)) % max(n_real, 1)
        init_carry = (
            used0, tg_counts0, job_counts0, spread_counts0, spread_entry0,
            np.int32(offset0), np.zeros(g_count, bool), e_base0, dp_counts0,
            pre_alive0, pre_remaining0, pre_counts0,
        )
        xs = (
            tg_idx, penalty_idx, evict_node, evict_res, evict_tg,
            limit_p, sum_sw_p, ev_factor, rev_factor,
            # forced_node rides a WIDTH axis so unrestricted (generic)
            # evals compile the restriction away entirely
            np.zeros((p, 0), np.int32),
        )

        enc = EncodedEval(
            n_real=n_real, n_pad=n_pad, g=g_count, s=sv, v=vv, p=p,
            dtype=fdtype, static=static, carry=init_carry, xs=xs,
            missing_list=missing_list, nodes=nodes, table=table,
            start_ns=start, dense_ok=dense_ok,
            pre_allocs=(pre_tables.allocs if pre_tables is not None else None),
        )
        if enc_cache is not None and cache_key is not None:
            # arrays are read-only downstream (the batcher pads into
            # fresh buffers; apply only reads); a later hit swaps the
            # ring offset and host context (and usage arrays on an
            # epoch roll)
            if len(enc_cache) >= 32:
                # concurrent encoders (HOST_WORK_SEM admits several) may
                # race to evict the same oldest key — default-pop (an
                # evicted in-flight claim is re-published right below or
                # released by its owner's fallback path)
                enc_cache.pop(next(iter(enc_cache)), None)
            enc_cache[cache_key] = (cur_epoch, enc)
        ev = claim_cell.pop("ev", None)
        if ev is not None:
            ev.set()
        return enc

    def run_scan_single(self, enc: "EncodedEval"):
        """Run one encoded eval through the single-eval jit'd scan."""
        # Build the scan (enables x64) BEFORE converting arrays, or the
        # float64 inputs silently truncate to float32.
        place_scan = self._scan_fn()
        import jax.numpy as jnp

        static = tuple(jnp.asarray(a) for a in enc.static)
        init_carry = tuple(jnp.asarray(a) for a in enc.carry)
        xs = tuple(jnp.asarray(a) for a in enc.xs)

        _carry, (chosen, scores, pulls, skipped, evict) = place_scan(
            enc.n_pad, static, init_carry, xs
        )
        return (
            np.asarray(chosen), np.asarray(scores),
            np.asarray(pulls), np.asarray(skipped), np.asarray(evict),
        )

    @staticmethod
    def _pipeline_remember(sched, enc: "EncodedEval") -> None:
        """Hand this wave's encode to the pipeline's re-dispatch registry
        (pipeline/redispatch.py) before the device dispatch: on a partial
        OCC commit, the async applier re-enters the device stage from the
        remembered encode (row-subset + usage-epoch patch) instead of
        re-running snapshot/encode. No-op outside the pipelined server."""
        pipe = getattr(sched.planner, "pipeline", None)
        if pipe is None:
            return
        try:
            pipe.remember_wave(
                sched.eval.id, enc, sched.job,
                getattr(sched.ctx.state, "node_epoch", -1),
            )
        except Exception:  # noqa: BLE001 — observability hook, never fatal
            logger.debug("pipeline remember_wave failed", exc_info=True)

    @staticmethod
    def _pipeline_forget(sched) -> None:
        """Drop a remembered encode when the wave degrades to the host
        path (failed device dispatch) — the registry entry would
        otherwise strand until the eval acks."""
        pipe = getattr(sched.planner, "pipeline", None)
        if pipe is None:
            return
        try:
            pipe.registry.forget(sched.eval.id)
        except Exception:  # noqa: BLE001
            logger.debug("pipeline forget failed", exc_info=True)

    # ------------------------------------------------------------------
    # System scheduler path: one alloc per ELIGIBLE node — each placement
    # names its node up front (system_sched.go:268-286), so the dense pass
    # is the same scan with a per-placement forced_node restriction and no
    # spread/affinity/limit machinery (SystemStack has none, stack.go:166).
    # ------------------------------------------------------------------

    def compute_system_placements(self, sched, place: List, sched_config=None,
                                  _preempt_pass: bool = False):
        """Batch a SystemScheduler eval's placements through one device
        scan. Returns True when fully handled, a non-empty list of
        leftover placement tuples when the device handled everything
        except nodes that need preemption (the caller runs its host
        per-node loop over just that subset), or NotImplemented to fall
        back to the host stack wholesale (which is semantically
        complete). ``sched_config`` is the SchedulerConfiguration the
        caller already read when choosing this path.
        """
        try:
            import jax  # noqa: F401
        except ImportError:
            return NotImplemented
        if not place:
            return True

        job = sched.job
        ctx = sched.ctx
        nodes = list(sched.nodes)
        n_real = len(nodes)

        from ..utils import metrics as _metrics

        def fallback(reason: str):
            logger.debug("tpu system engine fallback: %s", reason)
            _metrics.incr_counter("nomad.tpu_engine.fallback")
            return NotImplemented

        for node in nodes:
            if len({net.device for net in node.node_resources.networks if net.device}) > 1:
                return fallback("multi-NIC node")

        from ..utils import phases as _phases

        from ..trace import lifecycle as _tlc

        tg_specs: Dict[str, TGSpec] = {}
        port_cache: Dict[str, object] = {}
        try:
            with _phases.track("encode"), \
                    _tlc.pipeline_stage("encode", sched.eval.id):
                for tup in place:
                    tg = tup.task_group
                    if tg.name not in tg_specs:
                        tg_specs[tg.name] = build_tg_spec(
                            ctx, job, tg, nodes, False, port_cache)
                table = build_node_table(ctx, job, nodes)
        except UnsupportedByEngine as e:
            return fallback(str(e))
        int_mode = bool(ctx.deterministic)
        if int_mode:
            reason = _int_spec_gate_reason(table, tg_specs, job)
            if reason is not None:
                return fallback(reason)
        num_dims = table.totals.shape[1]
        start = _time.monotonic_ns()
        fdtype = np.int32 if int_mode else np.float32

        pre_tables = None
        if _preempt_pass:
            # Second device pass over capacity-failed forced nodes: encode
            # WITH the preemption candidate tables. Any gate failure hands
            # the SUBSET to the host per-node loop (list return), never
            # the whole eval — pass-1 results are already applied.
            if not int_mode:
                return list(place)
            if num_dims != 4:
                return list(place)  # preempt_for_device is host-only
            if any(
                tup.task_group.networks
                or any(t.resources.networks for t in tup.task_group.tasks)
                for tup in place
            ):
                return list(place)  # preempt_for_network is host-only
            from .encode import build_preempt_tables

            pre_tables, _pre_reason = build_preempt_tables(ctx, job, nodes)
            if _pre_reason is not None:
                logger.debug("tpu system preempt pass to host: %s", _pre_reason)
                return list(place)

        n_pad = _round_up(max(n_real, 1))
        g_count = len(job.task_groups)
        specs_by_gi = {spec.index: spec for spec in tg_specs.values()}

        totals = np.zeros((n_pad, num_dims), fdtype)
        totals[:n_real] = table.totals
        reserved = np.zeros((n_pad, num_dims), fdtype)
        reserved[:n_real] = table.reserved
        used0 = np.zeros((n_pad, num_dims), fdtype)
        used0[:n_real] = table.used
        tg_counts0 = np.zeros((g_count, n_pad), np.int32)
        tg_counts0[:, :n_real] = table.tg_counts
        job_counts0 = np.zeros(n_pad, np.int32)
        job_counts0[:n_real] = table.job_counts

        if int_mode:
            from .intscore import E27_ONE, e27_np, xq_np

            node_c2 = (totals[:, :2] - reserved[:, :2]).astype(np.int64)
            free0 = node_c2 - used0[:, :2] - reserved[:, :2]
            e_base0 = e27_np(xq_np(free0, node_c2)).astype(np.int32)
            e_ask = np.full((g_count, n_pad, 2), E27_ONE, np.int32)
        else:
            e_base0 = np.zeros((0, 2), np.int32)
            e_ask = np.zeros((0, 0, 2), np.int32)

        asks = np.zeros((g_count, num_dims), fdtype)
        feas = np.zeros((g_count, n_pad), bool)
        for gi, spec in specs_by_gi.items():
            asks[gi] = spec.ask
            feas[gi, :n_real] = spec.feasible
            if int_mode:
                for d in (0, 1):
                    e_ask[gi, :, d] = e27_np(
                        xq_np(np.full(n_pad, -int(spec.ask[d]), np.int64),
                              node_c2[:, d])
                    ).astype(np.int32)

        # SystemStack has no spread/affinity/limit/anti-affinity iterators:
        # encode them inert (zero/absent) so those score terms vanish.
        # (the packed feature plane's affinity lane stays zero)
        from .intscore import pack_feat_planes

        feat_packed = pack_feat_planes(feas)
        aff_score = np.zeros((0, n_pad), np.int64 if int_mode else fdtype)
        desired_counts = np.ones(g_count, np.int32)
        dh_job = np.zeros(g_count, bool)
        dh_tg = np.zeros(g_count, bool)
        limits = np.ones(g_count, np.int32)
        spread_vids = np.full((g_count, 1, n_pad), 1, np.int32)
        spread_desired = np.full((g_count, 1, 2), -1, fdtype)
        spread_weights = np.zeros((g_count, 1), fdtype)
        spread_has_targets = np.zeros((g_count, 1), bool)
        spread_active = np.zeros((g_count, 1), bool)
        sum_spread_weights = np.zeros(g_count, fdtype)
        spread_counts0 = np.zeros((g_count, 1, 2), fdtype)
        spread_entry0 = np.zeros((g_count, 1, 2), bool)

        p = len(place)
        tg_name_to_gi = {g.name: i for i, g in enumerate(job.task_groups)}
        tg_idx = np.zeros(p, np.int32)
        forced = np.zeros(p, np.int32)
        for pi, tup in enumerate(place):
            tg_idx[pi] = tg_name_to_gi[tup.task_group.name]
            forced[pi] = table.node_index.get(tup.alloc.node_id, -1)
        if (forced < 0).any():
            return fallback("system placement on unknown node")

        from ..structs.structs import CONSTRAINT_DISTINCT_PROPERTY

        has_dp = any(
            c.operand == CONSTRAINT_DISTINCT_PROPERTY
            for c in list(job.constraints)
            + [c for tg in job.task_groups for c in tg.constraints]
        )
        if has_dp:
            # host DistinctPropertyIterator counts DP blocks as FILTERED
            # (not exhausted); the dense pass can't split that per forced
            # node without replaying counts — host fallback keeps the
            # bookkeeping identical. (The generic path vectorizes DP.)
            return fallback("system distinct_property")
        dp_vids = np.zeros((0, n_pad), np.int32)
        dp_limit = np.zeros(0, np.int32)
        dp_applies = np.zeros((g_count, 0), bool)
        dp_counts0 = np.zeros((0, 1), np.int32)

        if int_mode:
            # fold reserved into totals (see encode_eval): e factors were
            # computed from the split above
            totals = totals - reserved
            reserved = np.zeros((0, num_dims), fdtype)

        (pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf,
         pre_alive0, pre_remaining0, pre_counts0) = _pad_preempt_arrays(
            pre_tables, n_pad, n_real, node_c2 if int_mode else None)

        static = (
            totals, reserved, asks, feat_packed, aff_score,
            desired_counts, dh_job, dh_tg, limits, spread_vids, spread_desired,
            spread_weights, spread_has_targets, spread_active,
            sum_spread_weights, np.int32(n_real), e_ask,
            dp_vids, dp_limit, dp_applies,
            pre_res, pre_prio, pre_elig, pre_mp, pre_gid, pre_evf,
        )
        init_carry = (
            used0, tg_counts0, job_counts0, spread_counts0, spread_entry0,
            np.int32(0), np.zeros(g_count, bool), e_base0, dp_counts0,
            pre_alive0, pre_remaining0, pre_counts0,
        )
        xs = (
            tg_idx,
            np.full((p, 0), -1, np.int32),       # no reschedule penalties
            np.full(p, -1, np.int32),            # no evictions
            np.zeros((p, 0), fdtype),
            np.full(p, -1, np.int32),
            np.ones(p, np.int32),                # limit: the single node
            np.zeros(p, fdtype),
            np.zeros((p, 0), np.int32),
            np.zeros((p, 0), np.int32),
            forced.reshape(p, 1),
        )
        enc = EncodedEval(
            n_real=n_real, n_pad=n_pad, g=g_count, s=1, v=2, p=p,
            dtype=fdtype, static=static, carry=init_carry, xs=xs,
            missing_list=list(place), nodes=nodes, table=table,
            start_ns=start,
            pre_allocs=(pre_tables.allocs if pre_tables is not None else None),
        )

        # All-distinct forced nodes (single-TG system jobs): the scan-free
        # vectorized kernel — O(1) dispatch instead of O(P) scan steps.
        # Duplicated forced nodes (multi-TG system jobs placing several
        # allocs on one node) interact through used/tg_counts and keep
        # the sequential scan.
        batcher = getattr(sched.planner, "device_batcher", None)
        with _phases.track("device_wait"), \
                _tlc.pipeline_stage("dispatch", sched.eval.id):
            if len(set(forced.tolist())) == p and pre_tables is None:
                # (the forced fast path never encodes preemption — a preempt
                # pass always takes the sequential scan below)
                chosen, scores, pulls, skipped, evict = self.run_forced(enc)
                if batcher is not None:
                    # the forced kernel bypasses the gather queue; count it in
                    # the batcher's stats so dispatch accounting stays whole.
                    # This read-modify-write runs on scheduler worker threads
                    # concurrently with the dispatcher thread's own updates —
                    # both sides take the batcher's lock (guarded-by _lock).
                    with batcher._lock:
                        batcher.stats["dispatches"] = batcher.stats.get("dispatches", 0) + 1
                        batcher.stats["evals"] = batcher.stats.get("evals", 0) + 1
            elif batcher is not None:
                chosen, scores, pulls, skipped, evict = batcher.run(enc)
            else:
                chosen, scores, pulls, skipped, evict = self.run_scan_single(enc)

        # Preemption is a host-side greedy search per node. When enabled
        # and a forced node failed on CAPACITY (feasible by constraints
        # but no fit — port occupancy included: the host preempts port
        # holders), the device results are KEPT for every other placement
        # and only the capacity-failed subset is handed back to the host
        # per-node stack (rank.py BinPackIterator with evict=True), which
        # runs the Preemptor with vectorized distance scoring
        # (scheduler/preemption.py). Constraint-filtered nodes never
        # preempt, so they stay on the device path. The host processes
        # the leftover subset in placement order — the same order the
        # pure-host loop would visit those nodes — so preemption-count
        # penalties (max_parallel) accumulate identically.
        preemption_on = True
        if sched_config is not None:
            preemption_on = sched_config.preemption_config.system_scheduler_enabled
        leftover: List = []
        if preemption_on and not _preempt_pass:
            chosen = np.asarray(chosen)
            keep: List[int] = []
            for pi, tup in enumerate(place):
                if int(chosen[pi]) < 0:
                    spec = tg_specs[tup.task_group.name]
                    idx = int(forced[pi])
                    if idx < n_real and spec.constraint_feasible[idx]:
                        leftover.append(tup)
                        continue
                keep.append(pi)
            if leftover:
                place = [place[k] for k in keep]
                kp = np.asarray(keep, np.int64)
                chosen = np.asarray(chosen)[kp]
                scores = np.asarray(scores)[kp]
                evict = np.asarray(evict)[kp]

        if not _preempt_pass:
            _metrics.incr_counter("nomad.tpu_engine.handled")
        self._apply_system_results(
            sched, place, nodes, table, tg_specs, chosen, scores, start,
            enc=enc, evict=np.asarray(evict),
        )
        if not leftover:
            return True
        # Second device pass: re-encode JUST the capacity-failed forced
        # nodes with the preemption candidate tables (tpu/preempt.py), so
        # preempting system evals never leave the TPU path. Pass-1
        # results are already applied above, so the re-encode sees the
        # same proposed plan state the host per-node loop would. A pass-2
        # gate failure returns the subset for the host loop instead —
        # never NotImplemented (pass 1 is committed).
        _metrics.incr_counter("nomad.tpu_engine.system_preempt_pass")
        res = self.compute_system_placements(
            sched, leftover, sched_config, _preempt_pass=True)
        if res is NotImplemented:
            return leftover
        return res

    def _apply_system_results(self, sched, place, nodes, table, tg_specs,
                              chosen, scores, start_ns, enc=None,
                              evict=None) -> None:
        """Materialize system-scan results: allocs for fits, queued-alloc
        bookkeeping for constraint-filtered nodes, failed metrics +
        per-node blocked evals for capacity failures (system_sched.py host
        path semantics). The all-clean case (every node placed, fresh,
        no network/device asks) takes the dense block path — one-per-node
        system jobs are exactly the shape that benefits."""
        from ..structs.structs import AllocMetric

        job = sched.job
        ctx = sched.ctx

        chosen = np.asarray(chosen)
        if (
            not getattr(sched.eval, "annotate_plan", False)
            and len(place)
            and (chosen[: len(place)] >= 0).all()
            and all(
                (tup.alloc is None or not tup.alloc.id)
                and not tup.task_group.networks
                and not any(
                    t.resources.networks or t.resources.devices
                    for t in tup.task_group.tasks
                )
                for tup in place
            )
        ):
            self._apply_system_results_dense(
                sched, place, nodes, chosen, scores, start_ns,
                enc=enc, evict=evict,
            )
            return

        assigner = _ResourceAssigner(ctx, nodes)

        for pi, tup in enumerate(place):
            tg = tup.task_group
            node_idx = int(chosen[pi])

            if node_idx < 0:
                idx = table.node_index.get(tup.alloc.node_id, -1)
                spec = tg_specs[tg.name]
                if idx < 0 or not spec.constraint_feasible[idx]:
                    # constraint mismatch: the node just isn't in the
                    # job's domain — not a failure. (Port-OCCUPIED nodes
                    # are NOT this case: they're exhausted below, like
                    # the host's rank-phase port exhaustion.)
                    sched.queued_allocs[tg.name] -= 1
                    if (
                        sched.eval.annotate_plan
                        and sched.plan.annotations is not None
                        and tg.name in sched.plan.annotations.desired_tg_updates
                    ):
                        sched.plan.annotations.desired_tg_updates[tg.name].place -= 1
                    continue
                if sched.failed_tg_allocs and tg.name in sched.failed_tg_allocs:
                    sched.failed_tg_allocs[tg.name].coalesced_failures += 1
                    continue
                metrics = AllocMetric()
                metrics.nodes_evaluated = 1
                metrics.nodes_exhausted = 1
                metrics.nodes_available = sched.nodes_by_dc
                if sched.failed_tg_allocs is None:
                    sched.failed_tg_allocs = {}
                sched.failed_tg_allocs[tg.name] = metrics
                sched._add_blocked(nodes[idx])
                continue

            node = nodes[node_idx]
            task_resources, shared_networks, ok = assigner.build(node_idx, tg)
            if not ok:
                if sched.failed_tg_allocs and tg.name in sched.failed_tg_allocs:
                    sched.failed_tg_allocs[tg.name].coalesced_failures += 1
                    continue
                if sched.failed_tg_allocs is None:
                    sched.failed_tg_allocs = {}
                metrics = AllocMetric()
                metrics.nodes_evaluated = 1
                metrics.nodes_exhausted = 1
                metrics.nodes_available = sched.nodes_by_dc
                sched.failed_tg_allocs[tg.name] = metrics
                sched._add_blocked(node)
                continue

            metrics = AllocMetric()
            metrics.nodes_evaluated = 1
            metrics.nodes_available = sched.nodes_by_dc
            if scores.dtype.kind == "i":
                from .intscore import score60_to_float

                score_f = score60_to_float(scores[pi])
            else:
                score_f = float(scores[pi])
            metrics.score_node(node, "binpack", score_f)
            metrics.score_node(node, "normalized-score", score_f)
            metrics.populate_score_meta_data()

            resources = AllocatedResources(
                tasks=task_resources,
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb, networks=shared_networks
                ),
            )
            alloc = Allocation(
                namespace=job.namespace,
                eval_id=sched.eval.id,
                name=tup.name,
                job_id=job.id,
                task_group=tg.name,
                metrics=metrics,
                node_id=node.id,
                node_name=node.name,
                allocated_resources=resources,
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
            )
            if tup.alloc is not None and tup.alloc.id:
                alloc.previous_allocation = tup.alloc.id
            if (
                evict is not None and evict.ndim == 2 and evict.shape[1]
                and enc is not None and enc.pre_allocs is not None
            ):
                row = evict[pi]
                ks = sorted(
                    (c for c in range(row.shape[0]) if int(row[c]) >= 0),
                    key=lambda c: int(row[c]),
                )
                if ks:
                    cand = enc.pre_allocs[node_idx]
                    stops = [cand[c] for c in ks]
                    for stop in stops:
                        sched.plan.append_preempted_alloc(stop, alloc.id)
                    alloc.preempted_allocations = [s.id for s in stops]
            sched.plan.append_alloc(alloc)

        ctx.metrics.allocation_time_ns = _time.monotonic_ns() - start_ns

    @staticmethod
    def _scores_to_float(scores) -> np.ndarray:
        """Display-float conversion (int mode carries score60s)."""
        if scores.dtype.kind == "i":
            from .intscore import TERM_ONE

            return np.asarray(scores, np.float64) / (60.0 * TERM_ONE)
        return np.asarray(scores, np.float64)

    @staticmethod
    def _dense_block(job, tg, eval_id, node_idxs, nodes, names, scores_f,
                     nodes_evaluated, nodes_available, deployment_id=""):
        """One DenseTGPlacements block for a task group's placements —
        shared by the generic and system dense paths. The dense gate
        guarantees no network/device asks, so one AllocatedResources
        prototype covers every slot and ask_vec's mbits is 0."""
        from ..structs.structs import DenseTGPlacements, generate_uuids

        proto = AllocatedResources(
            tasks={
                t.name: AllocatedTaskResources(
                    cpu_shares=t.resources.cpu,
                    memory_mb=t.resources.memory_mb,
                )
                for t in tg.tasks
            },
            shared=AllocatedSharedResources(disk_mb=tg.ephemeral_disk.size_mb),
        )
        return DenseTGPlacements(
            namespace=job.namespace,
            job_id=job.id,
            task_group=tg.name,
            eval_id=eval_id,
            deployment_id=deployment_id,
            job=job,
            resources_proto=proto,
            ask_vec=(
                float(sum(t.resources.cpu for t in tg.tasks)),
                float(sum(t.resources.memory_mb for t in tg.tasks)),
                float(tg.ephemeral_disk.size_mb),
                0.0,
            ),
            ids=generate_uuids(len(node_idxs)),
            names=names,
            node_ids=[nodes[int(j)].id for j in node_idxs],
            node_names=[nodes[int(j)].name for j in node_idxs],
            scores=[float(s) for s in scores_f],
            nodes_evaluated=list(nodes_evaluated),
            nodes_available=nodes_available,
        )

    def _apply_system_results_dense(self, sched, place, nodes, chosen,
                                    scores, start_ns, enc=None,
                                    evict=None) -> None:
        """System-path dense blocks: same DenseTGPlacements flow as the
        generic path, grouped by task group. Preconditions checked by the
        caller: every placement chose its node, all fresh, no
        network/device asks."""
        job = sched.job
        scores_f = self._scores_to_float(scores)
        by_tg: Dict[str, List[int]] = {}
        for pi, tup in enumerate(place):
            by_tg.setdefault(tup.task_group.name, []).append(pi)
        tg_by_name = {tg.name: tg for tg in job.task_groups}
        has_pre = (
            evict is not None and evict.ndim == 2 and evict.shape[1] > 0
            and enc is not None and enc.pre_allocs is not None
        )
        for tg_name, idxs in by_tg.items():
            block = self._dense_block(
                job, tg_by_name[tg_name], sched.eval.id,
                [chosen[k] for k in idxs], nodes,
                names=[place[k].name for k in idxs],
                scores_f=[scores_f[k] for k in idxs],
                nodes_evaluated=[1] * len(idxs),
                nodes_available=getattr(sched, "nodes_by_dc", {}),
            )
            if has_pre:
                pre_ids: List[List[str]] = []
                any_pre = False
                for bi, k in enumerate(idxs):
                    row = evict[int(k)]
                    ks = sorted(
                        (c for c in range(row.shape[0]) if int(row[c]) >= 0),
                        key=lambda c: int(row[c]),
                    )
                    if not ks:
                        pre_ids.append([])
                        continue
                    cand = enc.pre_allocs[int(chosen[int(k)])]
                    stops = [cand[c] for c in ks]
                    for stop in stops:
                        sched.plan.append_preempted_alloc(stop, block.ids[bi])
                    pre_ids.append([s.id for s in stops])
                    any_pre = True
                if any_pre:
                    block.preempted = pre_ids
            sched.plan.dense_placements.append(block)
        sched.ctx.metrics.allocation_time_ns = _time.monotonic_ns() - start_ns

    # ------------------------------------------------------------------

    def _apply_results_dense(self, sched, enc, chosen, scores, pulls,
                             evict=None) -> None:
        """Record scan results as DenseTGPlacements blocks — one per task
        group, parallel arrays only. The per-placement work here is a few
        list appends; AllocMetric/Allocation objects materialize lazily
        on read (structs.DenseTGPlacements.materialize). Preconditions
        (checked by the caller): enc.dense_ok, every placement chosen."""
        job = sched.job
        deployment_id = ""
        if sched.deployment is not None and sched.deployment.active():
            deployment_id = sched.deployment.id

        scores_f = self._scores_to_float(scores)
        pulls = np.asarray(pulls)
        tg_idx = enc.xs[0]  # [p] task-group index per placement
        missing_list = enc.missing_list
        has_pre = (
            evict is not None and evict.ndim == 2 and evict.shape[1] > 0
            and enc.pre_allocs is not None
        )

        for gi in np.unique(tg_idx):
            sel = np.nonzero(tg_idx == gi)[0]
            block = self._dense_block(
                job, job.task_groups[int(gi)], sched.eval.id,
                chosen[sel], enc.nodes,
                names=[missing_list[k].get_name() for k in sel],
                scores_f=scores_f[sel],
                nodes_evaluated=pulls[sel].tolist(),
                nodes_available=getattr(sched, "_nodes_by_dc", {}),
                deployment_id=deployment_id,
            )
            if has_pre:
                # eviction sets ride the block as parallel id lists AND go
                # into plan.node_preemptions (plan_apply re-checks them and
                # the FSM commits the evictions)
                pre_ids: List[List[str]] = []
                any_pre = False
                for bi, k in enumerate(sel):
                    row = evict[int(k)]
                    ks = sorted(
                        (c for c in range(row.shape[0]) if int(row[c]) >= 0),
                        key=lambda c: int(row[c]),
                    )
                    if not ks:
                        pre_ids.append([])
                        continue
                    cand = enc.pre_allocs[int(chosen[int(k)])]
                    stops = [cand[c] for c in ks]
                    for stop in stops:
                        sched.plan.append_preempted_alloc(stop, block.ids[bi])
                    pre_ids.append([s.id for s in stops])
                    any_pre = True
                if any_pre:
                    block.preempted = pre_ids
            sched.plan.dense_placements.append(block)

        sched.ctx.metrics.allocation_time_ns = _time.monotonic_ns() - enc.start_ns

    def _apply_results(self, sched, missing_list, nodes, table, chosen, scores,
                       pulls, skipped_steps, start_ns, enc=None,
                       evict=None) -> None:
        """Materialize scan results into the plan (allocs, stops, metrics)."""
        from ..structs.structs import AllocMetric

        job = sched.job
        ctx = sched.ctx
        deployment_id = ""
        if sched.deployment is not None and sched.deployment.active():
            deployment_id = sched.deployment.id
        now = _time.time_ns()

        # Lazy per-node NetworkIndex / DeviceAllocator mirrors for port and
        # device-instance assignment (the discrete half the capacity dims
        # pre-checked on device).
        assigner = _ResourceAssigner(ctx, nodes)

        for pi, missing in enumerate(missing_list):
            tg = missing.get_task_group()
            node_idx = int(chosen[pi])

            if skipped_steps[pi]:
                # coalesced failure (TG already failed earlier in this eval)
                if sched.failed_tg_allocs and tg.name in sched.failed_tg_allocs:
                    sched.failed_tg_allocs[tg.name].coalesced_failures += 1
                continue

            prev_allocation = missing.get_previous_allocation()
            stop_prev, stop_desc = missing.stop_previous_alloc()

            metrics = AllocMetric()
            metrics.nodes_evaluated = int(pulls[pi])
            metrics.nodes_available = getattr(sched, "_nodes_by_dc", {})

            if node_idx < 0:
                if sched.failed_tg_allocs is None:
                    sched.failed_tg_allocs = {}
                sched.failed_tg_allocs[tg.name] = metrics
                continue

            if stop_prev and prev_allocation is not None:
                sched.plan.append_stopped_alloc(prev_allocation, stop_desc, "")

            node = nodes[node_idx]

            task_resources, shared_networks, ok = assigner.build(node_idx, tg)
            if not ok:
                # Port/device-instance collision the capacity model missed:
                # extremely rare; record as failed placement (plan applier
                # would have rejected it anyway).
                if sched.failed_tg_allocs is None:
                    sched.failed_tg_allocs = {}
                sched.failed_tg_allocs[tg.name] = metrics
                if stop_prev and prev_allocation is not None:
                    sched.plan.pop_update(prev_allocation)
                continue

            if scores.dtype.kind == "i":
                # int-spec score60 -> display float (metrics only; never
                # used in selection comparisons)
                from .intscore import score60_to_float

                score_f = score60_to_float(scores[pi])
            else:
                score_f = float(scores[pi])
            metrics.score_node(node, "binpack", score_f)
            metrics.score_node(node, "normalized-score", score_f)
            metrics.populate_score_meta_data()

            resources = AllocatedResources(
                tasks=task_resources,
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb, networks=shared_networks
                ),
            )

            alloc = Allocation(
                namespace=job.namespace,
                eval_id=sched.eval.id,
                name=missing.get_name(),
                job_id=job.id,
                task_group=tg.name,
                metrics=metrics,
                node_id=node.id,
                node_name=node.name,
                deployment_id=deployment_id,
                allocated_resources=resources,
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
            )

            if prev_allocation is not None:
                alloc.previous_allocation = prev_allocation.id
                if missing.is_rescheduling():
                    from ..scheduler.generic_sched import update_reschedule_tracker

                    update_reschedule_tracker(alloc, prev_allocation, now)

            if missing.is_canary() and sched.deployment is not None:
                state = sched.deployment.task_groups.get(tg.name)
                if state is not None:
                    state.placed_canaries.append(alloc.id)
                from ..structs.structs import AllocDeploymentStatus

                alloc.deployment_status = AllocDeploymentStatus(canary=True)

            if (
                evict is not None and evict.ndim == 2 and evict.shape[1]
                and enc is not None and enc.pre_allocs is not None
            ):
                # device eviction set: column c holds the second-pass rank
                # (>=0 kept, -1 dropped); materialize in rank order — the
                # order the host oracle reports preempted allocs in
                row = evict[pi]
                ks = sorted(
                    (c for c in range(row.shape[0]) if int(row[c]) >= 0),
                    key=lambda c: int(row[c]),
                )
                if ks:
                    cand = enc.pre_allocs[node_idx]
                    stops = [cand[c] for c in ks]
                    for stop in stops:
                        sched.plan.append_preempted_alloc(stop, alloc.id)
                    alloc.preempted_allocations = [s.id for s in stops]

            sched.plan.append_alloc(alloc)

        ctx.metrics.allocation_time_ns = _time.monotonic_ns() - start_ns


# ---------------------------------------------------------------------------
# Synthetic inputs (graft entry / dryrun / microbench)
# ---------------------------------------------------------------------------


def example_scan_inputs(n_nodes: int = 64, n_tgs: int = 2, n_placements: int = 16,
                        n_spreads: int = 1, vocab: int = 4,
                        dtype=np.float32, seed: int = 0, num_dims: int = 4):
    """Build plausible dense scan inputs directly (no scheduler objects).

    Returns (n_pad, static, init_carry, xs) as numpy arrays, shaped exactly
    like compute_placements builds them. ``dtype=np.int32`` builds the
    exact-integer parity encoding (spread targets in hundredths, Q30
    affinity ints — the intscore.py spec); float dtypes build the
    throughput encoding.
    """
    dtype = np.dtype(dtype)
    int_mode = dtype.kind == "i"
    rng = np.random.default_rng(seed)
    n_pad = _round_up(n_nodes)
    # zero n_spreads = a true ZERO S axis: the spread machinery
    # (one-hot [S,V,N] lookups, boosts, count carries) compiles away
    # entirely, matching production encode for spread-free jobs
    g, s, v = n_tgs, n_spreads, vocab + 1

    totals = np.zeros((n_pad, num_dims), dtype)
    totals[:n_nodes, DIM_CPU] = rng.choice([2000, 4000, 8000], n_nodes)
    totals[:n_nodes, DIM_MEM] = rng.choice([4096, 8192, 16384], n_nodes)
    totals[:n_nodes, 2] = 100 * 1024
    totals[:n_nodes, DIM_MBITS] = 1000
    reserved = np.zeros((n_pad, num_dims), dtype)
    reserved[:n_nodes, DIM_CPU] = 100
    reserved[:n_nodes, DIM_MEM] = 256
    used0 = np.zeros((n_pad, num_dims), dtype)

    asks = np.zeros((g, num_dims), dtype)
    asks[:, DIM_CPU] = rng.choice([100, 250, 500], g)
    asks[:, DIM_MEM] = rng.choice([128, 256, 512], g)
    asks[:, 2] = 150
    asks[:, DIM_MBITS] = 10

    feas = np.zeros((g, n_pad), bool)
    feas[:, :n_nodes] = rng.random((g, n_nodes)) < 0.9
    from .intscore import pack_feat_planes

    feat_packed = pack_feat_planes(feas)
    # no affinities in the synthetic workload: zero G axis (the step
    # compiles the affinity term away — matching production encode)
    aff_score = np.zeros((0, n_pad), dtype)
    desired_counts = np.full(g, max(n_placements // g, 1), np.int32)
    dh_job = np.zeros(g, bool)
    dh_tg = np.zeros(g, bool)
    limits = np.full(g, max(2, int(np.ceil(np.log2(max(n_nodes, 2))))), np.int32)

    spread_vids = np.full((g, s, n_pad), v - 1, np.int32)
    spread_vids[:, :, :n_nodes] = rng.integers(0, vocab, (g, s, n_nodes))
    spread_desired = np.full((g, s, v), -1, dtype) if int_mode else \
        np.full((g, s, v), -1.0, dtype)
    if int_mode:
        # hundredths (d = percent * count), evenly targeted
        spread_desired[:, :, :vocab] = (100 * n_placements) // vocab
    else:
        spread_desired[:, :, :vocab] = float(n_placements) / vocab
    spread_weights = np.full((g, s), 50, dtype)
    spread_has_targets = np.ones((g, s), bool)
    spread_active = np.zeros((g, s), bool)
    spread_active[:, :n_spreads] = True
    sum_spread_weights = np.full(g, 50 * max(n_spreads, 1), dtype)
    spread_counts0 = np.zeros((g, s, v), dtype)
    spread_entry0 = np.zeros((g, s, v), bool)

    if int_mode:
        from .intscore import E27_ONE, e27_np, xq_np

        node_c2 = (totals[:, :2] - reserved[:, :2]).astype(np.int64)
        e_base0 = e27_np(xq_np(node_c2 - used0[:, :2] - reserved[:, :2],
                               node_c2)).astype(np.int32)
        e_ask = np.full((g, n_pad, 2), E27_ONE, np.int32)
        for gi in range(g):
            for d in (0, 1):
                e_ask[gi, :, d] = e27_np(
                    xq_np(np.full(n_pad, -int(asks[gi, d]), np.int64),
                          node_c2[:, d])
                ).astype(np.int32)
        # reserved folds into totals (see encode_eval)
        totals = totals - reserved
        reserved = np.zeros((0, num_dims), dtype)
    else:
        e_base0 = np.zeros((0, 2), np.int32)
        e_ask = np.zeros((0, 0, 2), np.int32)

    static = (totals, reserved, asks, feat_packed, aff_score,
              desired_counts, dh_job, dh_tg, limits, spread_vids,
              spread_desired, spread_weights, spread_has_targets,
              spread_active, sum_spread_weights, np.int32(n_nodes), e_ask,
              np.zeros((0, n_pad), np.int32),   # dp_vids: no distinct_property
              np.zeros(0, np.int32),
              np.zeros((g, 0), bool),
              # no preemption: zero-width candidate axis compiles the
              # eviction path away
              np.zeros((n_pad, 0, 4), np.int32), np.zeros((n_pad, 0), np.int32),
              np.zeros((n_pad, 0), bool), np.zeros((n_pad, 0), np.int32),
              np.zeros((n_pad, 0), np.int32), np.zeros((n_pad, 0, 2), np.int32))
    init_carry = (used0, np.zeros((g, n_pad), np.int32), np.zeros(n_pad, np.int32),
                  spread_counts0, spread_entry0, np.int32(0), np.zeros(g, bool),
                  e_base0, np.zeros((0, 1), np.int32),
                  np.zeros((n_pad, 0), bool), np.zeros((0, 3), np.int64),
                  np.zeros(0, np.int32))
    limit_val = max(2, int(np.ceil(np.log2(max(n_nodes, 2)))))
    xs = (rng.integers(0, g, n_placements).astype(np.int32),
          np.full((n_placements, 0), -1, np.int32),  # no reschedule history
          np.full(n_placements, -1, np.int32),
          # no evictions: zero-width axes compile the evict path away
          np.zeros((n_placements, 0), dtype),
          np.full(n_placements, -1, np.int32),
          np.full(n_placements, 2**31 - 1 if n_spreads else limit_val, np.int32),
          np.full(n_placements, 50 * max(n_spreads, 1), dtype),
          np.zeros((n_placements, 0), np.int32),
          np.zeros((n_placements, 0), np.int32),
          np.zeros((n_placements, 0), np.int32))  # forced_node: unrestricted
    return n_pad, static, init_carry, xs


# ---------------------------------------------------------------------------
# Chunked throughput scan: K placements of one task group per step
# ---------------------------------------------------------------------------

CHUNK_K = 128


def _build_chunk_scan(chunk_k: int = CHUNK_K):
    """Throughput-mode scan: each step places up to K instances of one task
    group on the top-K scoring distinct feasible nodes.

    Every chosen node is individually capacity-checked for one ask, so the
    resulting plan is valid; scores refresh between chunks rather than
    between single placements. This trades the reference's exact sequential
    semantics (kept in the parity scan) for ~K x fewer sequential device
    steps — the reference itself already subsamples candidates per placement
    (log2 N window), so chunked top-K dominates it on both quality and speed.

    A per-TG DEFICIT rides an internal carry: a chunk that places fewer
    than asked (feasible set momentarily smaller than K) rolls the
    shortfall into that TG's later chunks — including want=0 retry steps
    appended by ``chunk_schedule(retry_rounds=...)`` — so large chunk
    sizes keep exact placement counts instead of dropping the tail.
    """
    import jax
    import jax.numpy as jnp

    from .intscore import (
        FEAT_AFF_BIT,
        FEAT_FEAS_BIT,
        pack_presence_lanes,
        unpack_feat_lane,
    )

    jax.config.update("jax_enable_x64", True)
    _enable_persistent_compile_cache()
    CHUNK = int(chunk_k)

    def step(static, carry_and_deficit, x):
        # Gather-free like the parity step (_make_step): dynamic row-selects
        # become one-hot where+sum picks; the top-K scatter-adds become
        # one-hot [K, N] membership sums. Exact (single non-zero term per
        # select; top_k indices are distinct) and ~10x faster on this
        # backend than dynamic-index gathers/scatters in a scan body.
        carry, deficit = carry_and_deficit
        (totals, reserved, asks, feat_packed, aff_score, desired_counts,
         dh_job, dh_tg, limits, spread_vids, spread_desired, spread_weights,
         spread_has_targets, spread_active, sum_spread_weights, n_real,
         *_extra) = static
        (used, tg_counts, job_counts, spread_counts, spread_entry, offset,
         failed, *_cextra) = carry
        tg_idx, want = x

        n_pad = totals.shape[0]
        g_count = asks.shape[0]
        g = tg_idx
        fdt = totals.dtype

        iota_g = jnp.arange(g_count, dtype=jnp.int32)
        sel_g = (iota_g == g)  # [G] one-hot of the TG
        iota = jnp.arange(n_pad, dtype=jnp.int32)

        def pick_g(arr, fill=0):
            shape = (g_count,) + (1,) * (arr.ndim - 1)
            return jnp.sum(jnp.where(sel_g.reshape(shape), arr, fill), axis=0)

        ask = pick_g(asks)                               # [D]
        # one packed uint8 plane carries feasibility + affinity presence
        # (intscore.pack_feat_planes), same layout as the parity step
        feat_g = pick_g(feat_packed)                     # [N] uint8
        feas_g = unpack_feat_lane(feat_g, FEAT_FEAS_BIT)
        tg_counts_g = pick_g(tg_counts)                  # [N]
        dh_job_g = jnp.any(sel_g & dh_job)
        dh_tg_g = jnp.any(sel_g & dh_tg)
        desired_g = pick_g(desired_counts).astype(fdt)

        util = used + reserved + ask[None, :]
        fits = jnp.all(util <= totals, axis=-1)
        dh_mask = jnp.where(
            dh_job_g,
            job_counts == 0,
            jnp.where(dh_tg_g, ~((tg_counts_g > 0) & (job_counts > 0)), True),
        )
        feasible = feas_g & fits & dh_mask & (iota < n_real)

        node_cpu = totals[:, DIM_CPU] - reserved[:, DIM_CPU]
        node_mem = totals[:, DIM_MEM] - reserved[:, DIM_MEM]
        free_cpu = 1.0 - util[:, DIM_CPU] / jnp.maximum(node_cpu, 1e-9)
        free_mem = 1.0 - util[:, DIM_MEM] / jnp.maximum(node_mem, 1e-9)
        binpack = jnp.clip(20.0 - (jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)), 0.0, 18.0) / 18.0

        collisions = tg_counts_g.astype(fdt)
        anti_present = collisions > 0
        anti = jnp.where(anti_present, -(collisions + 1.0) / desired_g, 0.0)

        # shape specialization (compile-time): affinity-free workloads
        # encode a ZERO G axis (engine.encode_eval / example_scan_inputs)
        # and the term vanishes from the compiled step
        if aff_score.shape[0] == 0:
            aff = jnp.zeros(n_pad, fdt)
            aff_p = jnp.zeros(n_pad, bool)
        else:
            aff = pick_g(aff_score)
            aff_p = unpack_feat_lane(feat_g, FEAT_AFF_BIT)

        vids = pick_g(spread_vids)                       # [S, N]
        s_counts = pick_g(spread_counts)                 # [S, V]
        desired_sv = pick_g(spread_desired)              # [S, V]
        weights_s = pick_g(spread_weights)               # [S]
        active_s = pick_g(spread_active, False)          # [S]
        sum_sw_g = pick_g(sum_spread_weights)
        v_plus = s_counts.shape[-1]
        iota_v = jnp.arange(v_plus, dtype=jnp.int32)
        big = jnp.finfo(fdt).max / 16.0
        # value-id lookups as one-hot sums over V (no take_along_axis)
        oh_vids = vids[:, None, :] == iota_v[None, :, None]  # [S, V, N]
        used_count = jnp.sum(jnp.where(oh_vids, s_counts[:, :, None], 0.0), axis=1) + 1.0
        d = jnp.sum(jnp.where(oh_vids, desired_sv[:, :, None], 0.0), axis=1)
        missing = vids == v_plus - 1
        weight_frac = weights_s[:, None] / jnp.maximum(sum_sw_g, 1e-9)
        targeted = jnp.where(
            d > 0.0,
            (d - used_count) / jnp.where(d > 0.0, d, 1.0) * weight_frac,
            jnp.where(d == 0.0, -big, -1.0),
        )
        per_spread = jnp.where(missing, -1.0, targeted)
        per_spread = jnp.where(active_s[:, None], per_spread, 0.0)
        spread_total = jnp.sum(per_spread, axis=0)
        spread_p = spread_total != 0.0

        # popcount num_terms over one packed presence plane (no reschedule
        # penalties in chunked mode: that lane rides constant-false)
        presence = pack_presence_lanes(
            anti_present, jnp.zeros(n_pad, bool), aff_p, spread_p
        )
        num_terms = (1 + jax.lax.population_count(presence)).astype(fdt)
        final = (binpack + anti + jnp.where(aff_p, aff, 0.0) + spread_total) / num_terms

        neg_inf = -jnp.inf
        masked = jnp.where(feasible, final, neg_inf)
        top_scores, top_idx = jax.lax.top_k(masked, CHUNK)
        # int sums promote to int64 under x64 — cast back to keep the
        # carry dtypes fixed
        want_total = (want + pick_g(deficit)).astype(jnp.int32)
        want_eff = jnp.minimum(want_total, CHUNK)
        valid = (jnp.arange(CHUNK, dtype=jnp.int32) < want_eff) & (top_scores > neg_inf)
        placed = jnp.sum(valid.astype(jnp.int32)).astype(jnp.int32)
        deficit = jnp.where(sel_g, want_total - placed, deficit).astype(jnp.int32)

        # one-hot membership of the chosen nodes: top_k indices are
        # distinct, so sel_nodes is 0/1 and the adds are exact
        oh_sel = (iota[None, :] == top_idx[:, None]) & valid[:, None]  # [K, N]
        sel_nodes = jnp.sum(oh_sel.astype(jnp.int32), axis=0).astype(jnp.int32)  # [N]
        sel_nodes_f = sel_nodes.astype(fdt)
        used = used + sel_nodes_f[:, None] * ask[None, :]
        tg_counts = tg_counts + sel_g[:, None] * sel_nodes[None, :]
        job_counts = job_counts + sel_nodes
        # spread count add: per (s, v), how many chosen nodes carry value v
        add_sv = jnp.sum(
            jnp.where(oh_vids, sel_nodes_f[None, None, :], 0.0), axis=2
        ) * active_s[:, None].astype(fdt)                              # [S, V]
        spread_counts = spread_counts + jnp.where(
            sel_g[:, None, None], add_sv[None, :, :], 0.0
        )

        new_carry = (used, tg_counts, job_counts, spread_counts, spread_entry,
                     offset, failed, *_cextra)
        out = (top_idx, jnp.where(valid, top_scores, 0.0), valid, placed)
        return (new_carry, deficit), out

    @partial(jax.jit, static_argnames=("n_pad",))
    def chunk_scan(n_pad, static, init_carry, xs, deficit=None):
        import jax.lax as lax

        n_tgs = static[2].shape[0]
        if deficit is None:
            deficit = jnp.zeros(n_tgs, jnp.int32)
        (carry, deficit_out), ys = lax.scan(
            lambda c, x: step(static, c, x), (init_carry, deficit), xs
        )
        # deficit_out rides along so multi-phase schedules (bulk chunks →
        # tail chunks) hand unfilled counts to the next phase
        return carry, deficit_out, ys

    return chunk_scan


def chunk_schedule(counts_by_tg, chunk: int = CHUNK_K, retry_rounds: int = 0):
    """Expand per-TG placement counts into (tg_idx, want) step arrays.

    ``retry_rounds`` appends want=0 sweeps per TG: the scan's deficit
    carry drains any shortfall through them (capacity freed or discovered
    after a TG's main chunks have passed), never over-placing — a want=0
    step with zero deficit is a no-op."""
    # round-robin across TGs: scheduling one TG to completion before the
    # next starves the last TGs of capacity and piles the whole deficit on
    # them; interleaving spreads both load and shortfall evenly
    remaining = {gi: count for gi, count in counts_by_tg}
    tg_steps = []
    while any(v > 0 for v in remaining.values()):
        for gi, _count in counts_by_tg:
            if remaining[gi] <= 0:
                continue
            take = min(remaining[gi], chunk)
            tg_steps.append((gi, take))
            remaining[gi] -= take
    for _ in range(max(0, retry_rounds)):
        for gi, _count in counts_by_tg:
            tg_steps.append((gi, 0))
    tg_idx = np.asarray([s[0] for s in tg_steps], np.int32)
    want = np.asarray([s[1] for s in tg_steps], np.int32)
    return tg_idx, want
