"""Bridge between GenericScheduler and the JAX placement engine.

``compute_placements_with_engine`` returns True when the engine handled the
eval's whole placement batch, or NotImplemented to fall back to the host
iterator stack (the host path is always semantically complete).

Both entry points run under the ``engine_gate`` phase: the gate checks,
encode attempts and fallback decisions are host work the worker pays on
EVERY eval (device-handled or not), and without a span of their own they
showed up as unexplained worker_busy time in phases.coverage. The
engine's finer phases (encode/pad_stack/device/apply) nest inside; the
coverage union dedups the overlap.
"""
from __future__ import annotations

from ..utils import phases as _phases


def compute_placements_with_engine(sched, destructive, place):
    with _phases.track("engine_gate"):
        # the lazy engine import is part of the gate cost: the first
        # eval pays it (jax + kernel modules), and outside the span it
        # surfaced as a one-shot unexplained worker_busy chunk
        try:
            from .engine import TpuPlacementEngine
        except ImportError:
            return NotImplemented
        engine = TpuPlacementEngine.shared()
        return engine.compute_placements(sched, destructive, place)


def compute_system_placements_with_engine(sched, place, sched_config=None):
    """SystemScheduler device path (forced-node dense pass); True when
    handled, a list of leftover placements when only preemption-needing
    nodes remain for the host loop, NotImplemented to fall back to the
    host per-node stack wholesale."""
    with _phases.track("engine_gate"):
        try:
            from .engine import TpuPlacementEngine
        except ImportError:
            return NotImplemented
        engine = TpuPlacementEngine.shared()
        return engine.compute_system_placements(sched, place, sched_config)
