"""Bridge between GenericScheduler and the JAX placement engine.

``select_with_tpu_engine`` may return NotImplemented to fall back to the host
iterator stack (e.g. when the task group uses features the device engine
doesn't accelerate yet — the host path is always semantically complete).
"""
from __future__ import annotations


def select_with_tpu_engine(sched, tg, select_options):
    try:
        from .engine import TpuPlacementEngine
    except ImportError:
        return NotImplemented
    engine = TpuPlacementEngine.shared()
    return engine.select(sched, tg, select_options)
