"""Bridge between GenericScheduler and the JAX placement engine.

``compute_placements_with_engine`` returns True when the engine handled the
eval's whole placement batch, or NotImplemented to fall back to the host
iterator stack (the host path is always semantically complete).
"""
from __future__ import annotations


def compute_placements_with_engine(sched, destructive, place):
    try:
        from .engine import TpuPlacementEngine
    except ImportError:
        return NotImplemented
    engine = TpuPlacementEngine.shared()
    return engine.compute_placements(sched, destructive, place)


def compute_system_placements_with_engine(sched, place, sched_config=None):
    """SystemScheduler device path (forced-node dense pass); True when
    handled, a list of leftover placements when only preemption-needing
    nodes remain for the host loop, NotImplemented to fall back to the
    host per-node stack wholesale."""
    try:
        from .engine import TpuPlacementEngine
    except ImportError:
        return NotImplemented
    engine = TpuPlacementEngine.shared()
    return engine.compute_system_placements(sched, place, sched_config)
