"""Exact integer scoring spec for the ``tpu_binpack`` parity engine.

The round-2 engine scored in float64 — emulated (double-double) on TPU
v5e and STILL not bit-identical to the host (XLA's f64 ``pow`` rounds
differently from libm, flipping exact-tie orderings; that forced the
parity suite onto the CPU backend). This module replaces float scoring
with a deterministic integer program: every runtime operation is an
int32/int64 add, multiply, shift, compare or floor division — exact on
every backend — so the device scan's selection decisions are
bit-identical to a pure-Python evaluation of the same spec ON THE REAL
CHIP, with no floating point in the comparison path.

Cost model that shaped the design (profiled on the tunneled axon
backend): scan-body cost is per-HLO-pass over the [batch, nodes]
arrays; a 26-multiply exponential chain or an int64 division per step
is ruinous, while small ([batch]- or [S,V]-shaped) ops are free.
Hence the exponential is INCREMENTAL-MULTIPLICATIVE:

  e_base[n]  Q27 10**x_base, x_base = (cap - used - reserved)/cap,
             carried per node; initialized by the encode-time chain and
             updated by MULTIPLYING precomputed Q27 factors when a
             placement/eviction changes the node (a running product —
             each update floor-rounds at Q27, drift <= k*2**-27 for k
             touch events, mirrored exactly by the oracle)
  e_ask[g,n] Q27 10**(-ask_g/cap_n): static per eval (encode-time)
  ev/rev     Q27 eviction/revert factors: per-placement scalars
             (the evicted node is known at encode time)
  score      E_sel = (e_base * e_ask) >> 27 per dim; BestFit-v3 =
             clip(20*2**27 - Ec - Em, 0, 18*2**27); Q30 term =
             (fit * 4) // 9 (constant divisor — lowered to mult+shift)

Numeric layout
  x (free fraction)   Q24, x_q = floor(x * 2**24), clamped to [-2, 1]
  10**x               Q28 bit-product chain (ENCODE TIME ONLY):
                      prod over set bits i of round(2**28 * 10**(2**(i-24)));
                      negative x via 2**56 // E(|x|); Q27 = (Q28+1)>>1
  score terms         Q30: binpack as above; anti-affinity and the
                      even-spread boost via Q45 reciprocals of SMALL
                      denominators (counts <= 2**17, so the reciprocal
                      error is < 4 Q30-ulp); the targeted spread boost
                      via ONE exact int64 floor division
  final selection     score60 = terms_sum * (60 // num_terms) —
                      num_terms in 1..5 all divide 60, so the mean
                      normalization (rank.go:688) is an EXACT multiply

Precision vs the reference's float64 (funcs.go:154 ScoreFit): the spec
tracks the real-valued score within ~5e-7, so orderings agree with the
host float64 pipeline whenever true score gaps exceed that — which the
parity fuzz corpus (and any realistic cluster: the smallest binpack gap
is ~ask/capacity ~ 1e-2) clears by orders of magnitude. Exact rational
ties (identical node tuples) tie in BOTH systems and fall to the same
deterministic rank tie-break.

Magnitude gates (enforced by encode; host fallback otherwise):
  cpu/mem capacities       <= 2**24
  reserved                 <= 2 * (totals - reserved)
  any capacity/ask         <= 2**28
  job total count          <= 100_000
  spread weight            in [0, 256]; spread percent in [0, 100]
  sum of spread weights    > 0 when spreads exist
With these every int64 intermediate stays below 2**63.
"""
from __future__ import annotations

from typing import List

import numpy as np

# Fixed-point scales
XQ_BITS = 24          # Q24 free-fraction quantization
E_BITS = 28           # Q28 encode-time exponential chain
E27_BITS = 27         # Q27 runtime e_base / factor arrays (fit int32)
TERM_BITS = 30        # Q30 score terms
RECIP_BITS = 45       # Q45 reciprocals of small denominators
TERM_ONE = 1 << TERM_BITS
XQ_ONE = 1 << XQ_BITS
E_ONE = 1 << E_BITS
E27_ONE = 1 << E27_BITS

# d == 0 spread-target sentinel: the host uses -finfo.max/16; any value
# far beyond the legitimate term range works.
BIG_FP = 1 << 44

# Max job total count for the int path (overflow gate, see module doc)
MAX_TOTAL_COUNT = 100_000

# E-chain constants: c[i] = round(2**28 * 10**(2**(i-24))) for i = 0..25.
# Bits 0..23 are fractional (10**(2**-24) .. 10**(1/2)); bit 24 is 10**1,
# bit 25 is 10**2 (|x| <= 2 needs two integer bits).
_CHAIN_LEN = XQ_BITS + 2


def _chain_constants() -> List[int]:
    from decimal import Decimal, getcontext

    getcontext().prec = 50
    out = []
    ten = Decimal(10)
    for i in range(_CHAIN_LEN):
        exp = Decimal(2) ** (i - XQ_BITS)
        val = ten ** exp
        out.append(int((val * (1 << E_BITS)).to_integral_value(rounding="ROUND_HALF_EVEN")))
    return out


CHAIN = _chain_constants()


# ---------------------------------------------------------------------------
# Pure-Python / numpy reference (the spec oracle — exact integer math).
# These run at ENCODE time and in tests; nothing here touches the device.
# ---------------------------------------------------------------------------


def xq_py(free_num: int, cap: int) -> int:
    """x_q = floor(free_num * 2**24 / cap), clamped to [-2, 1] in Q24.

    The +1 upper clamp keeps every Q27 exponential <= 10*2**27 (int32);
    free fractions above 1 cannot occur for real state (used,res >= 0),
    and an eviction factor above 10 would mean evicting more than 100%
    of effective capacity in one alloc."""
    q = (int(free_num) << XQ_BITS) // max(int(cap), 1)
    return max(-2 * XQ_ONE, min(XQ_ONE, q))


def exp10_fp_py(x_q: int) -> int:
    """Q28 10**x for x_q in Q24, |x_q| <= 2*2**24. Exact per the spec."""
    neg = x_q < 0
    xa = -x_q if neg else x_q
    acc = E_ONE
    for i in range(_CHAIN_LEN):
        if (xa >> i) & 1:
            acc = (acc * CHAIN[i]) >> E_BITS
    if neg:
        acc = (1 << (2 * E_BITS)) // max(acc, 1)
    return acc


def e27_py(x_q: int) -> int:
    """Q27 10**x: the Q28 chain rounded-half-up to Q27 (fits int32)."""
    return (exp10_fp_py(x_q) + 1) >> 1


def xq_np(free_num, cap):
    """Vectorized x_q (numpy int64; floor division, clamped to [-2, 1])."""
    free_num = np.asarray(free_num, np.int64)
    cap = np.maximum(np.asarray(cap, np.int64), 1)
    q = np.floor_divide(free_num << XQ_BITS, cap)
    return np.clip(q, -2 * XQ_ONE, XQ_ONE)


def exp10_fp_np(x_q):
    """Vectorized Q28 chain — bit-identical to exp10_fp_py (int64 exact)."""
    x_q = np.asarray(x_q, np.int64)
    neg = x_q < 0
    xa = np.abs(x_q)
    acc = np.full(x_q.shape, E_ONE, np.int64)
    for i in range(_CHAIN_LEN):
        bit = (xa >> i) & 1
        f = np.where(bit == 1, np.int64(CHAIN[i]), np.int64(E_ONE))
        acc = (acc * f) >> E_BITS
    recip = np.int64(1 << (2 * E_BITS)) // np.maximum(acc, 1)
    return np.where(neg, recip, acc)


def e27_np(x_q):
    return (exp10_fp_np(x_q) + 1) >> 1


def binpack_fp_from_e(ec: int, em: int) -> int:
    """Q30 BestFit-v3 from the two Q27 exponentials (runtime formula):
    clip(20 - 10**free_cpu - 10**free_mem, 0, 18)/18, as (fit*4)//9."""
    fit = 20 * E27_ONE - int(ec) - int(em)
    fit = max(0, min(18 * E27_ONE, fit))
    return (fit * 4) // 9


def e_sel_py(e_base: int, e_ask: int) -> int:
    """Selection-time Q27 exponential: running-product multiply."""
    return (int(e_base) * int(e_ask)) >> E27_BITS


def anti_fp_py(collisions: int, desired: int) -> int:
    """Q30 job anti-affinity penalty: -(collisions+1)/desired
    (rank.go:509) via the Q45-reciprocal of the (small) desired count."""
    if collisions <= 0:
        return 0
    q = (1 << RECIP_BITS) // max(int(desired), 1)
    return -(((collisions + 1) * q) >> (RECIP_BITS - TERM_BITS))


def spread_targeted_fp_py(d_hund: int, used_count: int, weight: int, sum_w: int) -> int:
    """Q30 targeted spread boost: ((d-u)/d) * (w/sum_w), d in hundredths,
    as ONE exact floor division (the only big division in the spec).

    d_hund < 0 means no target for this value (-1), d_hund == 0 is the
    zero-percent sentinel (-BIG_FP, the host's -inf boost)."""
    if d_hund == 0:
        return -BIG_FP
    if d_hund < 0:
        return -TERM_ONE
    num = (d_hund - 100 * used_count) * weight * TERM_ONE
    den = d_hund * max(sum_w, 1)
    return num // den  # Python floor division (spec: floor semantics)


def even_fp_py(current: int, min_c: int, max_c: int, has_entries: bool) -> int:
    """Q30 even-spread boost (spread.go:178 semantics) via the
    Q45-reciprocal of min_c (a count, <= 2**17)."""
    if not has_entries:
        return 0
    r = (1 << RECIP_BITS) // max(min_c, 1)
    sh = RECIP_BITS - TERM_BITS
    if current != min_c:
        if min_c == 0:
            return -TERM_ONE
        return ((min_c - current) * r) >> sh
    if min_c == max_c:
        return -TERM_ONE
    if min_c == 0:
        return TERM_ONE
    return ((max_c - min_c) * r) >> sh


def aff_fp_py(total_weight: int, sum_abs_weight: int) -> int:
    """Q30 normalized affinity score (rank.go:640): total/sum_abs, exact."""
    if sum_abs_weight == 0:
        return 0
    return (total_weight * TERM_ONE) // sum_abs_weight


def score60_py(terms_sum: int, num_terms: int) -> int:
    """Final comparable score: mean of terms scaled by 60 (exact)."""
    return terms_sum * (60 // max(1, min(5, num_terms)))


def score60_to_float(score60) -> float:
    """Display conversion (metrics only — never used in comparisons)."""
    return float(score60) / (60.0 * TERM_ONE)


# ---------------------------------------------------------------------------
# Packed-mask lanes (the roofline pass-reduction layout).
#
# The scan step's per-node boolean planes ride PACKED layouts so the step
# touches fewer [B, N] arrays per placement:
#
#   feature plane  uint8 [G, N], emitted once per eval by encode:
#                  bit FEAT_FEAS_BIT = class/constraint feasibility,
#                  bit FEAT_AFF_BIT  = affinity presence. One static plane
#                  (and one pick_g pass) instead of two.
#   presence plane uint8 [N], built per step: one bit per optional score
#                  term; num_terms = 1 + population_count(plane) replaces
#                  the chain of four astype(int32) adds.
#   count lanes    int32 [N]: two boolean count planes packed into 16-bit
#                  fields so ONE ring cumsum serves both. int64 packing
#                  would fit wider counts, but int64 prefix sums are
#                  pathologically slow on this backend — int32 lanes are
#                  free and exact while each lane's total stays below
#                  2**15 (n_pad < PACK_COUNT_MAX, asserted by callers).
#
# These helpers are the ONLY sanctioned way to cross a packed boundary
# (nomad-lint's dtype-discipline rule flags raw shift/mask unpacking and
# float promotion of packed planes). They are backend-agnostic: numpy
# arrays at encode time, jax arrays inside the jit'd step.
# ---------------------------------------------------------------------------

PACK_LANE_BITS = 16
PACK_LANE_MASK = (1 << PACK_LANE_BITS) - 1
# counts packed per lane must stay strictly below this (the high lane's
# shifted total must fit int32, and the low lane must never carry)
PACK_COUNT_MAX = 1 << (PACK_LANE_BITS - 1)

FEAT_FEAS_BIT = 0   # class/constraint feasibility
FEAT_AFF_BIT = 1    # affinity presence


def pack_feat_planes(feas, aff_present=None):
    """Pack the per-TG feasibility plane (and, when the eval carries
    affinities, the affinity-presence plane) into ONE uint8 [G, N] bit
    plane. Emitted once per eval at encode time; the cached-encode
    re-dispatch path reuses the packed plane as-is."""
    packed = feas.astype("uint8")
    if aff_present is not None and aff_present.shape[0]:
        packed = packed | (aff_present.astype("uint8") << FEAT_AFF_BIT)
    return packed


def unpack_feat_lane(packed, bit):
    """Boolean lane ``bit`` of a packed feature plane."""
    return ((packed >> bit) & 1).astype(bool)


def pack_presence_lanes(m0, m1, m2, m3):
    """Pack four boolean term-presence planes into one uint8 bit plane;
    ``1 + population_count(plane)`` is the score's num_terms."""
    return (
        m0.astype("uint8")
        | (m1.astype("uint8") << 1)
        | (m2.astype("uint8") << 2)
        | (m3.astype("uint8") << 3)
    )


def pack_count_lanes(lo_mask, hi_mask):
    """Pack two boolean count planes into one int32 plane: ``lo`` in bits
    0..15, ``hi`` in bits 16..30. Prefix sums over the packed plane are
    exact per lane while both totals stay below PACK_COUNT_MAX: neither
    lane can carry into the other, and every ring-cumsum branch is
    lane-wise non-negative."""
    return lo_mask.astype("int32") | (hi_mask.astype("int32") << PACK_LANE_BITS)


def unpack_count_lo(packed):
    """Low 16-bit count lane of a packed (cumsummed) count plane."""
    return packed & PACK_LANE_MASK


def unpack_count_hi(packed):
    """High count lane of a packed (cumsummed) count plane."""
    return packed >> PACK_LANE_BITS
