"""Device-side preemption: vectorized eviction-set construction.

Vectorizes the reference's eviction selection (``scheduler/preemption.go``
:198 PreemptForTaskGroup, :608-660 distance metrics, :663 grouping) as an
exact integer spec in the ``tpu/intscore.py`` discipline: every runtime
operation is an int32/int64 add, multiply, shift, compare or floor
division — bit-identical on every backend — so the device scan's eviction
sets match a pure-Python evaluation of the same spec ON THE REAL CHIP.

The reference algorithm (host oracle, ``scheduler/preemption.py``):

  1. candidates = non-terminal allocs on the node, minus the placing job's
     own allocs; ELIGIBLE candidates additionally have a job and a
     priority at least PRIORITY_DELTA below the placing job's
  2. node remaining = capacity - reserved - sum(ALL candidates) (the
     reference subtracts ineligible candidates too; own-job allocs are
     invisible to the met-check)
  3. greedy: sweep priority groups ascending; within the current group
     pick argmin of distance(resources still needed, candidate) +
     max_parallel penalty (first occurrence on ties), add its resources to
     ``available``, subtract from ``needed``; stop when
     available >= original ask on (cpu, mem, disk) — ``superset`` ignores
     networks. Never met -> no preemption.
  4. second pass: re-rank the greedy set by distance vs the FRESH ask,
     DESCENDING (stable: ties keep greedy order), keep the shortest
     prefix whose resources + remaining meet the ask.

Int spec (Q16 fixed point — THE deterministic-mode spec, used by the host
``Preemptor`` when ``ctx.deterministic`` and by the device kernel, so the
two agree bit-for-bit):

  coordinate  c_d = floor((needed_d - res_d) << 16 / needed_d) when
              needed_d > 0 else 0, clamped to [-CQ_CAP, CQ_CAP]
  distance    dist = isqrt(sum_d c_d**2)      (floor integer sqrt, Q16)
  penalty     ((num_preempted + 1) - max_parallel) * 50 << 16
              when max_parallel > 0 and num_preempted >= max_parallel
  key         dist + penalty

Precision vs the reference's float64: coordinates track the real ratios
within 2**-16 relative and the floor-isqrt collapses only sub-2**-16
relative distance gaps, so orderings agree with the float64 oracle
whenever true distance gaps exceed ~1e-4 — which real resource shapes
(integer MHz/MB asks) clear by orders of magnitude. Exact ties fall to
the same first-occurrence / stable-sort tie-break in both systems.

Magnitude gates (enforced by ``encode.build_preempt_tables``; host
fallback otherwise): resources and asks <= 2**28, candidates per node
<= C_MAX, distinct (job, namespace, task_group) preemption-count groups
<= GP_MAX. The per-coordinate clamp bounds sum-of-squares below 2**62,
so the int64 isqrt is exact.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

CQ_BITS = 16
CQ_ONE = 1 << CQ_BITS
# Per-coordinate clamp: |c_d| <= 2**30 keeps sum(c**2) <= 3*2**60 < 2**62.
CQ_CAP = 1 << 30
# Reference MAX_PARALLEL_PENALTY (50.0), in Q16.
PENALTY_UNIT = 50
# Reference PRIORITY_DELTA (minimum priority gap for eligibility).
PRIORITY_DELTA = 10
# Encode gates (host fallback above these).
C_MAX = 16
GP_MAX = 64
RES_CAP = 1 << 28
_BIG = 1 << 62
_I32_MAX = (1 << 31) - 1


# ---------------------------------------------------------------------------
# Pure-Python / numpy spec (the oracle — exact integer math, host-side).
# ---------------------------------------------------------------------------


def coord_q_py(needed_d: int, res_d: int) -> int:
    if needed_d <= 0:
        return 0
    q = ((int(needed_d) - int(res_d)) << CQ_BITS) // int(needed_d)
    return max(-CQ_CAP, min(CQ_CAP, q))


def dist_q_py(needed3: Sequence[int], res3: Sequence[int]) -> int:
    s = 0
    for d in range(3):
        c = coord_q_py(int(needed3[d]), int(res3[d]))
        s += c * c
    return math.isqrt(s)


def penalty_q_py(max_parallel: int, num_preempted: int) -> int:
    if max_parallel > 0 and num_preempted >= max_parallel:
        return ((num_preempted + 1) - max_parallel) * PENALTY_UNIT << CQ_BITS
    return 0


def select_eviction_set_py(
    ask3: Sequence[int],
    remaining3: Sequence[int],
    res3: Sequence[Sequence[int]],
    prio: Sequence[int],
    pen: Sequence[int],
    elig: Sequence[bool],
) -> Optional[List[int]]:
    """The full greedy + second-pass spec over flat candidate arrays in
    node insertion order. Returns candidate indices in final (second-pass)
    order, or None when the ask cannot be met.

    ``remaining3`` is the node remaining AFTER subtracting all candidates
    (the reference's node_remaining_resources at greedy start). ``pen``
    is the Q16 penalty per candidate (static across greedy rounds, like
    the reference's per-group penalty vector).

    The single loop with a per-round minimum-alive-priority restriction
    is exactly the reference's ascending priority-group sweep: a group is
    exhausted before the minimum moves to the next priority, and the
    met-check runs after every eviction.
    """
    n = len(prio)
    alive = [bool(elig[i]) for i in range(n)]
    needed = [int(a) for a in ask3]
    avail = [int(r) for r in remaining3]
    ask = [int(a) for a in ask3]
    order: List[int] = []
    met = False
    while not met and any(alive):
        pmin = min(prio[i] for i in range(n) if alive[i])
        best_key = None
        best_i = -1
        for i in range(n):
            if not alive[i] or prio[i] != pmin:
                continue
            key = dist_q_py(needed, res3[i]) + int(pen[i])
            if best_key is None or key < best_key:
                best_key = key
                best_i = i
        alive[best_i] = False
        order.append(best_i)
        for d in range(3):
            avail[d] += int(res3[best_i][d])
            needed[d] -= int(res3[best_i][d])
        met = all(avail[d] >= ask[d] for d in range(3))
    if not met:
        return None

    # Second pass: distance vs the FRESH ask, descending, stable (ties
    # keep greedy order); shortest covering prefix.
    d2 = [dist_q_py(ask, res3[i]) for i in order]
    srt = sorted(range(len(order)), key=d2.__getitem__, reverse=True)
    avail = [int(r) for r in remaining3]
    out: List[int] = []
    for k in srt:
        i = order[k]
        out.append(i)
        for d in range(3):
            avail[d] += int(res3[i][d])
        if all(avail[d] >= ask[d] for d in range(3)):
            break
    return out


# ---------------------------------------------------------------------------
# Device kernels (jnp; imported lazily by the scan step).
# ---------------------------------------------------------------------------


def isqrt_jnp(n):
    """Floor integer square root of an int64 array, 0 <= n < 2**62.

    Bit-by-bit restoring method: 31 unrolled rounds of int64
    add/shift/compare — exact on every backend (the float path would
    round differently between libm and XLA)."""
    import jax.numpy as jnp

    n = n.astype(jnp.int64)
    x = jnp.zeros_like(n)
    r = n
    for shift in range(60, -1, -2):
        bit = jnp.int64(1) << shift
        t = x + bit
        take = r >= t
        r = jnp.where(take, r - t, r)
        x = jnp.where(take, (x >> 1) + bit, x >> 1)
    return x


def coord_q_jnp(needed_d, res_d):
    """Q16 distance coordinate (int64 arrays, broadcastable)."""
    import jax.numpy as jnp

    q = jnp.floor_divide(
        (needed_d - res_d) << CQ_BITS, jnp.maximum(needed_d, 1)
    )
    return jnp.clip(jnp.where(needed_d > 0, q, 0), -CQ_CAP, CQ_CAP)


def greedy_select_jnp(ask3, res3, prio, pen, alive0, remaining0):
    """Vectorized greedy eviction sweep over every node at once.

    ask3 [3] int64, res3 [N, C, 3] int64, prio [N, C] int32,
    pen [N, C] int64, alive0 [N, C] bool (eligible and not yet evicted),
    remaining0 [N, 3] int64 (per-node remaining after subtracting all
    candidates). Returns (sel_ord [N, C] int32: greedy round that took
    the slot or -1, met [N] bool).

    The loop unrolls C rounds (C <= C_MAX by the encode gate); every
    round is elementwise + row-reduce over [N, C] — no gathers, matching
    the scan-body discipline of ``engine._make_step``."""
    import jax.numpy as jnp

    n_pad, c_w = res3.shape[0], res3.shape[1]
    alive = alive0
    needed = jnp.broadcast_to(ask3[None, :], (n_pad, 3)).astype(jnp.int64)
    avail = remaining0.astype(jnp.int64)
    met = jnp.zeros(n_pad, bool)
    sel_ord = jnp.full((n_pad, c_w), -1, jnp.int32)
    for t in range(c_w):
        active = (~met) & jnp.any(alive, axis=1)
        pmin = jnp.min(jnp.where(alive, prio, _I32_MAX), axis=1)
        cand = alive & (prio == pmin[:, None])
        q = coord_q_jnp(needed[:, None, :], res3)  # [N, C, 3]
        key = isqrt_jnp(jnp.sum(q * q, axis=-1)) + pen
        key = jnp.where(cand, key, _BIG)
        kmin = jnp.min(key, axis=1)
        is_min = cand & (key == kmin[:, None])
        # first occurrence on ties (the reference's strict-< argmin scan)
        first = is_min & (jnp.cumsum(is_min.astype(jnp.int32), axis=1) == 1)
        take = first & active[:, None]
        sel_ord = jnp.where(take, jnp.int32(t), sel_ord)
        freed = jnp.sum(jnp.where(take[:, :, None], res3, 0), axis=1)
        avail = avail + freed
        needed = needed - freed
        alive = alive & ~take
        did = jnp.any(take, axis=1)
        met = met | (did & jnp.all(avail >= ask3[None, :], axis=1))
    return sel_ord, met


def second_pass_jnp(ask3, res3_ch, sel_ord_ch, remaining_ch):
    """Second-pass superset filter for ONE node's greedy set ([C]-shaped:
    runs on the chosen node's extracted row, off the hot [N] axis).

    Returns (keep [C] bool, rank [C] int32): final eviction order is
    ascending rank over kept slots — distance vs the fresh ask
    descending, ties in greedy order (the reference's stable
    reverse-sort)."""
    import jax.numpy as jnp

    selected = sel_ord_ch >= 0
    q = coord_q_jnp(ask3[None, :].astype(jnp.int64), res3_ch)
    d2 = isqrt_jnp(jnp.sum(q * q, axis=-1))  # [C]
    # before(c', c): c' sorts ahead of c — larger distance, or equal
    # distance and earlier greedy round. (d2, greedy round) is unique
    # per selected slot, so ranks are a permutation.
    before = (d2[None, :] > d2[:, None]) | (
        (d2[None, :] == d2[:, None]) & (sel_ord_ch[None, :] < sel_ord_ch[:, None])
    )
    rank = jnp.sum(
        (before & selected[None, :]).astype(jnp.int32), axis=1
    )
    rank = jnp.where(selected, rank, jnp.int32(_I32_MAX))
    prefix = selected[None, :] & (rank[None, :] <= rank[:, None])
    cum = jnp.sum(jnp.where(prefix[:, :, None], res3_ch[None, :, :], 0), axis=1)
    met_c = jnp.all(remaining_ch[None, :] + cum >= ask3[None, :], axis=1)
    first_met = jnp.min(jnp.where(selected & met_c, rank, _I32_MAX))
    keep = selected & (rank <= first_met)
    return keep, rank
