"""nomad-trace: always-on, low-overhead eval-lifecycle observability.

Pieces (ISSUE 4 tentpole + ISSUE 12 flight recorder + ISSUE 15
cross-process tracing):

  lifecycle    per-delivery eval trace records stamped at broker enqueue
               -> dequeue -> scheduler invoke (host/device path, OCC
               attempt) -> plan submit -> apply -> ack/nack, with
               tail-latency gauges
  watchdog     leader-side liveness monitor: dumps broker stats,
               per-worker current spans, thread stacks and the last
               flight frames when placement throughput flatlines while
               evals are in flight
  flight       continuous flight recorder: a leader-owned sampler that
               snapshots gauges + direct probes into a bounded ring
               (optional JSONL spill) every ~250ms
  attribution  critical-path engine: joins lifecycle + pipeline spans
               into a ranked per-component bottleneck_report() with a
               coverage self-check; stitched_report() extends it across
               processes (rpc_wait / forward_hop / follower_lag)
  context      cross-process TraceContext (trace_id/span_id/parent_id)
               carried in the RPC envelope + Evaluation payloads, with
               a per-process bounded span ring drained by Trace.Export
  stitch       collector merging N processes' span rings into per-eval
               span trees, estimating per-process clock offset from
               client/server span pairs
  (phases)     wall-clock phase attribution lives in utils/phases.py;
               this package consumes it for the coverage self-check

The reference scatters the same signals across per-call timers
(nomad/worker.go:245 invoke_scheduler, nomad/plan_apply.go:185/369/400);
here they are joined per evaluation so a stalled eval is a queryable
record, not a needle across counters.
"""
from . import attribution, context, lifecycle, stitch
from .context import TraceContext
from .flight import FlightRecorder, install_server_probes
from .watchdog import LivenessWatchdog

__all__ = [
    "attribution",
    "context",
    "lifecycle",
    "stitch",
    "TraceContext",
    "FlightRecorder",
    "install_server_probes",
    "LivenessWatchdog",
]
