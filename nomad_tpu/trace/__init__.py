"""nomad-trace: always-on, low-overhead eval-lifecycle observability.

Three pieces (ISSUE 4 tentpole):

  lifecycle  per-delivery eval trace records stamped at broker enqueue ->
             dequeue -> scheduler invoke (host/device path, OCC attempt) ->
             plan submit -> apply -> ack/nack, with tail-latency gauges
  watchdog   leader-side liveness monitor: dumps broker stats, per-worker
             current spans and thread stacks when placement throughput
             flatlines while evals are in flight
  (phases)   wall-clock phase attribution lives in utils/phases.py; this
             package consumes it for the coverage self-check

The reference scatters the same signals across per-call timers
(nomad/worker.go:245 invoke_scheduler, nomad/plan_apply.go:185/369/400);
here they are joined per evaluation so a stalled eval is a queryable
record, not a needle across counters.
"""
from . import lifecycle
from .watchdog import LivenessWatchdog

__all__ = ["lifecycle", "LivenessWatchdog"]
