"""Critical-path attribution: turn spans into a ranked bottleneck ledger.

The lifecycle layer records WHAT happened (per-eval stamps, per-wave
pipeline stage spans, aux spans for ``wait_min_index`` and ``raft_fsm``)
but not WHY a run was slow. This module joins those spans into an
exclusive wall-clock decomposition of the makespan and emits
``bottleneck_report()``: "wait_min_index: 41% of makespan; broker
dequeue idle: 22%; ...".

The decomposition is a greedy exclusive claim in a fixed precedence
order (work stages before waits, waits before idle): each instant of
the makespan is attributed to the HIGHEST-precedence component active
at that instant. That answers "what was the system doing" the way a
profiler's self-time does — an eval sitting in the broker queue while
the device is mid-dispatch is pipelining, not a bottleneck; the same
queue time with nothing else running is. Components claim only once, so
the entries sum to at most the makespan and

    coverage = attributed_time / makespan

is a self-check on the span set itself: coverage < 0.9 means the
instrumentation lost track of what the system was doing and the report
says so instead of ranking garbage.

All interval math is on the lifecycle clock (``time.monotonic``).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import lifecycle

#: claim order: real work first, then ordered waits, then idle. Renaming
#: or reordering changes report semantics — tests pin this.
PRECEDENCE: Tuple[str, ...] = (
    "encode",          # pipeline stage: dense-plan encode
    "dispatch",        # pipeline stage: device dispatch
    "evaluate",        # pipeline stage: scheduler evaluate
    "commit",          # pipeline stage: applier commit
    "raft_fsm",        # aux span: raft apply + FSM
    "invoke",          # scheduler think-time not covered by stage spans
    "wait_min_index",  # aux span: worker blocked on SnapshotMinIndex
    "commit_wait",     # plan submitted, waiting for the applier
    "finalize",        # applied, waiting for ack bookkeeping
    "invoke_wait",     # dequeued, waiting for a scheduler slot
    "queue_wait",      # enqueued, waiting for a broker dequeue
    "broker_idle",     # no eval in flight at all (dequeue idle)
)

COVERAGE_FLOOR = 0.9

Interval = Tuple[float, float]


# -- interval algebra -------------------------------------------------------


def _merged(spans: Iterable[Interval],
            lo: Optional[float] = None,
            hi: Optional[float] = None) -> List[Interval]:
    """Sorted, coalesced, optionally clipped intervals."""
    out: List[Interval] = []
    for a, b in sorted(spans):
        if lo is not None:
            a = max(a, lo)
        if hi is not None:
            b = min(b, hi)
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _length(merged: Sequence[Interval]) -> float:
    return sum(b - a for a, b in merged)


def _subtract(merged: Sequence[Interval],
              claimed: Sequence[Interval]) -> List[Interval]:
    """``merged`` minus ``claimed`` (both sorted+coalesced)."""
    out: List[Interval] = []
    j = 0
    for a, b in merged:
        cur = a
        while j < len(claimed) and claimed[j][1] <= cur:
            j += 1
        k = j
        while k < len(claimed) and claimed[k][0] < b:
            ca, cb = claimed[k]
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
            k += 1
        if cur < b:
            out.append((cur, b))
    return out


def _complement(merged: Sequence[Interval], lo: float, hi: float) -> List[Interval]:
    return _subtract([(lo, hi)], _merged(merged))


# -- component extraction ---------------------------------------------------


def _record_component_spans(records: Sequence[Dict[str, object]],
                            now: float) -> Dict[str, List[Interval]]:
    """Per-component raw intervals from lifecycle records. Open-ended
    segments (eval still in flight) extend to ``now``."""
    comps: Dict[str, List[Interval]] = {
        "queue_wait": [], "invoke_wait": [], "invoke": [],
        "commit_wait": [], "finalize": [],
    }
    for r in records:
        enq = r.get("enqueue_t")
        if enq is None:
            continue
        end = r.get("end_t") or now
        deq = r.get("dequeue_t")
        inv0 = r.get("invoke_start_t")
        inv1 = r.get("invoke_end_t")
        sub = r.get("submit_t")
        app = r.get("apply_t")
        comps["queue_wait"].append((enq, deq if deq is not None else end))
        if deq is not None:
            comps["invoke_wait"].append(
                (deq, inv0 if inv0 is not None else end))
        if inv0 is not None:
            comps["invoke"].append((inv0, inv1 if inv1 is not None else end))
        if sub is not None:
            comps["commit_wait"].append((sub, app if app is not None else end))
        if app is not None:
            comps["finalize"].append((app, end))
    return comps


def _wave_windows(records: Sequence[Dict[str, object]],
                  now: float) -> List[Interval]:
    return _merged(
        (r["enqueue_t"], r.get("end_t") or now)
        for r in records if r.get("enqueue_t") is not None
    )


# -- the decomposition ------------------------------------------------------


def critical_path(records: Optional[Sequence[Dict[str, object]]] = None,
                  spans: Optional[Sequence[Tuple[str, str, float, float]]] = None,
                  now: Optional[float] = None) -> Dict[str, object]:
    """Exclusive per-component wall-clock decomposition of the makespan.

    ``records``/``spans`` default to the live lifecycle tables; tests
    pass synthetic sets. Returns makespan bounds, per-component claimed
    seconds (precedence order) and the coverage self-check.
    """
    if records is None:
        records = lifecycle.raw_records()
    if spans is None:
        spans = lifecycle.pipeline_spans()
    if now is None:
        now = lifecycle.pipeline_now()

    bounds: List[float] = []
    for r in records:
        if r.get("enqueue_t") is not None:
            bounds.append(r["enqueue_t"])
            bounds.append(r.get("end_t") or now)
    for (_s, _w, a, b) in spans:
        bounds.append(a)
        bounds.append(b)
    if not bounds:
        return {"makespan_s": 0.0, "t0": None, "t1": None, "waves": 0, "components": {},
                "occ_retries": 0, "coverage": 0.0, "unattributed_s": 0.0}
    t0, t1 = min(bounds), max(bounds)
    makespan = t1 - t0
    if makespan <= 0:
        return {"makespan_s": 0.0, "t0": t0, "t1": t1, "waves": 0, "components": {},
                "occ_retries": 0, "coverage": 0.0, "unattributed_s": 0.0}

    comp_spans = _record_component_spans(records, now)
    occ_retries = sum(1 for r in records if r.get("outcome") == "nack")
    for stage, _wave, a, b in spans:
        comp_spans.setdefault(stage, []).append((a, b))
    comp_spans["broker_idle"] = _complement(_wave_windows(records, now), t0, t1)

    order = list(PRECEDENCE) + sorted(set(comp_spans) - set(PRECEDENCE))
    claimed: List[Interval] = []
    components: Dict[str, float] = {}
    for name in order:
        raw = comp_spans.get(name)
        if not raw:
            continue
        merged = _merged(raw, t0, t1)
        exclusive = _subtract(merged, claimed)
        seconds = _length(exclusive)
        if seconds > 0:
            components[name] = seconds
        claimed = _merged(claimed + exclusive)
    attributed = _length(claimed)
    return {
        "makespan_s": round(makespan, 6),
        "t0": t0,
        "t1": t1,
        "waves": len(_wave_windows(records, now)),
        "components": {k: round(v, 6) for k, v in components.items()},
        "occ_retries": occ_retries,
        "coverage": round(attributed / makespan, 4),
        "unattributed_s": round(makespan - attributed, 6),
    }


def bottleneck_report(records: Optional[Sequence[Dict[str, object]]] = None,
                      spans: Optional[Sequence[Tuple[str, str, float, float]]] = None,
                      now: Optional[float] = None,
                      top_n: int = 0) -> Dict[str, object]:
    """The ranked wall-clock ledger. ``entries`` are sorted by claimed
    seconds (ties broken by name — deterministic for equal span sets);
    ``top`` is the one-line headline ("wait_min_index: 41% of makespan").
    ``coverage_ok`` is the >=0.9 self-check: when it fails the top line
    says the instrumentation lost coverage instead of naming a stage.
    """
    cp = critical_path(records, spans, now)
    makespan = cp["makespan_s"]
    entries = [
        {
            "component": name,
            "seconds": seconds,
            "share": round(seconds / makespan, 4) if makespan else 0.0,
        }
        for name, seconds in cp["components"].items()
    ]
    entries.sort(key=lambda e: (-e["seconds"], e["component"]))
    if top_n > 0:
        entries = entries[:top_n]
    coverage_ok = cp["coverage"] >= COVERAGE_FLOOR
    if not entries:
        top = "no spans recorded"
    elif not coverage_ok:
        top = (f"coverage {cp['coverage']:.0%} below "
               f"{COVERAGE_FLOOR:.0%} floor: span set incomplete")
    else:
        lead = entries[0]
        top = f"{lead['component']}: {lead['share']:.0%} of makespan"
    return {
        "makespan_s": makespan,
        "waves": cp["waves"],
        "occ_retries": cp["occ_retries"],
        "coverage": cp["coverage"],
        "coverage_ok": coverage_ok,
        "unattributed_s": cp["unattributed_s"],
        "entries": entries,
        "top": top,
    }


def format_report(report: Dict[str, object], top_n: int = 5) -> str:
    """Human one-liner for logs/records: ``wait_min_index: 41%; broker
    dequeue idle: 22%; ... (coverage 96%)``."""
    parts = [
        f"{e['component']}: {e['share']:.0%}"
        for e in report.get("entries", [])[:top_n]
    ]
    if not parts:
        return report.get("top", "no spans recorded")
    return "; ".join(parts) + f" (coverage {report.get('coverage', 0):.0%})"
