"""Critical-path attribution: turn spans into a ranked bottleneck ledger.

The lifecycle layer records WHAT happened (per-eval stamps, per-wave
pipeline stage spans, aux spans for ``wait_min_index`` and ``raft_fsm``)
but not WHY a run was slow. This module joins those spans into an
exclusive wall-clock decomposition of the makespan and emits
``bottleneck_report()``: "wait_min_index: 41% of makespan; broker
dequeue idle: 22%; ...".

The decomposition is a greedy exclusive claim in a fixed precedence
order (work stages before waits, waits before idle): each instant of
the makespan is attributed to the HIGHEST-precedence component active
at that instant. That answers "what was the system doing" the way a
profiler's self-time does — an eval sitting in the broker queue while
the device is mid-dispatch is pipelining, not a bottleneck; the same
queue time with nothing else running is. Components claim only once, so
the entries sum to at most the makespan and

    coverage = attributed_time / makespan

is a self-check on the span set itself: coverage < 0.9 means the
instrumentation lost track of what the system was doing and the report
says so instead of ranking garbage.

Idle comes in two explicitly distinguished flavors. ``idle`` is
INSTRUMENTED: scheduler workers record their coalesced empty-dequeue
periods (lifecycle.IDLE_STAGE), so dead time between waves is claimed
with direct evidence and counts toward coverage. ``broker_idle`` is the
SYNTHESIZED complement of the wave windows — no eval in flight at all —
and ranks below ``idle``. Time inside the makespan that neither work
spans, instrumented idle, nor the complement explains stays
unattributed and drags coverage below the floor: an instrumentation
hole must still fail the self-check, never get laundered as idle.

All interval math is on the lifecycle clock (``time.monotonic``).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import lifecycle

#: claim order: real work first, then ordered waits, then idle. Renaming
#: or reordering changes report semantics — tests pin this.
PRECEDENCE: Tuple[str, ...] = (
    "encode",          # pipeline stage: dense-plan encode
    "dispatch",        # pipeline stage: device dispatch
    "evaluate",        # pipeline stage: scheduler evaluate
    "commit",          # pipeline stage: applier commit
    "raft_fsm",        # aux span: raft apply + FSM
    "invoke",          # scheduler think-time not covered by stage spans
    "wait_min_index",  # aux span: worker blocked on SnapshotMinIndex
    "commit_wait",     # plan submitted, waiting for the applier
    "finalize",        # applied, waiting for ack bookkeeping
    "invoke_wait",     # dequeued, waiting for a scheduler slot
    "queue_wait",      # enqueued, waiting for a broker dequeue
    "idle",            # INSTRUMENTED worker idle: >=1 scheduler worker
                       # recorded a coalesced empty-dequeue period and no
                       # higher component was active (lifecycle.IDLE_STAGE)
    "broker_idle",     # synthesized complement: no eval in flight at all
)

COVERAGE_FLOOR = 0.9

Interval = Tuple[float, float]


# -- interval algebra -------------------------------------------------------


def _merged(spans: Iterable[Interval],
            lo: Optional[float] = None,
            hi: Optional[float] = None) -> List[Interval]:
    """Sorted, coalesced, optionally clipped intervals."""
    out: List[Interval] = []
    for a, b in sorted(spans):
        if lo is not None:
            a = max(a, lo)
        if hi is not None:
            b = min(b, hi)
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _length(merged: Sequence[Interval]) -> float:
    return sum(b - a for a, b in merged)


def _subtract(merged: Sequence[Interval],
              claimed: Sequence[Interval]) -> List[Interval]:
    """``merged`` minus ``claimed`` (both sorted+coalesced)."""
    out: List[Interval] = []
    j = 0
    for a, b in merged:
        cur = a
        while j < len(claimed) and claimed[j][1] <= cur:
            j += 1
        k = j
        while k < len(claimed) and claimed[k][0] < b:
            ca, cb = claimed[k]
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
            k += 1
        if cur < b:
            out.append((cur, b))
    return out


def _complement(merged: Sequence[Interval], lo: float, hi: float) -> List[Interval]:
    return _subtract([(lo, hi)], _merged(merged))


# -- component extraction ---------------------------------------------------


def _record_component_spans(records: Sequence[Dict[str, object]],
                            now: float) -> Dict[str, List[Interval]]:
    """Per-component raw intervals from lifecycle records. Open-ended
    segments (eval still in flight) extend to ``now``."""
    comps: Dict[str, List[Interval]] = {
        "queue_wait": [], "invoke_wait": [], "invoke": [],
        "commit_wait": [], "finalize": [],
    }
    for r in records:
        enq = r.get("enqueue_t")
        if enq is None:
            continue
        end = r.get("end_t") or now
        deq = r.get("dequeue_t")
        inv0 = r.get("invoke_start_t")
        inv1 = r.get("invoke_end_t")
        sub = r.get("submit_t")
        app = r.get("apply_t")
        comps["queue_wait"].append((enq, deq if deq is not None else end))
        if deq is not None:
            comps["invoke_wait"].append(
                (deq, inv0 if inv0 is not None else end))
        if inv0 is not None:
            comps["invoke"].append((inv0, inv1 if inv1 is not None else end))
        if sub is not None:
            comps["commit_wait"].append((sub, app if app is not None else end))
        if app is not None:
            comps["finalize"].append((app, end))
    return comps


def _wave_windows(records: Sequence[Dict[str, object]],
                  now: float) -> List[Interval]:
    return _merged(
        (r["enqueue_t"], r.get("end_t") or now)
        for r in records if r.get("enqueue_t") is not None
    )


# -- the decomposition ------------------------------------------------------


def _greedy_claim(comp_spans: Dict[str, List[Interval]],
                  precedence: Sequence[str],
                  t0: float, t1: float) -> Tuple[Dict[str, float], float]:
    """The exclusive claim loop shared by the single-process and the
    stitched decomposition: walk components in precedence order, each
    claims only the instants no higher-precedence component already
    holds. Returns (component -> seconds, total attributed seconds)."""
    order = list(precedence) + sorted(set(comp_spans) - set(precedence))
    claimed: List[Interval] = []
    components: Dict[str, float] = {}
    for name in order:
        raw = comp_spans.get(name)
        if not raw:
            continue
        merged = _merged(raw, t0, t1)
        exclusive = _subtract(merged, claimed)
        seconds = _length(exclusive)
        if seconds > 0:
            components[name] = seconds
        claimed = _merged(claimed + exclusive)
    return components, _length(claimed)


def critical_path(records: Optional[Sequence[Dict[str, object]]] = None,
                  spans: Optional[Sequence[Tuple[str, str, float, float]]] = None,
                  now: Optional[float] = None) -> Dict[str, object]:
    """Exclusive per-component wall-clock decomposition of the makespan.

    ``records``/``spans`` default to the live lifecycle tables; tests
    pass synthetic sets. Returns makespan bounds, per-component claimed
    seconds (precedence order) and the coverage self-check.
    """
    if records is None:
        records = lifecycle.raw_records()
    if spans is None:
        spans = lifecycle.pipeline_spans()
    if now is None:
        now = lifecycle.pipeline_now()

    bounds: List[float] = []
    for r in records:
        if r.get("enqueue_t") is not None:
            bounds.append(r["enqueue_t"])
            bounds.append(r.get("end_t") or now)
    for (_s, _w, a, b) in spans:
        bounds.append(a)
        bounds.append(b)
    if not bounds:
        return {"makespan_s": 0.0, "t0": None, "t1": None, "waves": 0, "components": {},
                "occ_retries": 0, "coverage": 0.0, "unattributed_s": 0.0}
    t0, t1 = min(bounds), max(bounds)
    makespan = t1 - t0
    if makespan <= 0:
        return {"makespan_s": 0.0, "t0": t0, "t1": t1, "waves": 0, "components": {},
                "occ_retries": 0, "coverage": 0.0, "unattributed_s": 0.0}

    comp_spans = _record_component_spans(records, now)
    occ_retries = sum(1 for r in records if r.get("outcome") == "nack")
    for stage, _wave, a, b in spans:
        comp_spans.setdefault(stage, []).append((a, b))
    comp_spans["broker_idle"] = _complement(_wave_windows(records, now), t0, t1)

    components, attributed = _greedy_claim(comp_spans, PRECEDENCE, t0, t1)
    return {
        "makespan_s": round(makespan, 6),
        "t0": t0,
        "t1": t1,
        "waves": len(_wave_windows(records, now)),
        "components": {k: round(v, 6) for k, v in components.items()},
        "occ_retries": occ_retries,
        "coverage": round(attributed / makespan, 4),
        "unattributed_s": round(makespan - attributed, 6),
    }


def bottleneck_report(records: Optional[Sequence[Dict[str, object]]] = None,
                      spans: Optional[Sequence[Tuple[str, str, float, float]]] = None,
                      now: Optional[float] = None,
                      top_n: int = 0) -> Dict[str, object]:
    """The ranked wall-clock ledger. ``entries`` are sorted by claimed
    seconds (ties broken by name — deterministic for equal span sets);
    ``top`` is the one-line headline ("wait_min_index: 41% of makespan").
    ``coverage_ok`` is the >=0.9 self-check: when it fails the top line
    says the instrumentation lost coverage instead of naming a stage.
    """
    cp = critical_path(records, spans, now)
    makespan = cp["makespan_s"]
    entries = [
        {
            "component": name,
            "seconds": seconds,
            "share": round(seconds / makespan, 4) if makespan else 0.0,
        }
        for name, seconds in cp["components"].items()
    ]
    entries.sort(key=lambda e: (-e["seconds"], e["component"]))
    if top_n > 0:
        entries = entries[:top_n]
    coverage_ok = cp["coverage"] >= COVERAGE_FLOOR
    if not entries:
        top = "no spans recorded"
    elif not coverage_ok:
        top = (f"coverage {cp['coverage']:.0%} below "
               f"{COVERAGE_FLOOR:.0%} floor: span set incomplete")
    else:
        lead = entries[0]
        top = f"{lead['component']}: {lead['share']:.0%} of makespan"
    return {
        "makespan_s": makespan,
        "waves": cp["waves"],
        "occ_retries": cp["occ_retries"],
        "coverage": cp["coverage"],
        "coverage_ok": coverage_ok,
        "unattributed_s": cp["unattributed_s"],
        "entries": entries,
        "top": top,
    }


def format_report(report: Dict[str, object], top_n: int = 5) -> str:
    """Human one-liner for logs/records: ``wait_min_index: 41%; broker
    dequeue idle: 22%; ... (coverage 96%)``."""
    parts = [
        f"{e['component']}: {e['share']:.0%}"
        for e in report.get("entries", [])[:top_n]
    ]
    if not parts:
        return report.get("top", "no spans recorded")
    return "; ".join(parts) + f" (coverage {report.get('coverage', 0):.0%})"


# -- stitched (cross-process) decomposition ---------------------------------
#
# Same greedy exclusive claim, but over the wall-clock spans a stitched
# multi-process collection produced (trace/stitch.py output, already
# clock-aligned). This is where wire time finally gets a name: an RPC's
# client span minus its matched server child is time on the wire or in
# the accept queue (``rpc_wait``); a client span whose PARENT is a
# server span is a layer-7 forwarding hop (``forward_hop``); a
# wait_min_index span recorded by a follower-driven worker is
# replication lag (``follower_lag``).

#: stitched claim order: eval work, then the wire, then handler time and
#: queue waits, then idle between traces.
STITCHED_PRECEDENCE: Tuple[str, ...] = (
    "invoke",          # worker-side scheduler think-time
    "forward_hop",     # client span under a server span: follower -> leader hop
    "rpc_wait",        # client span minus its matched server child: wire + accept
    "follower_lag",    # wait_min_index on a follower-driven worker
    "wait_min_index",  # wait_min_index on the leader's own worker
    "commit_wait",     # plan submitted, waiting for the applier
    "finalize",        # applied, waiting for ack bookkeeping
    "rpc_handler",     # server-side handler time not otherwise claimed
    "queue_wait",      # enqueued, waiting for a broker dequeue
    "driver",          # driver-side root spans (event.*)
    "trace_idle",      # no trace in flight at all
)

#: lifecycle/worker span name -> stitched component. ``eval.wait_min_index``
#: is resolved by role attr (follower_lag vs wait_min_index) below.
_STITCHED_SPAN_COMPONENTS: Dict[str, str] = {
    "eval.queue_wait": "queue_wait",
    "eval.invoke": "invoke",
    "eval.commit_wait": "commit_wait",
    "eval.finalize": "finalize",
}


def _stitched_component_spans(
    spans: Sequence[Dict[str, object]],
) -> Dict[str, List[Interval]]:
    """Raw per-component intervals from a clock-aligned span set."""
    comps: Dict[str, List[Interval]] = defaultdict(list)
    by_id: Dict[object, Dict[str, object]] = {}
    for s in spans:
        sid = s.get("span_id")
        if sid is not None:
            by_id[sid] = s
    # server spans matched to their client parent: subtracted from the
    # client interval so rpc_wait is the wire/accept remainder only
    server_child: Dict[object, List[Interval]] = defaultdict(list)
    for s in spans:
        if s.get("kind") == "server":
            parent = by_id.get(s.get("parent_id"))
            if parent is not None and parent.get("kind") == "client":
                server_child[s.get("parent_id")].append((s["start"], s["end"]))
    for s in spans:
        iv: Interval = (s["start"], s["end"])
        name = str(s.get("name", ""))
        kind = s.get("kind")
        if name == "eval.wait_min_index":
            role = (s.get("attrs") or {}).get("role")
            comps["follower_lag" if role == "follower" else
                  "wait_min_index"].append(iv)
        elif name in _STITCHED_SPAN_COMPONENTS:
            comps[_STITCHED_SPAN_COMPONENTS[name]].append(iv)
        elif kind == "client":
            parent = by_id.get(s.get("parent_id"))
            if parent is not None and parent.get("kind") == "server":
                # this process is relaying someone else's request:
                # the whole hop is forwarding overhead
                comps["forward_hop"].append(iv)
            else:
                kids = _merged(server_child.get(s.get("span_id"), ()))
                if kids:
                    comps["rpc_wait"].extend(_subtract([iv], kids))
                else:
                    # server never exported (killed replica / evicted
                    # ring): the whole call reads as wire time
                    comps["rpc_wait"].append(iv)
        elif kind == "server":
            comps["rpc_handler"].append(iv)
        else:
            comps["driver"].append(iv)
    return dict(comps)


def stitched_critical_path(
    spans: Sequence[Dict[str, object]],
) -> Dict[str, object]:
    """Exclusive decomposition of a stitched span set's makespan.
    ``spans`` is the flat clock-aligned list ``stitch.stitch()`` returns
    under ``"spans"``. Same shape as :func:`critical_path` plus the
    process roster."""
    valid = [
        s for s in spans
        if isinstance(s.get("start"), (int, float))
        and isinstance(s.get("end"), (int, float))
        and s["end"] >= s["start"]
    ]
    if not valid:
        return {"makespan_s": 0.0, "t0": None, "t1": None, "traces": 0,
                "processes": [], "components": {}, "coverage": 0.0,
                "unattributed_s": 0.0}
    t0 = min(s["start"] for s in valid)
    t1 = max(s["end"] for s in valid)
    makespan = t1 - t0
    traces = {str(s.get("trace_id")) for s in valid}
    processes = sorted({str(s.get("process")) for s in valid})
    if makespan <= 0:
        return {"makespan_s": 0.0, "t0": t0, "t1": t1, "traces": len(traces),
                "processes": processes, "components": {}, "coverage": 0.0,
                "unattributed_s": 0.0}
    comp_spans = _stitched_component_spans(valid)
    # idle = no trace window active at all (precedent: broker_idle)
    windows = _merged(
        (min(s["start"] for s in group), max(s["end"] for s in group))
        for group in _by_trace(valid).values()
    )
    comp_spans["trace_idle"] = _complement(windows, t0, t1)
    components, attributed = _greedy_claim(
        comp_spans, STITCHED_PRECEDENCE, t0, t1)
    return {
        "makespan_s": round(makespan, 6),
        "t0": t0,
        "t1": t1,
        "traces": len(traces),
        "processes": processes,
        "components": {k: round(v, 6) for k, v in components.items()},
        "coverage": round(attributed / makespan, 4),
        "unattributed_s": round(makespan - attributed, 6),
    }


def _by_trace(
    spans: Sequence[Dict[str, object]],
) -> Dict[str, List[Dict[str, object]]]:
    groups: Dict[str, List[Dict[str, object]]] = defaultdict(list)
    for s in spans:
        groups[str(s.get("trace_id"))].append(s)
    return groups


def stitched_report(spans: Sequence[Dict[str, object]],
                    top_n: int = 0) -> Dict[str, object]:
    """Ranked cross-process ledger; the multi-process sibling of
    :func:`bottleneck_report` with the same >=0.9 coverage self-check."""
    cp = stitched_critical_path(spans)
    makespan = cp["makespan_s"]
    entries = [
        {
            "component": name,
            "seconds": seconds,
            "share": round(seconds / makespan, 4) if makespan else 0.0,
        }
        for name, seconds in cp["components"].items()
    ]
    entries.sort(key=lambda e: (-e["seconds"], e["component"]))
    if top_n > 0:
        entries = entries[:top_n]
    coverage_ok = cp["coverage"] >= COVERAGE_FLOOR
    if not entries:
        top = "no spans recorded"
    elif not coverage_ok:
        top = (f"coverage {cp['coverage']:.0%} below "
               f"{COVERAGE_FLOOR:.0%} floor: span set incomplete")
    else:
        lead = entries[0]
        top = f"{lead['component']}: {lead['share']:.0%} of makespan"
    return {
        "makespan_s": makespan,
        "traces": cp["traces"],
        "processes": cp["processes"],
        "coverage": cp["coverage"],
        "coverage_ok": coverage_ok,
        "unattributed_s": cp["unattributed_s"],
        "entries": entries,
        "top": top,
    }
