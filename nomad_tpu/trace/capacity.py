"""Capacity-pressure observability: unblock storms as first-class gauges.

The failover module (:mod:`.failover`) answers "how long were we
headless"; this one answers "how long were we *saturated*". When demand
exceeds capacity, evals park in ``BlockedEvals``; when capacity arrives
(node registrations, alloc stops, an autoscaler step) the tracker
re-enqueues them in batches — an *unblock storm*. This module measures
that storm end-to-end:

- ``unblock_to_place_ms`` — per-eval latency from the batched broker
  re-enqueue to the eval's successful ack (the placement landed). The
  p50/p99 of this distribution is the capacity-to-placement SLO the
  chaos gate bounds.
- ``unblock_batch_size`` — size of each coalesced re-enqueue batch.
  Mean > 1 during a storm is the observable proof that per-class /
  per-node / quota triggers were deduped into batched enqueues instead
  of a per-trigger stampede.
- ``blocked_depth`` peak — high-water mark of parked evals, so a run
  can assert the depth drained back to ~0 by trace end.

Producers: ``BlockedEvals`` stamps unblocked ids and batch sizes;
``EvalBroker.ack`` closes the latency sample (a dict-lookup no-op for
evals that were never blocked); the autoscaler/replay note depth.
Numeric summary fields are published under ``nomad.blocked_evals.*``
next to the tracker's own EmitStats gauges.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

from ..utils import metric_names, metrics
from ..utils.lock_witness import module_witness_lock

_MAX_PENDING = 131072     # unblocked-but-not-yet-placed watermark cap
_MAX_SAMPLES = 131072

_lock = module_witness_lock("capacity._lock")
_pending: Dict[str, float] = {}     # eval id -> unblock stamp (monotonic)
_place_ms: List[float] = []         # closed unblock->ack latencies
_batches: List[int] = []            # per-flush coalesced batch sizes
_peak_blocked = 0
_unblocked_total = 0
_placed_total = 0


def _percentile(sorted_vals: List[float], pct: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * pct))
    return sorted_vals[idx]


def mark_unblocked(eval_ids: Iterable[str], t: Optional[float] = None) -> None:
    """Stamp a batch of evals at their re-enqueue (BlockedEvals flush)."""
    global _unblocked_total
    stamp = time.monotonic() if t is None else t
    with _lock:
        for eid in eval_ids:
            _pending[eid] = stamp
            _unblocked_total += 1
        while len(_pending) > _MAX_PENDING:
            _pending.pop(next(iter(_pending)))


def observe_placed(eval_id: str, t: Optional[float] = None) -> None:
    """Close an unblock->place sample on broker ack. Cheap no-op for the
    (overwhelmingly common) eval that was never blocked."""
    global _placed_total
    if not _pending:
        return
    with _lock:
        start = _pending.pop(eval_id, None)
        if start is None:
            return
        _placed_total += 1
        ms = ((time.monotonic() if t is None else t) - start) * 1000.0
        _place_ms.append(ms)
        del _place_ms[:-_MAX_SAMPLES]
    metrics.add_sample("nomad.blocked_evals.unblock_to_place_ms", ms)


def record_batch(size: int) -> None:
    """One coalesced re-enqueue batch left for the broker."""
    with _lock:
        _batches.append(int(size))
        del _batches[:-_MAX_SAMPLES]
    metrics.add_sample("nomad.blocked_evals.unblock_batch_size", float(size))


def note_blocked_depth(depth: int) -> None:
    """Track the blocked-eval high-water mark (stats sweeps call this)."""
    global _peak_blocked
    with _lock:
        if depth > _peak_blocked:
            _peak_blocked = depth


def peak_blocked() -> int:
    with _lock:
        return _peak_blocked


def summary() -> Dict[str, object]:
    """Storm ledger for artifacts; numeric fields double as gauges."""
    with _lock:
        lat = sorted(_place_ms)
        batches = list(_batches)
        out: Dict[str, object] = {
            "unblocked_total": _unblocked_total,
            "placed_total": _placed_total,
            "pending_unblocked": len(_pending),
            "peak_blocked": _peak_blocked,
        }
    out["unblock_to_place_ms_p50"] = _percentile(lat, 0.50)
    out["unblock_to_place_ms_p99"] = _percentile(lat, 0.99)
    out["unblock_to_place_ms_max"] = lat[-1] if lat else None
    out["unblock_batches"] = len(batches)
    out["unblock_batch_size_mean"] = (
        round(sum(batches) / len(batches), 2) if batches else None
    )
    out["unblock_batch_size_max"] = max(batches) if batches else None
    return out


def publish_gauges() -> None:
    """Publish the numeric summary under ``nomad.blocked_evals.*`` (the
    leader stats sweep and flight publisher both drive this)."""
    metric_names.publish_family("nomad.blocked_evals", summary())


def reset() -> None:
    global _peak_blocked, _unblocked_total, _placed_total
    with _lock:
        _pending.clear()
        _place_ms.clear()
        _batches.clear()
        _peak_blocked = 0
        _unblocked_total = 0
        _placed_total = 0
