"""Cross-process trace context: TraceContext propagation + span ring.

Every observability layer before this one (lifecycle records, pipeline
spans, the flight recorder) sees exactly ONE process. The production
shape is a 3-OS-process wire-raft cluster, so a request that crosses a
process boundary — eval submit → leader forward → broker dequeue →
follower worker → Plan.Submit → ack — simply vanished from the trace.
This module is the missing carrier:

  TraceContext   (trace_id, span_id, parent_id) — 16-hex ids. The
                 current context rides a ``contextvars.ContextVar`` so
                 it follows the logical request through nested calls
                 without threading an argument through every layer.
  wire format    ``inject()`` returns a plain ``{"trace_id",
                 "span_id"}`` dict; the RPC transport carries it in the
                 request envelope's ``trace`` field (rpc/codec.py) and
                 eval payloads carry it in ``Evaluation.trace_ctx`` so
                 the SAME trace_id survives the raft log and a broker
                 dequeue by a different process.
  span ring      completed spans land in a bounded deque with a
                 monotonically increasing ``seq`` — ``export(after)``
                 is a cursor drain (the ``Trace.Export`` RPC), so a
                 collector polling N replicas never double-counts and
                 eviction only loses the tail it was too slow to read.
  spill          optional crash-proof JSONL spill (append + flush per
                 span, same discipline as trace/flight.py): a
                 SIGKILLed replica still leaves its spans on disk.

Span times are WALL clock (``time.time()``) — cross-process stitching
needs a common axis, and trace/stitch.py estimates per-process clock
offset from client/server span pairs rather than trusting it. The
in-process lifecycle/pipeline layers stay on ``time.monotonic``;
:func:`wall_from_monotonic` converts when they emit spans here.
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..utils.lock_witness import module_witness_lock

#: ring capacity: at ~300B/span this bounds the table at ~20MB while
#: retaining the full span set of a chaos run when the collector drains
#: on a 1s cadence
RING_CAP = 65536


class TraceContext:
    """One node of the span tree: ids only, no timing (timing lives on
    the recorded span dicts)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def to_wire(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(trace={self.trace_id} span={self.span_id} "
                f"parent={self.parent_id})")


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("nomad_trace_ctx", default=None)

_lock = module_witness_lock("trace.context._lock")
_spans: "deque[Dict[str, object]]" = deque(maxlen=RING_CAP)
_seq = 0
_dropped = 0
_process: Optional[str] = None
_spill_fh = None


# -- process identity / spill ----------------------------------------------


def set_process(name: str) -> None:
    """Name this process in every span it records (replica node id in
    multi-process runs; defaults to ``pid:<pid>``)."""
    global _process
    with _lock:
        _process = name


def process_name() -> str:
    with _lock:
        if _process is None:
            return f"pid:{os.getpid()}"
        return _process


def configure_spill(path: Optional[str]) -> None:
    """Open (or close, with None) the crash-proof JSONL spill."""
    global _spill_fh
    with _lock:
        if _spill_fh is not None:
            try:
                _spill_fh.close()
            except OSError:
                pass
            _spill_fh = None
        if path:
            try:
                _spill_fh = open(path, "a", encoding="utf-8")
            except OSError:
                _spill_fh = None


def reset() -> None:
    """Drop all spans and state (tests)."""
    global _seq, _dropped, _process
    configure_spill(None)
    with _lock:
        _spans.clear()
        _seq = 0
        _dropped = 0
        _process = None


# -- context propagation ----------------------------------------------------


def current() -> Optional[TraceContext]:
    return _current.get()


def activate(ctx: Optional[Dict[str, str]]):
    """Enter a context carried over the wire (an RPC envelope's
    ``trace`` field, an ``Evaluation.trace_ctx``): subsequent spans in
    this thread parent to the carried span. Returns a token for
    :func:`deactivate`; None input is a no-op returning None."""
    if not ctx or not ctx.get("trace_id"):
        return None
    return _current.set(
        TraceContext(ctx["trace_id"], ctx.get("span_id") or _new_id())
    )


def deactivate(token) -> None:
    if token is not None:
        _current.reset(token)


def inject() -> Optional[Dict[str, str]]:
    """The current context as a wire dict, or None outside any trace."""
    ctx = _current.get()
    return ctx.to_wire() if ctx is not None else None


# -- span recording ---------------------------------------------------------


def wall_from_monotonic(t: float) -> float:
    """Convert a ``time.monotonic`` stamp to the wall-clock axis spans
    are recorded on."""
    return t + (time.time() - time.monotonic())


def record_span(name: str, start: float, end: float, *,
                kind: str = "internal",
                trace_id: Optional[str] = None,
                span_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                attrs: Optional[Dict[str, object]] = None) -> Dict[str, object]:
    """Record an externally-timed span (wall-clock seconds). Defaults
    parent/trace to the ambient context when ids are not given."""
    global _seq, _dropped
    ctx = _current.get()
    if trace_id is None:
        trace_id = ctx.trace_id if ctx is not None else _new_id()
    if parent_id is None and span_id is None and ctx is not None:
        parent_id = ctx.span_id
    span: Dict[str, object] = {
        "trace_id": trace_id,
        "span_id": span_id or _new_id(),
        "parent_id": parent_id,
        "name": name,
        "kind": kind,
        "process": process_name(),
        "start": start,
        "end": end,
    }
    if attrs:
        span["attrs"] = attrs
    with _lock:
        _seq += 1
        span["seq"] = _seq
        if len(_spans) == _spans.maxlen:
            _dropped += 1
        _spans.append(span)
        fh = _spill_fh
    if fh is not None:
        try:
            fh.write(json.dumps(span, sort_keys=True, default=str) + "\n")
            fh.flush()
        except (OSError, ValueError):
            pass
    return span


@contextmanager
def span(name: str, kind: str = "internal",
         ctx: Optional[TraceContext] = None,
         attrs: Optional[Dict[str, object]] = None):
    """Open a child span of ``ctx`` (default: the ambient context; a new
    root trace when there is none), make it ambient for the body, record
    it on exit. Yields the mutable attrs dict so the body can stamp
    byte counts / error tags."""
    parent = ctx if ctx is not None else _current.get()
    trace_id = parent.trace_id if parent is not None else _new_id()
    me = TraceContext(trace_id, _new_id(),
                      parent.span_id if parent is not None else None)
    token = _current.set(me)
    span_attrs: Dict[str, object] = dict(attrs) if attrs else {}
    t0 = time.time()
    try:
        yield span_attrs
    except BaseException as e:
        span_attrs.setdefault("error", type(e).__name__)
        raise
    finally:
        _current.reset(token)
        record_span(
            name, t0, time.time(), kind=kind, trace_id=me.trace_id,
            span_id=me.span_id, parent_id=me.parent_id,
            attrs=span_attrs or None,
        )


# -- read side --------------------------------------------------------------


def export(after_seq: int = 0, limit: int = RING_CAP) -> Dict[str, object]:
    """Cursor drain for the ``Trace.Export`` RPC: spans with
    ``seq > after_seq``, plus the next cursor. Bounded and idempotent —
    a collector that crashes and re-polls with its last cursor never
    double-counts."""
    with _lock:
        out = [s for s in _spans if s["seq"] > after_seq]
        next_seq = _seq
        dropped = _dropped
    if limit >= 0:
        out = out[:limit]
    if out:
        next_seq = out[-1]["seq"]
    return {
        "process": process_name(),
        "next_seq": next_seq,
        "dropped": dropped,
        "spans": out,
    }


def snapshot(recent: Optional[int] = None) -> List[Dict[str, object]]:
    with _lock:
        out = list(_spans)
    if recent is not None and recent >= 0:
        out = out[-recent:] if recent else []
    return out


def stats() -> Dict[str, object]:
    """Cheap counters for flight-recorder probes."""
    with _lock:
        return {"spans": len(_spans), "seq": _seq, "dropped": _dropped}
