"""Failover MTTR tracking: crash-recovery episodes as first-class gauges.

The eval-lifecycle spans (:mod:`.lifecycle`) answer "how slow is the
steady state"; this module answers "how long were we headless". A
failover *episode* starts when a leader dies (SIGKILL in the crash
harness, any abrupt leader loss in production) and collects:

- ``time_to_new_leader_ms`` — kill to a survivor winning a HIGHER term;
- ``time_to_first_commit_ms`` — kill to the first write committed
  through the new leader (the cluster is writable again);
- ``restart_catchup_ms`` — restart of the killed server to its applied
  index reaching the leader's snapshot boundary;
- ``snapshot_installs`` — how many InstallSnapshot rounds the rejoin
  took (>=1 means the compacted-log path was exercised).

Numeric fields are published as ``nomad.chaos.failover.<field>`` gauges
next to the ``nomad.trace.*`` family, so ``/v1/metrics`` carries
recovery MTTR the same way it carries tail latency, and
:class:`nomad_tpu.chaos.slo.SLOGate` can bound them.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils import metric_names
from ..utils.lock_witness import module_witness_lock

_MAX_EPISODES = 64

_lock = module_witness_lock("failover._lock")
_episodes: List[Dict[str, object]] = []


def _publish(fields: Dict[str, object]) -> None:
    metric_names.publish_family("nomad.chaos.failover", fields)


def record(**fields) -> Dict[str, object]:
    """Open a new failover episode with whatever is known so far (``None``
    values are dropped); returns the episode dict."""
    ep = {k: v for k, v in fields.items() if v is not None}
    with _lock:
        _episodes.append(ep)
        del _episodes[:-_MAX_EPISODES]
    _publish(ep)
    return ep


def note(**fields) -> Dict[str, object]:
    """Merge late-arriving fields into the latest episode (restart
    catch-up is measured long after the election numbers)."""
    add = {k: v for k, v in fields.items() if v is not None}
    with _lock:
        if not _episodes:
            _episodes.append({})
        ep = _episodes[-1]
        ep.update(add)
        out = dict(ep)
    _publish(add)
    return out


def latest() -> Optional[Dict[str, object]]:
    with _lock:
        return dict(_episodes[-1]) if _episodes else None


def episodes() -> List[Dict[str, object]]:
    with _lock:
        return [dict(ep) for ep in _episodes]


def summary() -> Dict[str, object]:
    with _lock:
        eps = [dict(ep) for ep in _episodes]
    return {"episodes": len(eps), "last": eps[-1] if eps else None}


def reset() -> None:
    with _lock:
        _episodes.clear()
