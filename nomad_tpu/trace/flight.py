"""Flight recorder: continuous low-rate sampling of the whole system.

Round 5's headline DNF took guesswork to diagnose because nothing
recorded the system's state over time: the device was busy 101.8s of a
600s window and the other 500s were invisible. The flight recorder is
the black box for that post-mortem — a leader-owned daemon thread that
every ``interval_s`` (~0.25s) snapshots the metrics surface plus a set
of DIRECT probes (broker depth and dequeue waiters, pipeline stage
depths and applier inflight slots, plan-queue depth, device-batcher
queue depth and dispatch-profile deltas, state-store min-index waiters,
encode-cache counters, per-replica raft/broker stats in multi-process
runs) into a timestamped frame. Frames live in a bounded ring and
optionally spill to JSONL, so a crashed or timed-out run still carries
its own telemetry tail in the bench artifact.

Disarmed, the recorder is a strict no-op: no thread, no probe calls,
no allocations beyond the constructor. The sampling thread measures its
own tick cost; ``overhead()`` reports the duty cycle so the stress gate
can assert the recorder stays under 1% of wall clock.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils import lock_witness, metrics, race_witness
from . import context, lifecycle
from ..utils.lock_witness import witness_lock

_clock = time.monotonic

#: publish the (comparatively expensive) gauge sweep every Nth tick so a
#: 250ms sampling cadence doesn't pay pipeline-summary sorting 4x/s
_PUBLISH_EVERY_S = 2.0


class FlightRecorder:
    def __init__(self, interval_s: float = 0.25, retain: int = 1024,
                 spill_path: Optional[str] = None) -> None:
        self.interval_s = float(interval_s)
        self.retain = int(retain)
        self.spill_path = spill_path or None
        self._frames: "deque[Dict[str, object]]" = deque(maxlen=max(1, self.retain))
        self._probes: Dict[str, Callable[[], object]] = {}
        self._publishers: List[Callable[[], None]] = []
        self._lock = witness_lock("flight.FlightRecorder._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._spill_fh = None
        self._seq = 0
        self._ticks = 0
        self._tick_total_s = 0.0
        self._tick_max_s = 0.0
        self._armed_t: Optional[float] = None
        self._armed_elapsed_s = 0.0  # accumulated across arm/disarm cycles
        self._last_publish_t: Optional[float] = None

    # -- wiring ----------------------------------------------------------

    def add_probe(self, name: str, fn: Callable[[], object]) -> None:
        """Register a per-tick probe. Probes must be cheap and may raise;
        a raising probe records ``{"error": ...}`` for that tick instead
        of killing the sampler."""
        with self._lock:
            self._probes[name] = fn

    def remove_probe(self, name: str) -> None:
        with self._lock:
            self._probes.pop(name, None)

    def add_publisher(self, fn: Callable[[], None]) -> None:
        """Register a gauge publisher driven from the sampling thread
        (so /v1/metrics stays fresh without the server's 10s sweep —
        bench and chaos harnesses have no agent at all)."""
        with self._lock:
            self._publishers.append(fn)

    # -- lifecycle -------------------------------------------------------

    @property
    def armed(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def arm(self) -> None:
        if self.interval_s <= 0 or self.armed:
            return
        self._stop.clear()
        with self._lock:
            self._armed_t = _clock()
            if self.spill_path and self._spill_fh is None:
                try:
                    self._spill_fh = open(self.spill_path, "a",
                                          encoding="utf-8")
                except OSError:
                    self._spill_fh = None
        self._thread = threading.Thread(
            target=self._run, name="flight-recorder", daemon=True
        )
        self._thread.start()

    def disarm(self) -> None:
        t = self._thread
        self._stop.set()
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        with self._lock:
            if self._armed_t is not None:
                self._armed_elapsed_s += _clock() - self._armed_t
                self._armed_t = None
            fh, self._spill_fh = self._spill_fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — telemetry never kills itself
                pass

    # -- sampling --------------------------------------------------------

    def tick(self) -> Dict[str, object]:
        """Take one sample (the thread's body; also callable directly —
        tests and the bench tail-flush use it synchronously)."""
        t0 = _clock()
        with self._lock:
            probes = list(self._probes.items())
            publishers = list(self._publishers)
        if publishers and (self._last_publish_t is None
                           or t0 - self._last_publish_t >= _PUBLISH_EVERY_S):
            self._last_publish_t = t0
            for pub in publishers:
                try:
                    pub()
                except Exception:  # noqa: BLE001
                    pass
        frame: Dict[str, object] = {
            "seq": self._seq,
            "t": round(t0, 4),
            "wall": round(time.time(), 3),
            "probes": {},
            "gauges": {},
            "counters": {},
        }
        for name, fn in probes:
            try:
                frame["probes"][name] = fn()
            except Exception as e:  # noqa: BLE001
                frame["probes"][name] = {"error": str(e) or type(e).__name__}
        sink = metrics.global_sink()
        try:
            frame["gauges"] = sink.gauges()
            frame["counters"] = sink.counter_sums()
        except Exception:  # noqa: BLE001
            pass
        dt = _clock() - t0
        frame["tick_ms"] = round(dt * 1000.0, 3)
        with self._lock:
            self._seq += 1
            self._ticks += 1
            self._tick_total_s += dt
            self._tick_max_s = max(self._tick_max_s, dt)
            self._frames.append(frame)
            fh = self._spill_fh
        if fh is not None:
            try:
                fh.write(json.dumps(frame, sort_keys=True, default=str) + "\n")
                fh.flush()
            except (OSError, ValueError):
                pass
        metrics.add_sample("nomad.flight.tick_ms", dt * 1000.0)
        return frame

    # -- read side -------------------------------------------------------

    def frames(self, recent: Optional[int] = None) -> List[Dict[str, object]]:
        with self._lock:
            out = list(self._frames)
        if recent is not None and recent >= 0:
            out = out[-recent:] if recent else []
        return out

    def overhead(self) -> Dict[str, object]:
        """Self-measured cost: ticks, mean/max tick time and the duty
        cycle (tick time / armed wall time) the stress gate asserts."""
        with self._lock:
            ticks = self._ticks
            total = self._tick_total_s
            mx = self._tick_max_s
            elapsed = self._armed_elapsed_s
            if self._armed_t is not None:
                elapsed += _clock() - self._armed_t
        return {
            "ticks": ticks,
            "tick_ms_avg": round(total * 1000.0 / ticks, 3) if ticks else 0.0,
            "tick_ms_max": round(mx * 1000.0, 3),
            "duty_cycle": round(total / elapsed, 5) if elapsed > 0 else 0.0,
        }

    def snapshot(self, recent: int = 64) -> Dict[str, object]:
        """The /v1/flight payload."""
        return {
            "armed": self.armed,
            "interval_s": self.interval_s,
            "retain": self.retain,
            "spill_path": self.spill_path,
            "overhead": self.overhead(),
            "frames": self.frames(recent),
        }

    def write_spill(self, path: str, recent: Optional[int] = None) -> int:
        """Dump the ring (tail-flush for bench artifacts); returns the
        number of frames written."""
        frames = self.frames(recent)
        with open(path, "w", encoding="utf-8") as fh:
            for frame in frames:
                fh.write(json.dumps(frame, sort_keys=True, default=str) + "\n")
        return len(frames)


# ---------------------------------------------------------------------------
# standard probe set for a Server
# ---------------------------------------------------------------------------


def _batcher_probe(batcher) -> Callable[[], Dict[str, object]]:
    """Queue depth plus dispatch-profile DELTAS: the profile's running
    totals tell you nothing per-frame; the tick-over-tick delta is the
    instantaneous dispatch rate."""
    last = {"dispatches": 0, "evals": 0}

    def probe() -> Dict[str, object]:
        prof = batcher.dispatch_profile()
        cur_d = int(prof.get("dispatches", 0) or 0)
        cur_e = int(prof.get("evals", 0) or 0)
        out = {
            "queue_depth": batcher.queue_depth(),
            "dispatches": cur_d,
            "dispatches_delta": cur_d - last["dispatches"],
            "evals_delta": cur_e - last["evals"],
            "compute_ms_avg": prof.get("compute_ms_avg"),
            "pad_stack_ms_avg": prof.get("pad_stack_ms_avg"),
        }
        last["dispatches"], last["evals"] = cur_d, cur_e
        return out

    return probe


def _encode_cache_probe() -> Callable[[], Dict[str, float]]:
    def probe() -> Dict[str, float]:
        sums = metrics.global_sink().counter_sums()
        prefix = "nomad.tpu_engine.encode_cache_"
        return {
            k[len(prefix):]: v for k, v in sums.items() if k.startswith(prefix)
        }

    return probe


def install_server_probes(rec: FlightRecorder, server) -> None:
    """Wire the standard probe set for one in-process Server."""
    rec.add_probe("broker", server.eval_broker.stats)
    rec.add_probe(
        "plan_queue",
        lambda: {"depth": server.plan_queue.stats().get("depth", 0)},
    )
    rec.add_probe("trace", lifecycle.quick_stats)
    # blocked-eval depth + storm counters (unblock batches, coalesced
    # dups, deferrals) so bottleneck_report-adjacent frames can attribute
    # blocked-wait time during capacity pressure
    rec.add_probe("blocked_evals", server.blocked_evals.stats)
    rec.add_probe("autoscaler", server.autoscaler.stats)
    if server.pipeline is not None:
        rec.add_probe("pipeline", server.pipeline.stats)
    if server.device_batcher is not None:
        rec.add_probe("batcher", _batcher_probe(server.device_batcher))
    rec.add_probe(
        "state",
        lambda: {
            "latest_index": server.fsm.state.latest_index,
            "min_index_waiters": server.fsm.state.min_index_waiters(),
        },
    )
    rec.add_probe("encode_cache", _encode_cache_probe())
    # nomad-watch: parked-watcher depth, wakeup/coalesce counters
    rec.add_probe("watch", server.watch_hub.stats)
    # nomad-lockdep: {"armed": 0} when disarmed; lock/edge/violation
    # counters when a witness is live (probes run OUTSIDE rec._lock, so
    # this adds no flight->witness order edge)
    rec.add_probe("lock_witness", lock_witness.stats)
    # nomad-race: same shape — {"armed": 0} or field/access/violation
    # counters when the Eraser lockset witness is live
    rec.add_probe("race_witness", race_witness.stats)
    # wire-RPC method table totals + distributed-trace ring counters.
    # Imported here, not at module top: rpc/transport imports this
    # package (trace.context) at import time, so a top-level import
    # would be circular.
    from ..rpc import transport as _transport

    rec.add_probe("rpc", _transport.rpc_stats_brief)
    rec.add_probe("xtrace", context.stats)
