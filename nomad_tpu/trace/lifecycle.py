"""Eval lifecycle spans: enqueue -> dequeue -> invoke -> submit -> apply -> ack.

Each DELIVERY ATTEMPT of an evaluation gets one ``EvalTrace`` record,
stamped in place by the broker, the worker, the scheduler (host/device
path tag) and the plan applier. Records move from an in-flight table to
a bounded ring buffer on ack/nack, so memory is O(inflight + ring) no
matter how long the server runs. A nacked eval's re-enqueue (after the
broker's compounding delay) opens a FRESH record; the broker's delivery
counter rides along as ``attempt`` — the OCC retry count.

Everything here is a dict op under one lock: cheap enough to stay on in
production, which is the point (round 5's 40x collapse was invisible
because nothing always-on recorded per-eval latency). Exported via the
``/v1/trace`` agent endpoint and as ``nomad.trace.*`` gauges on
``/v1/metrics`` (publish_gauges, called from the server's stats sweep).

Reference anchors: nomad/worker.go:245 (invoke_scheduler timing),
nomad/plan_apply.go:185,369,400 (submit/evaluate/apply timing) — the
same stages, joined per evaluation instead of aggregated per call.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..utils import metric_names, metrics
from ..utils.lock_witness import module_witness_lock
from ..utils.race_witness import tracked_deque, tracked_dict
from . import context as _xcontext

_DONE_CAP = 2048

_clock = time.monotonic


class EvalTrace:
    """One delivery attempt of one evaluation (all times ``time.monotonic``)."""

    __slots__ = (
        "eval_id", "job_id", "namespace", "type", "triggered_by", "priority",
        "attempt", "worker_id", "path",
        "enqueue_t", "dequeue_t", "invoke_start_t", "invoke_end_t",
        "submit_t", "apply_t", "end_t", "outcome", "trace_ctx",
    )

    def __init__(self, eval_id: str, job_id: str, namespace: str,
                 type_: str, triggered_by: str, priority: int,
                 enqueue_t: float) -> None:
        self.eval_id = eval_id
        self.job_id = job_id
        self.namespace = namespace
        self.type = type_
        self.triggered_by = triggered_by
        self.priority = priority
        self.attempt = 0
        self.worker_id: Optional[int] = None
        self.path: Optional[str] = None  # "host" | "device"
        self.enqueue_t = enqueue_t
        self.dequeue_t: Optional[float] = None
        self.invoke_start_t: Optional[float] = None
        self.invoke_end_t: Optional[float] = None
        self.submit_t: Optional[float] = None
        self.apply_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.outcome: Optional[str] = None  # "ack" | "nack" | "failed" | "flush"
        # carried distributed-trace context ({"trace_id","span_id"}) so
        # the record's phase spans land in the cross-process trace
        self.trace_ctx: Optional[Dict[str, str]] = None

    def total_ms(self, now: Optional[float] = None) -> float:
        end = self.end_t if self.end_t is not None else (now or _clock())
        return (end - self.enqueue_t) * 1000.0

    def to_dict(self, now: Optional[float] = None) -> Dict[str, object]:
        def ms(a: Optional[float], b: Optional[float]) -> Optional[float]:
            if a is None or b is None:
                return None
            return round((b - a) * 1000.0, 3)

        return {
            "eval_id": self.eval_id,
            "job_id": self.job_id,
            "namespace": self.namespace,
            "type": self.type,
            "triggered_by": self.triggered_by,
            "priority": self.priority,
            "attempt": self.attempt,
            "worker_id": self.worker_id,
            "path": self.path,
            "outcome": self.outcome,
            "queue_ms": ms(self.enqueue_t, self.dequeue_t),
            "invoke_wait_ms": ms(self.dequeue_t, self.invoke_start_t),
            "invoke_ms": ms(self.invoke_start_t, self.invoke_end_t),
            "submit_to_apply_ms": ms(self.submit_t, self.apply_t),
            "apply_to_end_ms": ms(self.apply_t, self.end_t),
            "total_ms": round(self.total_ms(now), 3),
        }

    def raw(self) -> Dict[str, object]:
        """Raw monotonic stamps (attribution joins these with pipeline
        spans on the same clock; to_dict() only exposes durations)."""
        return {
            "eval_id": self.eval_id,
            "type": self.type,
            "attempt": self.attempt,
            "path": self.path,
            "outcome": self.outcome,
            "enqueue_t": self.enqueue_t,
            "dequeue_t": self.dequeue_t,
            "invoke_start_t": self.invoke_start_t,
            "invoke_end_t": self.invoke_end_t,
            "submit_t": self.submit_t,
            "apply_t": self.apply_t,
            "end_t": self.end_t,
        }


_lock = module_witness_lock("lifecycle._lock")
_inflight: Dict[str, EvalTrace] = tracked_dict("lifecycle._inflight", {})
_done: "deque[EvalTrace]" = tracked_deque("lifecycle._done", maxlen=_DONE_CAP)
_counts: Dict[str, int] = {"ack": 0, "nack": 0, "failed": 0, "flush": 0}

# -- pipeline stage spans ---------------------------------------------------
#
# The eval-lifecycle pipeline (nomad_tpu/pipeline/) decomposes the leader's
# placement path into stages; each stage execution for one wave (wave id ==
# eval id) records a [start, end) span here. Unlike utils/phases (union wall
# shares, bench-window only), these are per-wave and always on: the overlap
# stress test reads raw spans to prove wave N+1's encode interleaves wave
# N's device dispatch, and the OCC-storm test counts encode spans per wave
# to prove re-dispatch skipped the encode stage.

PIPELINE_STAGES = ("encode", "dispatch", "evaluate", "commit")
_PIPE_CAP = 4096

#: aux stage name for worker dequeue idle: a scheduler worker that polls
#: the broker and finds nothing records its whole contiguous idle period
#: as ONE span (coalesced at the worker, one span per busy->idle->busy
#: transition, so 64 workers cannot flood the ring). These spans are what
#: lets attribution decompose the busy-vs-window residual explicitly
#: instead of leaving it unattributed (r05's invisible 498s).
IDLE_STAGE = "idle"

_pipe_open: Dict[str, int] = {s: 0 for s in PIPELINE_STAGES}
_pipe_done: Dict[str, "deque"] = {
    s: deque(maxlen=_PIPE_CAP) for s in PIPELINE_STAGES
}
_pipe_counts: Dict[str, int] = {s: 0 for s in PIPELINE_STAGES}
# measurement epoch: externally-timed spans (pipeline_record) are clamped
# to start no earlier than the last reset(), so a worker's idle
# accumulation that straddles a bench's warmup reset cannot drag the
# attribution makespan back into the warmup window
_pipe_epoch: float = 0.0


def reset() -> None:
    """Drop all records (tests / broker re-enable)."""
    # re-mint the rings through the factories so a race witness armed
    # after import still gets tracked tables (the import-time ones
    # predate arming)
    global _inflight, _done, _pipe_epoch
    with _lock:
        _inflight = tracked_dict("lifecycle._inflight", {})
        _done = tracked_deque("lifecycle._done", maxlen=_DONE_CAP)
        for k in _counts:
            _counts[k] = 0
        # aux stages (wait_min_index, raft_fsm, ...) registered via
        # setdefault must not survive a reset either
        for table in (_pipe_open, _pipe_done, _pipe_counts):
            for s in [k for k in table if k not in PIPELINE_STAGES]:
                del table[s]
        for s in PIPELINE_STAGES:
            _pipe_open[s] = 0
            _pipe_done[s].clear()
            _pipe_counts[s] = 0
        _pipe_epoch = _clock()


# -- stamping API (call sites: broker, worker, scheduler, applier) ---------


def on_enqueue(evaluation) -> None:
    """Eval entered a READY heap: open a record (no-op if one is already
    in flight for this id — e.g. requeue-after-ack dedup races)."""
    rec = EvalTrace(
        evaluation.id, getattr(evaluation, "job_id", ""),
        getattr(evaluation, "namespace", ""), getattr(evaluation, "type", ""),
        getattr(evaluation, "triggered_by", ""),
        getattr(evaluation, "priority", 0), _clock(),
    )
    rec.trace_ctx = getattr(evaluation, "trace_ctx", None)
    with _lock:
        _inflight.setdefault(evaluation.id, rec)


def on_dequeue(eval_id: str, attempt: int) -> None:
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None and rec.dequeue_t is None:
            rec.dequeue_t = _clock()
            rec.attempt = attempt


def on_worker(eval_id: str, worker_id: int) -> None:
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None:
            rec.worker_id = worker_id


def set_path(eval_id: str, path: str) -> None:
    """Tag which placement path the scheduler took: ``host`` (python
    iterator stack) or ``device`` (TPU batched scan)."""
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None:
            rec.path = path


def on_invoke_start(eval_id: str) -> None:
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None:
            rec.invoke_start_t = _clock()


def on_invoke_end(eval_id: str) -> None:
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None:
            rec.invoke_end_t = _clock()


def on_plan_submit(eval_id: str) -> None:
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None and rec.submit_t is None:
            rec.submit_t = _clock()


def on_apply(eval_id: str) -> None:
    """Plan applier resolved this eval's plan (committed or rejected)."""
    with _lock:
        rec = _inflight.get(eval_id)
        if rec is not None:
            rec.apply_t = _clock()


def eval_trace_ids(eval_id: str,
                   trace_ctx: Optional[Dict[str, str]]) -> Tuple[str, Optional[str]]:
    """(trace_id, parent_span_id) for an eval's spans: the carried
    context when the eval was created inside a trace, else a trace id
    derived from the eval id so an untraced eval's spans still group
    into one tree (roots, not orphans)."""
    ctx = trace_ctx or {}
    trace_id = ctx.get("trace_id") or eval_id.replace("-", "")[:16]
    return trace_id, ctx.get("span_id")


def _emit_trace_spans(rec: EvalTrace) -> None:
    """Emit the record's broker/applier-side phase spans into the
    cross-process span ring (trace/context.py). Worker-side phases
    (wait_min_index, invoke) are emitted by the worker in ITS process —
    in follower mode those stamps never reach this record at all."""
    trace_id, parent = eval_trace_ids(rec.eval_id, rec.trace_ctx)
    skew = _xcontext.wall_from_monotonic(0.0)
    attrs: Dict[str, object] = {
        "eval_id": rec.eval_id, "outcome": rec.outcome,
        "attempt": rec.attempt,
    }

    def emit(name: str, a: Optional[float], b: Optional[float]) -> None:
        if a is None or b is None or b < a:
            return
        _xcontext.record_span(
            name, a + skew, b + skew, trace_id=trace_id,
            parent_id=parent, attrs=attrs,
        )

    emit("eval.queue_wait", rec.enqueue_t,
         rec.dequeue_t if rec.dequeue_t is not None else rec.end_t)
    emit("eval.commit_wait", rec.submit_t, rec.apply_t)
    emit("eval.finalize", rec.apply_t, rec.end_t)


def _close(eval_id: str, outcome: str) -> None:
    with _lock:
        rec = _inflight.pop(eval_id, None)
        if rec is None:
            return
        rec.end_t = _clock()
        rec.outcome = outcome
        _done.append(rec)
        _counts[outcome] = _counts.get(outcome, 0) + 1
    # outside _lock: span recording takes the context ring's own lock
    _emit_trace_spans(rec)


def on_ack(eval_id: str) -> None:
    _close(eval_id, "ack")


def on_nack(eval_id: str, failed: bool = False) -> None:
    """Delivery failed. ``failed=True`` means the delivery limit was hit
    (eval routed to the failed queue — no fresh record will open)."""
    _close(eval_id, "failed" if failed else "nack")


def on_flush() -> None:
    """Broker flushed (leadership lost): close every in-flight record."""
    with _lock:
        now = _clock()
        flushed = list(_inflight.values())
        for rec in flushed:
            rec.end_t = now
            rec.outcome = "flush"
            _done.append(rec)
            _counts["flush"] += 1
        _inflight.clear()
    for rec in flushed:
        _emit_trace_spans(rec)


# -- pipeline stage stamping -----------------------------------------------


def pipeline_now() -> float:
    """The clock pipeline spans are recorded on (time.monotonic)."""
    return _clock()


@contextmanager
def pipeline_stage(stage: str, wave_id: str):
    """Record one stage execution for one wave. Depth (open count) is
    visible to gauges while the stage runs; the completed span lands in
    the per-stage ring on exit."""
    t0 = _clock()
    with _lock:
        _pipe_open[stage] = _pipe_open.get(stage, 0) + 1
    try:
        yield
    finally:
        t1 = _clock()
        with _lock:
            _pipe_open[stage] = max(0, _pipe_open.get(stage, 0) - 1)
            _pipe_done.setdefault(stage, deque(maxlen=_PIPE_CAP)).append(
                (wave_id, t0, t1)
            )
            _pipe_counts[stage] = _pipe_counts.get(stage, 0) + 1


def pipeline_record(stage: str, wave_id: str, t0: float, t1: float) -> None:
    """Record an externally-timed stage span (times from pipeline_now());
    used by the applier's waiter thread (per-payload commit times inside
    one batched raft entry) and by scheduler workers flushing coalesced
    ``idle`` dequeue-wait periods. Spans are clamped to the last reset()
    so accumulations straddling a bench's warmup reset cannot stretch the
    measured window backwards."""
    with _lock:
        t0 = max(t0, _pipe_epoch)
        if t1 <= t0:
            return
        _pipe_done.setdefault(stage, deque(maxlen=_PIPE_CAP)).append(
            (wave_id, t0, t1)
        )
        _pipe_counts[stage] = _pipe_counts.get(stage, 0) + 1


def pipeline_spans(stage: Optional[str] = None) -> List[Tuple[str, str, float, float]]:
    """Completed (stage, wave_id, t0, t1) spans, oldest first. The overlap
    and retry-reuse tests read these raw."""
    with _lock:
        stages = [stage] if stage is not None else list(_pipe_done)
        out = []
        for s in stages:
            out.extend((s, w, a, b) for (w, a, b) in _pipe_done.get(s, ()))
    out.sort(key=lambda r: r[2])
    return out


def pipeline_summary() -> Dict[str, Dict[str, object]]:
    """Per-stage depth / throughput / latency percentiles."""
    with _lock:
        snap = {
            s: (list(_pipe_done.get(s, ())), _pipe_open.get(s, 0),
                _pipe_counts.get(s, 0))
            for s in set(PIPELINE_STAGES) | set(_pipe_done)
        }
    out: Dict[str, Dict[str, object]] = {}
    for s, (spans, depth, count) in snap.items():
        lat = sorted((b - a) * 1000.0 for (_, a, b) in spans)
        out[s] = {
            "depth": depth,
            "count": count,
            "latency_ms_p50": round(_percentile(lat, 0.50), 3),
            "latency_ms_p95": round(_percentile(lat, 0.95), 3),
        }
    return out


# -- read side -------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summary() -> Dict[str, object]:
    now = _clock()
    with _lock:
        durations = sorted(r.total_ms() for r in _done)
        inflight = list(_inflight.values())
        counts = dict(_counts)
    slowest = max((r.total_ms(now) for r in inflight), default=0.0)
    return {
        "inflight": len(inflight),
        "completed": len(durations),
        "outcomes": counts,
        "eval_ms_p50": round(_percentile(durations, 0.50), 3),
        "eval_ms_p95": round(_percentile(durations, 0.95), 3),
        "eval_ms_p99": round(_percentile(durations, 0.99), 3),
        "slowest_inflight_ms": round(slowest, 3),
    }


def raw_records() -> List[Dict[str, object]]:
    """Raw-stamp dicts for every completed + in-flight record, oldest
    completion first (the attribution engine's input)."""
    with _lock:
        out = [r.raw() for r in _done]
        out.extend(r.raw() for r in _inflight.values())
    return out


def quick_stats() -> Dict[str, object]:
    """Cheap per-tick snapshot for the flight recorder: counts and open
    stage depths only — no percentile sorts (summary() and
    pipeline_summary() sort thousands of spans, too hot for a 250ms
    cadence)."""
    with _lock:
        return {
            "inflight": len(_inflight),
            "completed": len(_done),
            "outcomes": dict(_counts),
            "pipeline_depth": dict(_pipe_open),
            "pipeline_count": dict(_pipe_counts),
        }


def slowest_inflight(n: int = 5) -> List[Dict[str, object]]:
    """The n oldest in-flight records (watchdog dump material)."""
    now = _clock()
    with _lock:
        recs = sorted(_inflight.values(), key=lambda r: r.enqueue_t)[:n]
        return [r.to_dict(now) for r in recs]


def snapshot(recent: int = 64) -> Dict[str, object]:
    """The /v1/trace payload: summary + in-flight + recent completions."""
    now = _clock()
    with _lock:
        inflight = [r.to_dict(now) for r in
                    sorted(_inflight.values(), key=lambda r: r.enqueue_t)]
        done = [r.to_dict(now) for r in list(_done)[-recent:]]
    out = summary()
    out["inflight_evals"] = inflight
    out["recent"] = done
    out["pipeline"] = pipeline_summary()
    return out


def publish_gauges() -> None:
    """Push trace tail-latency gauges into the metrics sink (the server
    calls this from its periodic stats sweep, so /v1/metrics carries
    them without a /v1/trace round trip)."""
    s = summary()
    metrics.set_gauge("nomad.trace.eval_ms.p50", s["eval_ms_p50"])
    metrics.set_gauge("nomad.trace.eval_ms.p95", s["eval_ms_p95"])
    metrics.set_gauge("nomad.trace.eval_ms.p99", s["eval_ms_p99"])
    metrics.set_gauge("nomad.trace.slowest_inflight_ms",
                      s["slowest_inflight_ms"])
    metrics.set_gauge("nomad.trace.inflight", s["inflight"])
    flat: Dict[str, object] = {}
    for stage, row in pipeline_summary().items():
        flat[f"{stage}.depth"] = row["depth"]
        flat[f"{stage}.count"] = row["count"]
        flat[f"{stage}.latency_ms_p95"] = row["latency_ms_p95"]
    metric_names.publish_family("nomad.trace.pipeline", flat)
