"""Stitch N processes' span rings into per-trace span trees.

The collector side of cross-process tracing (trace/context.py): each
replica's ``Trace.Export`` RPC drains a bounded span ring; this module
merges those sets, aligns their clocks and rebuilds the tree one
logical request traced through the cluster —

    event.job_register                      (driver)
    └─ rpc.client.Job.Register              (driver)
       └─ rpc.server.Job.Register           (follower, forwarded=True)
          └─ rpc.client.Job.Register        (follower → leader hop)
             └─ rpc.server.Job.Register     (leader)
    eval.queue_wait / eval.invoke / ...     (leader + worker processes)

Clock alignment: span times are wall clock, and three OS processes'
wall clocks disagree by an unknown (possibly drifting) offset. Every
client/server span pair crossing a process boundary is an NTP-style
measurement: the server span must nest inside the client span in true
time, so

    offset(server rel client) = ((s.start - c.start) + (s.end - c.end)) / 2

cancels the symmetric part of the network delay. Per process pair we
take the median estimate over all pairs, then chain offsets through a
BFS from a reference process, so a process that only ever talks to the
leader still lands on the driver's axis.

Degradation is mandatory, never an exception: a SIGKILLed replica
exports nothing, so spans whose parent never arrived become ORPHAN
roots of a partial tree, and an unreachable process keeps offset 0.
"""
from __future__ import annotations

from collections import defaultdict, deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def merge_spans(
    span_sets: Iterable[Sequence[Dict[str, object]]],
) -> List[Dict[str, object]]:
    """Concatenate per-process span sets, dropping duplicates (a
    collector may drain overlapping windows) and sorting by
    ``(start, span_id)`` so equal inputs merge identically regardless
    of arrival order."""
    seen: set = set()
    out: List[Dict[str, object]] = []
    for spans in span_sets:
        for s in spans or ():
            key = (s.get("process"), s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
    out.sort(key=lambda s: (s.get("start", 0.0), str(s.get("span_id"))))
    return out


def _span_pairs(
    spans: Sequence[Dict[str, object]],
) -> List[Tuple[Dict[str, object], Dict[str, object]]]:
    """(client, server) pairs crossing a process boundary: the server
    span's parent is the client span, recorded by a different process."""
    by_id = {s.get("span_id"): s for s in spans}
    pairs = []
    for s in spans:
        if s.get("kind") != "server":
            continue
        c = by_id.get(s.get("parent_id"))
        if c is None or c.get("kind") != "client":
            continue
        if c.get("process") == s.get("process"):
            continue
        pairs.append((c, s))
    return pairs


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    if n % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2.0


def estimate_offsets(
    spans: Sequence[Dict[str, object]],
    reference: Optional[str] = None,
) -> Dict[str, float]:
    """Per-process clock offset RELATIVE to ``reference`` (default: the
    process recording the most spans; deterministic tie-break by name).
    ``normalized_time = span_time - offset[process]``."""
    processes = sorted({str(s.get("process")) for s in spans})
    if not processes:
        return {}
    if reference is None:
        counts: Dict[str, int] = defaultdict(int)
        for s in spans:
            counts[str(s.get("process"))] += 1
        reference = max(processes, key=lambda p: (counts[p], p))
    # edge (P, Q) -> offset estimates of Q's clock relative to P's
    edges: Dict[Tuple[str, str], List[float]] = defaultdict(list)
    for c, s in _span_pairs(spans):
        cp, sp = str(c.get("process")), str(s.get("process"))
        est = ((s["start"] - c["start"]) + (s["end"] - c["end"])) / 2.0
        edges[(cp, sp)].append(est)
        edges[(sp, cp)].append(-est)
    offsets: Dict[str, float] = {reference: 0.0}
    queue = deque([reference])
    while queue:
        p = queue.popleft()
        for (a, b), ests in edges.items():
            if a != p or b in offsets:
                continue
            offsets[b] = offsets[p] + _median(ests)
            queue.append(b)
    # unreachable processes (no RPC pair touches them): trust their
    # wall clock rather than dropping their spans
    for p in processes:
        offsets.setdefault(p, 0.0)
    return offsets


def normalize(
    spans: Sequence[Dict[str, object]],
    offsets: Dict[str, float],
) -> List[Dict[str, object]]:
    """Shifted copies of ``spans`` on the reference clock axis."""
    out = []
    for s in spans:
        off = offsets.get(str(s.get("process")), 0.0)
        if off:
            s = dict(s)
            s["start"] = s["start"] - off
            s["end"] = s["end"] - off
        out.append(s)
    return out


def build_trees(spans: Sequence[Dict[str, object]]) -> List[Dict[str, object]]:
    """Per-trace span trees, oldest trace first. Spans whose parent was
    never collected (its process died, or its ring evicted the span)
    surface as ``orphan`` roots — a PARTIAL tree, never an exception."""
    by_trace: Dict[str, List[Dict[str, object]]] = defaultdict(list)
    for s in spans:
        by_trace[str(s.get("trace_id"))].append(s)
    traces: List[Dict[str, object]] = []
    for trace_id, members in by_trace.items():
        nodes = {
            s["span_id"]: {"span": s, "children": []} for s in members
        }
        roots: List[Dict[str, object]] = []
        for s in members:
            node = nodes[s["span_id"]]
            parent = s.get("parent_id")
            if parent is None:
                roots.append(node)
            elif parent in nodes and parent != s["span_id"]:
                nodes[parent]["children"].append(node)
            else:
                node["orphan"] = True
                roots.append(node)
        # a parent-pointer cycle (corrupt input) leaves nodes unreachable
        # from any root; surface them as orphans instead of losing them
        reachable: set = set()
        stack = [n["span"]["span_id"] for n in roots]
        while stack:
            sid = stack.pop()
            if sid in reachable:
                continue
            reachable.add(sid)
            stack.extend(
                c["span"]["span_id"] for c in nodes[sid]["children"]
            )
        for sid, node in nodes.items():
            if sid not in reachable:
                node["orphan"] = True
                node["children"] = []
                roots.append(node)

        def sort_key(node):
            return (node["span"].get("start", 0.0),
                    str(node["span"].get("span_id")))

        def sort_rec(node) -> None:
            node["children"].sort(key=sort_key)
            for c in node["children"]:
                sort_rec(c)

        roots.sort(key=sort_key)
        for r in roots:
            sort_rec(r)
        start = min(s["start"] for s in members)
        end = max(s["end"] for s in members)
        traces.append({
            "trace_id": trace_id,
            "start": start,
            "end": end,
            "duration_ms": round((end - start) * 1000.0, 3),
            "processes": sorted({str(s.get("process")) for s in members}),
            "spans": len(members),
            "orphans": sum(1 for r in roots if r.get("orphan")),
            "roots": roots,
        })
    traces.sort(key=lambda t: (t["start"], t["trace_id"]))
    return traces


def stitch(
    span_sets: Iterable[Sequence[Dict[str, object]]],
    recent: Optional[int] = None,
    reference: Optional[str] = None,
) -> Dict[str, object]:
    """The full collector pass: merge → clock-align → trees. This is
    the ``/v1/trace/distributed`` payload and the chaos harnesses'
    stitched-trace sample."""
    spans = merge_spans(span_sets)
    offsets = estimate_offsets(spans, reference)
    norm = normalize(spans, offsets)
    traces = build_trees(norm)
    if recent is not None and recent >= 0:
        traces = traces[-recent:] if recent else []
    return {
        "processes": sorted(offsets),
        "clock_offsets_ms": {
            p: round(off * 1000.0, 3) for p, off in sorted(offsets.items())
        },
        "span_count": len(spans),
        "trace_count": len(set(str(s.get("trace_id")) for s in spans)),
        "traces": traces,
        "spans": norm,
    }


def format_tree(trace: Dict[str, object]) -> str:
    """ASCII rendering of one stitched trace (docs / debugging)."""
    lines = [
        f"trace {trace['trace_id']} "
        f"({trace['duration_ms']}ms, "
        f"processes: {', '.join(trace['processes'])})"
    ]
    t0 = trace["start"]

    def walk(node, depth: int) -> None:
        s = node["span"]
        tag = " ORPHAN" if node.get("orphan") else ""
        lines.append(
            "  " * depth
            + f"└─ {s['name']} [{s.get('process')}] "
            + f"+{(s['start'] - t0) * 1000.0:.1f}ms "
            + f"{(s['end'] - s['start']) * 1000.0:.2f}ms{tag}"
        )
        for c in node["children"]:
            walk(c, depth + 1)

    for r in trace["roots"]:
        walk(r, 1)
    return "\n".join(lines)
