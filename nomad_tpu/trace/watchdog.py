"""Liveness watchdog: self-diagnosing dump when placement flatlines.

Round 5's failure mode was a server that LOOKED idle: 5-6 evals sat
unacked for minutes, placement throughput flat, and nothing fired. The
watchdog is the inverse of a health check — it alarms on the
combination "no placement progress" + "evals in flight", which healthy
systems never hold for long (either the broker drains or placements
land).

It is a tick function, not a thread: the leader schedules ``tick()`` on
its existing timer loop (Server._schedule_leader_task), so the watchdog
dies with leadership and costs nothing on followers. Each tick samples
the desired-run alloc count (the scheduler's output) and broker depth
(its input); when output is flat for ``stall_after`` seconds while input
is nonzero, it logs ONE dump — broker stats, per-worker current span,
the slowest in-flight eval traces, and a full thread stack dump — to the
framework logger, which the agent monitor's ring buffer captures for
``/v1/agent/monitor`` pollers. Repeat dumps are rate-limited to one per
``stall_after`` window so a long stall doesn't flood the buffer — and
deduplicated within a stall episode: the FIRST alarm gets the full dump
(thread stacks and all); while the same flatline persists, later alarms
emit one compact heartbeat line each (a chaos run's long injected stall
would otherwise fill the ring buffer with identical stack dumps). Any
progress starts a fresh episode with a fresh full dump.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, Optional

from ..utils import metrics
from . import lifecycle


class LivenessWatchdog:
    def __init__(self, server, stall_after: float = 30.0,
                 logger: Optional[logging.Logger] = None) -> None:
        self.server = server
        self.stall_after = float(stall_after)
        self.logger = logger or logging.getLogger("nomad_tpu.trace.watchdog")
        self.fired = 0
        self._last_placed: Optional[int] = None
        self._last_progress_t: Optional[float] = None
        self._dumped_at: Optional[float] = None
        # alarms emitted for the CURRENT stall episode; >0 means the full
        # dump already went out and repeats degrade to heartbeat lines
        self._episode_alarms = 0

    # -- probes ----------------------------------------------------------

    def _placed_count(self) -> Optional[int]:
        try:
            return self.server.fsm.state.count_allocs_desired_run()
        except Exception:  # noqa: BLE001 — probe must never kill the timer
            return None

    def worker_spans(self) -> list:
        spans = []
        for w in getattr(self.server, "workers", []):
            cur = getattr(w, "current", None)
            if cur is not None:
                cur = dict(cur)
                cur["busy_s"] = round(time.monotonic() - cur.pop("since"), 3)
            spans.append({"worker": getattr(w, "id", "?"), "span": cur})
        return spans

    # -- tick ------------------------------------------------------------

    def tick(self) -> bool:
        """Sample; returns True when a dump was emitted this tick."""
        now = time.monotonic()
        placed = self._placed_count()
        try:
            broker = self.server.eval_broker.stats()
        except Exception:  # noqa: BLE001
            return False
        in_flight = int(broker.get("total_unacked", 0)) \
            + int(broker.get("total_ready", 0))

        if self._last_placed is None or placed != self._last_placed:
            self._last_placed = placed
            self._last_progress_t = now
            self._dumped_at = None
            self._episode_alarms = 0
            return False
        if in_flight == 0:
            # flat but empty: nothing owed, not a stall
            self._last_progress_t = now
            self._dumped_at = None
            self._episode_alarms = 0
            return False
        stalled = now - (self._last_progress_t or now)
        metrics.set_gauge("nomad.watchdog.stalled_s", round(stalled, 1))
        if stalled < self.stall_after:
            return False
        if self._dumped_at is not None and now - self._dumped_at < self.stall_after:
            return False
        self._dumped_at = now
        self.fired += 1
        self._episode_alarms += 1
        metrics.incr_counter("nomad.watchdog.fired")
        if self._episode_alarms == 1:
            self._dump(stalled, placed, broker)
        else:
            # same flatline, dump already on record: one compact line
            metrics.incr_counter("nomad.watchdog.heartbeat")
            self.logger.warning(
                "liveness watchdog: still stalled (%.1fs flat at %s "
                "desired-run allocs, %d in flight; alarm %d of this "
                "episode, suppressing repeat dumps)",
                stalled, placed, in_flight, self._episode_alarms,
            )
        return True

    def _dump(self, stalled: float, placed: Optional[int],
              broker: Dict[str, object]) -> None:
        from ..agent.monitor import thread_dump
        from ..utils import lock_witness

        # the flight recorder's tail shows what the system was doing
        # LEADING INTO the stall, which the instantaneous probes can't
        flight = getattr(self.server, "flight", None)
        flight_tail = flight.frames(recent=8) if flight is not None else []
        # when the lock witness is armed, which thread holds which locks
        # is often the entire stall story (empty table when disarmed)
        held = lock_witness.held_snapshot()
        self.logger.warning(
            "liveness watchdog: placement flat at %s desired-run allocs "
            "for %.1fs with evals in flight\n"
            "broker stats: %s\n"
            "worker spans: %s\n"
            "slowest in-flight evals: %s\n"
            "last flight frames: %s\n"
            "witnessed held locks per thread: %s\n"
            "thread stacks:\n%s",
            placed, stalled,
            json.dumps(broker, sort_keys=True, default=str),
            json.dumps(self.worker_spans(), sort_keys=True, default=str),
            json.dumps(lifecycle.slowest_inflight(5), sort_keys=True,
                       default=str),
            json.dumps(flight_tail, sort_keys=True, default=str),
            json.dumps(held, sort_keys=True, default=str)
            if held else "(witness disarmed)",
            thread_dump(),
        )
