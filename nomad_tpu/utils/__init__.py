"""Shared utilities (reference helper/ + lib/)."""
