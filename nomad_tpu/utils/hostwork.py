"""GIL convoy guard shared by the scheduling pipeline's host phases.

Pure-Python phases (reconcile, encode, result apply, snapshot copies)
are serial under the GIL regardless of thread count; letting hundreds of
worker threads enter them at once only buys context-switch thrash — the
measured inflation is ~3x at 256+ workers. A small bound keeps a few
threads in flight (numpy sections release the GIL) while the rest park
on the semaphore, where they cost nothing.

One SHARED semaphore across phases, not one per phase: the point is to
cap the number of RUNNABLE threads in the whole process, and a worker
holds it only for bounded, non-blocking sections (never across a device
dispatch or a plan-queue wait — that would deadlock the batch gather,
which needs every co-batched worker to reach the batcher).

The permit count scales with the host: the guarded sections are
numpy/memdb-read heavy and release the GIL for most of their wall time,
so a wave of 64+ concurrent evals wants more than a handful of
concurrent encoders — r05's fixed bound of 4 made the pre-device
stages (snapshot -> reconcile -> encode) trickle into the batcher one
at a time and left the device starved between waves. Bounded at 16:
past that the pure-Python remainder convoys on the GIL again.
``NOMAD_HOST_WORK_PERMITS`` overrides for experiments.
"""
from __future__ import annotations

import os
import threading


def _permits() -> int:
    env = os.environ.get("NOMAD_HOST_WORK_PERMITS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return min(16, max(4, os.cpu_count() or 4))


HOST_WORK_PERMITS = _permits()
HOST_WORK_SEM = threading.BoundedSemaphore(HOST_WORK_PERMITS)
