"""GIL convoy guard shared by the scheduling pipeline's host phases.

Pure-Python phases (reconcile, encode, result apply, snapshot copies)
are serial under the GIL regardless of thread count; letting hundreds of
worker threads enter them at once only buys context-switch thrash — the
measured inflation is ~3x at 256+ workers. A small bound keeps a few
threads in flight (numpy sections release the GIL) while the rest park
on the semaphore, where they cost nothing.

One SHARED semaphore across phases, not one per phase: the point is to
cap the number of RUNNABLE threads in the whole process, and a worker
holds it only for bounded, non-blocking sections (never across a device
dispatch or a plan-queue wait — that would deadlock the batch gather,
which needs every co-batched worker to reach the batcher).
"""
from __future__ import annotations

import threading

HOST_WORK_SEM = threading.BoundedSemaphore(4)
