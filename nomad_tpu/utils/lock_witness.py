"""nomad-lockdep's dynamic side: an opt-in lock witness.

Production lock sites create their locks through the factories here
(``witness_lock``/``witness_rlock``), naming each lock with the SAME key
the static analyzer (``nomad_tpu/analysis/lock_order.py``) derives for
it — ``module.Class._lockname`` for instance locks, ``module._lockname``
for module-level ones. When the witness is DISARMED (the default) the
factories return plain ``threading.Lock``/``RLock`` objects: production
pays nothing, not even an isinstance check per acquisition. When ARMED
(``NOMAD_LOCK_WITNESS=1`` in the environment at import time, or
``arm()`` before the locks are constructed — mirroring the chaos
injector's arm/disarm pattern) the factories return instrumented
wrappers that record, per thread, the set of held locks and, globally,
every acquisition-order edge ``A -> B`` ("B was acquired while A was
held"). Edges are keyed by lock NAME, not instance — kernel lockdep's
lock-class semantics — so a thousand short-lived ``StateStore``
snapshots share one node and same-name nesting is treated as reentrant
rather than inverted.

On every NEW edge the witness checks the global graph for a path
``B -> ... -> A``; finding one means two threads can take the same pair
of locks in opposite orders — a potential deadlock — and the witness
fails FAST with :class:`LockOrderViolation` carrying both acquisition
stacks (this thread's, plus the stack recorded when the reverse path's
first edge was witnessed). At teardown, :func:`cross_check` compares
every witnessed edge against the static analyzer's whole-program graph:
the dynamic run validates that the static pass is a sound
over-approximation, and the static pass covers orders no test happened
to exercise.

Conditions: ``threading.Condition(self._lock)`` works unchanged on a
witness lock — the wrapper implements ``_is_owned``/``_release_save``/
``_acquire_restore`` with held-set bookkeeping, so a ``wait()`` properly
drops the lock from the thread's held set while parked.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Sequence, Set, Tuple


class LockOrderViolation(RuntimeError):
    """A lock acquisition would close a cycle in the witnessed order
    graph — i.e. some other thread has taken (part of) the same lock set
    in the opposite order."""


def _stack_summary(skip: int = 2, limit: int = 14) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-limit:])


class LockWitness:
    """Global witness state: the order graph, per-thread held stacks and
    the first-witness stack for every edge."""

    def __init__(self) -> None:
        # internal mutex — a plain lock, invisible to the witness itself
        self._mu = threading.Lock()
        # name -> set of successor names ("successor acquired while name held")
        self._graph: Dict[str, Set[str]] = {}
        # (a, b) -> (thread name, stack at first witness)
        self._edge_stacks: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # thread ident -> ordered list of held lock names (dups collapsed)
        self._held: Dict[int, List[str]] = {}
        self._thread_names: Dict[int, str] = {}
        self._names: Set[str] = set()
        self.acquisitions = 0
        self.violations = 0

    # -- bookkeeping (called from _WitnessLock) --------------------------

    def _register(self, name: str) -> None:
        with self._mu:
            self._names.add(name)

    def note_acquired(self, name: str, record_edges: bool) -> None:
        """Called AFTER the inner lock is acquired. Records edges from
        every currently-held (differently-named) lock to ``name`` and
        fails fast if any new edge closes a cycle."""
        ident = threading.get_ident()
        with self._mu:
            self.acquisitions += 1
            held = self._held.setdefault(ident, [])
            self._thread_names[ident] = threading.current_thread().name
            if name in held:
                held.append(name)  # reentrant by name: no edges
                return
            if record_edges:
                for prior in dict.fromkeys(held):
                    if prior == name:
                        continue
                    succ = self._graph.setdefault(prior, set())
                    if name in succ:
                        continue
                    cyc = self._find_path(name, prior)
                    if cyc is not None:
                        self.violations += 1
                        # raise WITHOUT registering the hold: the caller
                        # releases the inner lock before propagating
                        raise self._violation(prior, name, cyc)
                    succ.add(name)
                    self._edge_stacks[(prior, name)] = (
                        threading.current_thread().name,
                        _stack_summary(skip=3),
                    )
            held.append(name)

    def note_released(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            held = self._held.get(ident)
            if not held:
                return
            # release the most recent entry with this name (LIFO-ish; out
            # of order releases still keep the multiset right)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break
            if not held:
                self._held.pop(ident, None)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS for a path src -> ... -> dst in the edge graph."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(self._graph.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _violation(self, prior: str, name: str,
                   cycle_path: List[str]) -> LockOrderViolation:
        chain = " -> ".join(cycle_path + [cycle_path[0]]) if cycle_path else ""
        first = self._edge_stacks.get(
            (cycle_path[0], cycle_path[1]) if len(cycle_path) > 1 else (name, prior)
        )
        other = (f"reverse edge first witnessed on thread "
                 f"{first[0]!r}:\n{first[1]}" if first else
                 "reverse edge stack unavailable")
        return LockOrderViolation(
            f"lock order inversion: acquiring {name!r} while holding "
            f"{prior!r}, but the witnessed graph already orders "
            f"{chain or (name + ' -> ' + prior)}.\n"
            f"this thread {threading.current_thread().name!r}:\n"
            f"{_stack_summary(skip=4)}\n{other}"
        )

    # -- read side -------------------------------------------------------

    def edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(
                (a, b) for a, succ in self._graph.items() for b in succ
            )

    def edge_stacks(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        with self._mu:
            return dict(self._edge_stacks)

    def held_names_current(self) -> Tuple[str, ...]:
        """Lock names the CALLING thread currently holds (dups collapsed,
        acquisition order). The race witness intersects these per-field:
        a shared field's candidate lockset is the intersection of every
        accessor's held set at access time (Eraser)."""
        ident = threading.get_ident()
        with self._mu:
            return tuple(dict.fromkeys(self._held.get(ident, ())))

    def held_snapshot(self) -> Dict[str, List[str]]:
        """Thread name -> held lock names, for the watchdog's stall dump."""
        with self._mu:
            return {
                self._thread_names.get(ident, str(ident)): list(names)
                for ident, names in sorted(self._held.items())
                if names
            }

    def stats(self) -> Dict[str, object]:
        with self._mu:
            return {
                "armed": 1,
                "locks": len(self._names),
                "edges": sum(len(s) for s in self._graph.values()),
                "acquisitions": self.acquisitions,
                "violations": self.violations,
            }

    def cross_check(self, static_edges: Sequence[Tuple[str, str]]
                    ) -> List[Tuple[str, str]]:
        """Witnessed edges MISSING from the static analyzer's graph —
        each one is a real runtime order the static pass failed to see
        (an unsoundness in its call resolution). Empty list == sound."""
        allowed = set(static_edges)
        return [e for e in self.edges() if e not in allowed]


class _WitnessLock:
    """Instrumented wrapper around a Lock/RLock. Duck-types the full
    lock protocol including the private Condition hooks."""

    def __init__(self, name: str, inner, witness: LockWitness) -> None:
        self._name = name
        self._inner = inner
        self._w = witness
        witness._register(name)

    # -- core protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                # trylocks (blocking=False) can't participate in a deadlock
                # cycle by themselves — record the hold, not the order edge
                self._w.note_acquired(self._name, record_edges=blocking)
            except LockOrderViolation:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        self._w.note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return bool(inner_locked()) if inner_locked is not None else False

    # -- Condition integration -------------------------------------------
    #
    # Condition.wait() swaps the lock out via _release_save and back via
    # _acquire_restore; the held-set must follow so a parked waiter does
    # not look like a lock holder to the witness.

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        # plain Lock: Python's fallback probe would self-deadlock through
        # the wrapper; approximate with "locked at all"
        return self._inner.locked()

    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        self._w.note_released(self._name)
        return state

    def _acquire_restore(self, state) -> None:
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        # re-entry after a wait IS an acquisition: if the thread still
        # holds other locks, the order edge is real
        self._w.note_acquired(self._name, record_edges=True)

    def __repr__(self) -> str:
        return f"<witness {self._name} {self._inner!r}>"


class _LazyWitnessLock:
    """A permanent wrapper for MODULE-LEVEL locks. These are minted at
    import time, before any witness can possibly be armed, so the
    construction-time arming check the instance-lock factories use would
    leave them plain forever — every held-set the race witness reads
    would be missing them, and every module-table access would look
    unlocked. Instead this wrapper consults the active witness on each
    acquire/release: one global read per operation when disarmed, noise
    next to the dict ops these locks guard."""

    __slots__ = ("_name", "_inner", "_registered_with")

    def __init__(self, name: str, inner) -> None:
        self._name = name
        self._inner = inner
        self._registered_with: Optional[LockWitness] = None

    def _witness(self) -> Optional["LockWitness"]:
        w = _ACTIVE
        if w is not None and w is not self._registered_with:
            # benign race: double _register is an idempotent set.add
            w._register(self._name)
            self._registered_with = w
        return w

    # -- core protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            w = self._witness()
            if w is not None:
                try:
                    w.note_acquired(self._name, record_edges=blocking)
                except LockOrderViolation:
                    self._inner.release()
                    raise
        return got

    def release(self) -> None:
        self._inner.release()
        w = _ACTIVE
        if w is not None:
            # tolerant of arming mid-hold: note_released ignores names
            # the witness never saw acquired
            w.note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return bool(inner_locked()) if inner_locked is not None else False

    # -- Condition integration (see _WitnessLock) ------------------------

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        return self._inner.locked()

    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        w = _ACTIVE
        if w is not None:
            w.note_released(self._name)
        return state

    def _acquire_restore(self, state) -> None:
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        w = self._witness()
        if w is not None:
            w.note_acquired(self._name, record_edges=True)

    def __repr__(self) -> str:
        return f"<lazy-witness {self._name} {self._inner!r}>"


# -- the production-facing factories ----------------------------------------
#
# _ACTIVE is None almost always; lock creation sites pay one global read at
# CONSTRUCTION time only. Exactly one witness can be active.

_ACTIVE: Optional[LockWitness] = None
_active_mu = threading.Lock()


def arm(witness: Optional[LockWitness] = None) -> LockWitness:
    """Install a witness. Locks created BEFORE arming stay plain — arm
    before constructing the servers under test."""
    global _ACTIVE
    with _active_mu:
        if _ACTIVE is not None and witness is not None and _ACTIVE is not witness:
            raise RuntimeError("another LockWitness is already armed; disarm first")
        if _ACTIVE is None:
            _ACTIVE = witness or LockWitness()
        return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    with _active_mu:
        _ACTIVE = None


def active() -> Optional[LockWitness]:
    return _ACTIVE


def witness_lock(name: str):
    """A ``threading.Lock`` — instrumented iff a witness is armed."""
    w = _ACTIVE
    if w is None:
        return threading.Lock()
    return _WitnessLock(name, threading.Lock(), w)


def witness_rlock(name: str):
    """A ``threading.RLock`` — instrumented iff a witness is armed."""
    w = _ACTIVE
    if w is None:
        return threading.RLock()
    return _WitnessLock(name, threading.RLock(), w)


def module_witness_lock(name: str):
    """A ``threading.Lock`` for MODULE-LEVEL state: lazily instrumented,
    so a witness armed after import (the only possible order) still sees
    it. Use ``witness_lock`` for instance locks — those are constructed
    after arming and get the zero-overhead-when-disarmed wrapper."""
    return _LazyWitnessLock(name, threading.Lock())


def module_witness_rlock(name: str):
    """``module_witness_lock`` with reentrant semantics."""
    return _LazyWitnessLock(name, threading.RLock())


def witness_condition(name: str, lock=None):
    """A ``threading.Condition``. Pass the (already witness-created)
    lock it guards; with no lock, an instrumented RLock is minted under
    ``name`` so the condition's internal lock is witnessed too."""
    if lock is None:
        lock = witness_rlock(name)
    return threading.Condition(lock)


def stats() -> Dict[str, object]:
    """Flight-recorder probe: cheap, never raises."""
    w = _ACTIVE
    if w is None:
        return {"armed": 0}
    return w.stats()


def held_snapshot() -> Dict[str, List[str]]:
    """Watchdog hook: thread -> held locks when armed, else empty."""
    w = _ACTIVE
    return w.held_snapshot() if w is not None else {}


if os.environ.get("NOMAD_LOCK_WITNESS") == "1":  # pragma: no cover - env gate
    arm()
