"""Metric-name registry: every gauge/counter family in one place.

The InmemSink aggregates by exact name, so an unbounded set of names
(one per eval id, per node, per exception string...) grows the sink's
interval dicts without bound and makes ``/v1/metrics`` quadratic to
render. The ``metrics-discipline`` lint rule (nomad-lint) therefore
requires metric names at instrumentation sites to be dotted ``nomad.*``
string literals (or module constants), and requires every family —
``nomad.<family>`` — to be documented here. Dynamic names are allowed
through exactly one blessed door, :func:`publish_family`, which turns a
stats dict into per-key gauges under a registered family prefix; the
key set is bounded by construction (a stats dict's keys, a stage set),
never by workload identifiers.

Reference anchor: armon/go-metrics keeps names as compile-time label
slices (e.g. nomad/eval_broker.go:825 EmitStats); this registry is the
python-side equivalent of that greppable inventory.
"""
from __future__ import annotations

from typing import Dict, Mapping

from . import metrics

#: family prefix (``nomad.<family>``) -> what lives under it. The lint
#: rule's collect pass reads the literal keys of this dict; an
#: instrumentation site whose name falls outside every family fails the
#: tree gate until the family is documented here.
FAMILIES: Dict[str, str] = {
    "nomad.broker": "eval broker depths: total_ready/unacked/blocked, "
                    "dequeue_waiters (gauges, leader stats sweep)",
    "nomad.blocked_evals": "blocked-eval tracker: EmitStats depth gauges "
                           "(publish_family), unblock_batch_size/"
                           "unblock_to_place_ms samples, "
                           "unblock_deferred counter",
    "nomad.autoscaler": "leader autoscaler loop: blocked_depth/"
                        "nodes_added gauges, scale_up/scale_down "
                        "counters",
    "nomad.plan": "plan pipeline: queue_depth gauge; evaluate/apply/"
                  "wait_for_index samples; dense_nodes_rejected counter",
    "nomad.worker": "scheduler workers: dequeue_eval/async_handoff "
                    "counters; invoke_scheduler.<type>/wait_for_index "
                    "samples (<type> is the bounded eval-type enum)",
    "nomad.server": "server one-shots: first_job_latency_ms gauge",
    "nomad.sched": "scheduler internals: reconcile sample",
    "nomad.fsm": "state-machine apply counters: "
                 "dense_placements_committed",
    "nomad.device_batcher": "device dispatch batcher: stats gauges "
                            "(publish_family) + pad_stack/dispatch/"
                            "compute/transfer samples",
    "nomad.pipeline": "async eval-lifecycle pipeline: stats gauges "
                      "(publish_family) + acked/nacked/nack.<why>/"
                      "redispatch*/slots_exhausted/backpressure/... "
                      "counters",
    "nomad.tpu_engine": "placement kernel engine: handled/fallback/"
                        "chunk/parity/encode_cache counters + "
                        "encode/apply/device_wait samples",
    "nomad.trace": "eval-lifecycle trace gauges: eval_ms percentiles, "
                   "inflight, slowest_inflight_ms, "
                   "pipeline.<stage>.* (publish_family)",
    "nomad.chaos": "chaos harness: failover.* probe gauges "
                   "(publish_family)",
    "nomad.watchdog": "liveness watchdog: fired/heartbeat counters, "
                      "stalled_s gauge",
    "nomad.heartbeat": "client heartbeat timers: active gauge",
    "nomad.state": "state store: latest_index gauge",
    "nomad.flight": "flight recorder self-telemetry: tick_ms sample, "
                    "frames/dropped counters, duty_cycle gauge",
    "nomad.rpc": "wire RPC layer: per-method latency_ms histograms, "
                 "req_bytes/resp_bytes samples, calls/errors/not_leader "
                 "counters (family_sample/family_counter — the method "
                 "enum is bounded by bind_server's registry), inflight "
                 "gauge",
    "nomad.watch": "blocking-query watch hub: watchers gauge, "
                   "wakeups/dropped_notifies/rejected_subscribes "
                   "counters",
}


def family_of(name: str) -> str:
    """``nomad.broker.total_ready`` -> ``nomad.broker``."""
    parts = name.split(".")
    return ".".join(parts[:2])


def publish_family(prefix: str, mapping: Mapping[str, object]) -> None:
    """Publish one gauge per numeric key of ``mapping`` under a
    registered family prefix — the single blessed site for dynamic
    metric names. Non-numeric values (notes, strings, bools ride along
    in stats dicts) are skipped, not coerced."""
    if family_of(prefix) not in FAMILIES:
        raise ValueError(
            f"metric family {prefix!r} is not registered in "
            f"nomad_tpu.utils.metric_names.FAMILIES"
        )
    for key, value in mapping.items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        metrics.set_gauge(f"{prefix}.{key}", float(value))


def _require_family(prefix: str) -> None:
    if family_of(prefix) not in FAMILIES:
        raise ValueError(
            f"metric family {prefix!r} is not registered in "
            f"nomad_tpu.utils.metric_names.FAMILIES"
        )


def family_sample(prefix: str, key: str, value: float) -> None:
    """Blessed dynamic-name door for SAMPLES (publish_family only does
    gauges): one histogram/summary series per ``<prefix>.<key>`` under a
    registered family. The key set must be bounded by construction — the
    RPC layer's per-method latency tables qualify (the method enum is
    the bind_server registry), per-eval or per-node keys do not."""
    _require_family(prefix)
    metrics.add_sample(f"{prefix}.{key}", value)


def family_counter(prefix: str, key: str, value: float = 1.0) -> None:
    """Blessed dynamic-name door for COUNTERS under a registered family
    (same bounded-key contract as :func:`family_sample`)."""
    _require_family(prefix)
    metrics.incr_counter(f"{prefix}.{key}", value)
