"""In-memory telemetry (reference: armon/go-metrics InmemSink wired in
command/agent/command.go:937 setupTelemetry; surfaced at /v1/metrics
http.go:189).

Same model as the reference: fixed-duration aggregation intervals (default
10s, retain 6); counters and samples aggregate {count, sum, min, max, mean};
gauges keep the last value. Metric names are dotted strings and match the
reference's instrumentation (e.g. ``nomad.worker.invoke_scheduler.service``,
``nomad.plan.evaluate``, ``nomad.plan.apply``) so dashboards transfer.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple
from .lock_witness import module_witness_lock, witness_lock


class LogHistogram:
    """Log₂-bucketed, mergeable histogram.

    Bucket ``i`` holds values in ``(2^(MIN_EXP+i-1), 2^(MIN_EXP+i)]``;
    bucket 0 is the underflow bucket (everything ≤ 2^MIN_EXP, including
    zeros) and the last bucket is the overflow. The layout is fixed at
    the class level so two histograms — e.g. per-method RPC latency
    tables exported by different replicas — merge by elementwise count
    addition, and percentiles of the merged distribution stay exact to
    one bucket width (a factor of 2).

    Not synchronized: every embedding (``_Aggregate`` under the
    ``InmemSink`` lock, the RPC method table under the transport's
    witness lock) already serializes writers.
    """

    #: first finite upper bound is 2^MIN_EXP (≈1µs when values are ms);
    #: values above 2^MAX_EXP (~12 days in ms) land in overflow
    MIN_EXP = -10
    MAX_EXP = 30
    NBUCKETS = MAX_EXP - MIN_EXP + 2  # + underflow + overflow

    __slots__ = ("counts",)

    def __init__(self, counts: Optional[Sequence[int]] = None) -> None:
        if counts is not None:
            if len(counts) != self.NBUCKETS:
                raise ValueError(
                    f"expected {self.NBUCKETS} buckets, got {len(counts)}"
                )
            self.counts = [int(c) for c in counts]
        else:
            self.counts = [0] * self.NBUCKETS

    def add(self, v: float) -> None:
        if v <= 0 or math.isnan(v):
            self.counts[0] += 1
            return
        # frexp: v = m * 2^e with 0.5 <= m < 1, so 2^(e-1) < v <= 2^e
        e = math.frexp(v)[1]
        idx = e - self.MIN_EXP
        if idx < 0:
            idx = 0
        elif idx >= self.NBUCKETS:
            idx = self.NBUCKETS - 1
        self.counts[idx] += 1

    @classmethod
    def upper_bound(cls, idx: int) -> float:
        """Inclusive upper bound of bucket ``idx`` (+inf for overflow)."""
        if idx >= cls.NBUCKETS - 1:
            return math.inf
        return 2.0 ** (cls.MIN_EXP + idx)

    @property
    def count(self) -> int:
        return sum(self.counts)

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """In-place elementwise merge; returns self for chaining."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        return self

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (0 when
        empty). Exact to a factor of 2 — enough to rank bottlenecks."""
        total = self.count
        if total <= 0:
            return 0.0
        rank = max(1, math.ceil(q * total))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i >= self.NBUCKETS - 1:
                    return 2.0 ** (self.MAX_EXP + 1)
                return self.upper_bound(i)
        return 2.0 ** (self.MAX_EXP + 1)

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, Prometheus-style, ending
        with ``(inf, total)``."""
        out: List[Tuple[float, int]] = []
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            out.append((self.upper_bound(i), cum))
        return out

    def to_wire(self) -> List[int]:
        """Counts list for RPC export (rebuild with LogHistogram(counts))."""
        return list(self.counts)


class _Aggregate:
    __slots__ = ("count", "sum", "min", "max", "hist")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.hist = LogHistogram()

    def ingest(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.hist.add(v)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def summary(self, name: str, rate_interval: float) -> dict:
        return {
            "Name": name,
            "Count": self.count,
            "Sum": round(self.sum, 6),
            "Min": round(self.min, 6) if self.count else 0,
            "Max": round(self.max, 6) if self.count else 0,
            "Mean": round(self.mean, 6),
            "Rate": round(self.sum / rate_interval, 6) if rate_interval else 0,
            "P50": self.hist.percentile(0.50),
            "P95": self.hist.percentile(0.95),
            "P99": self.hist.percentile(0.99),
        }


class _Interval:
    def __init__(self, start: float) -> None:
        self.start = start
        self.counters: Dict[str, _Aggregate] = {}
        self.samples: Dict[str, _Aggregate] = {}
        self.gauges: Dict[str, float] = {}


class InmemSink:
    def __init__(self, interval: float = 10.0, retain: int = 6) -> None:
        self.interval = interval
        self.retain = retain
        self._lock = witness_lock("metrics.InmemSink._lock")
        self._intervals: List[_Interval] = [_Interval(time.time())]

    def _current(self) -> _Interval:
        now = time.time()
        cur = self._intervals[-1]
        if now - cur.start >= self.interval:
            cur = _Interval(now - (now % self.interval))
            self._intervals.append(cur)
            if len(self._intervals) > self.retain:
                del self._intervals[: len(self._intervals) - self.retain]
        return cur

    # -- instrumentation api ---------------------------------------------

    def incr_counter(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._current().counters.setdefault(name, _Aggregate()).ingest(value)

    def add_sample(self, name: str, value: float) -> None:
        with self._lock:
            self._current().samples.setdefault(name, _Aggregate()).ingest(value)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._current().gauges[name] = value

    def measure_since(self, name: str, start: float) -> None:
        """Record elapsed milliseconds, go-metrics MeasureSince style."""
        self.add_sample(name, (time.monotonic() - start) * 1000.0)

    # -- query api --------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        """Merged gauge dict across retained intervals — the cheap
        accessor the flight recorder samples every tick (summary()
        sorts everything; this is one dict merge under the lock)."""
        with self._lock:
            merged: Dict[str, float] = {}
            for itv in self._intervals:
                merged.update(itv.gauges)
            return merged

    def counter_sums(self) -> Dict[str, float]:
        """Current-interval counter sums, unsorted (flight-frame cheap
        accessor; deltas between frames give per-tick rates)."""
        with self._lock:
            cur = self._intervals[-1]
            return {k: round(a.sum, 6) for k, a in cur.counters.items()}

    def summary(self) -> dict:
        """Aggregated view of the most recent *complete-ish* interval,
        matching the reference's /v1/metrics InmemSink DisplayMetrics."""
        with self._lock:
            cur = self._intervals[-1]
            merged_gauges: Dict[str, float] = {}
            for itv in self._intervals:
                merged_gauges.update(itv.gauges)
            return {
                "Timestamp": time.strftime(
                    "%Y-%m-%d %H:%M:%S +0000 UTC", time.gmtime(cur.start)
                ),
                "Gauges": [
                    {"Name": k, "Value": v} for k, v in sorted(merged_gauges.items())
                ],
                "Counters": [
                    cur.counters[k].summary(k, self.interval)
                    for k in sorted(cur.counters)
                ],
                "Samples": [
                    cur.samples[k].summary(k, self.interval)
                    for k in sorted(cur.samples)
                ],
            }

    def prometheus(self) -> str:
        """Text exposition format (reference supports a prometheus sink)."""
        out: List[str] = []

        def esc(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        with self._lock:
            merged_gauges: Dict[str, float] = {}
            for itv in self._intervals:
                merged_gauges.update(itv.gauges)
            cur = self._intervals[-1]
            for k, v in sorted(merged_gauges.items()):
                out.append(f"# TYPE {esc(k)} gauge")
                out.append(f"{esc(k)} {v}")
            for k in sorted(cur.counters):
                agg = cur.counters[k]
                out.append(f"# TYPE {esc(k)} counter")
                out.append(f"{esc(k)} {agg.sum}")
            for k in sorted(cur.samples):
                agg = cur.samples[k]
                n = esc(k)
                out.append(f"# TYPE {n} histogram")
                # sparse cumulative buckets: only the occupied region of
                # the fixed log₂ layout (each line is cumulative, so a
                # sparse `le` set is still valid exposition)
                prev = 0
                for le, cum in agg.hist.buckets():
                    if math.isinf(le) or cum == 0 or cum == prev:
                        continue
                    out.append(f'{n}_bucket{{le="{le:g}"}} {cum}')
                    prev = cum
                out.append(f'{n}_bucket{{le="+Inf"}} {agg.count}')
                out.append(f"{n}_sum {agg.sum}")
                out.append(f"{n}_count {agg.count}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._intervals = [_Interval(time.time())]


class StatsdSink:
    """Push sink speaking the statsd line protocol over UDP — covers the
    reference's statsd AND statsite sinks (statsite is line-compatible),
    and with ``datadog=True`` emits DogStatsD tag suffixes (the DataDog
    sink slot, command/agent/command.go:976-1018). Fire-and-forget UDP:
    a down collector never blocks or fails the server."""

    def __init__(self, address: str, prefix: str = "",
                 datadog: bool = False, tags: Optional[Dict[str, str]] = None) -> None:
        import socket

        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.prefix = prefix
        self.datadog = datadog
        self._tag_suffix = ""
        if datadog and tags:
            pairs = ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
            self._tag_suffix = f"|#{pairs}"

    def _emit(self, name: str, value: float, kind: str) -> None:
        if self.prefix:
            name = f"{self.prefix}.{name}"
        line = f"{name}:{value:g}|{kind}{self._tag_suffix}"
        try:
            self._sock.sendto(line.encode(), self._addr)
        except OSError:
            pass  # telemetry is never load-bearing

    def incr_counter(self, name: str, value: float = 1.0) -> None:
        self._emit(name, value, "c")

    def add_sample(self, name: str, value: float) -> None:
        self._emit(name, value, "ms")

    def set_gauge(self, name: str, value: float) -> None:
        self._emit(name, value, "g")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


#: process-global sink, like go-metrics' global Default registry
_global = InmemSink()

#: external push sinks fanned out alongside the inmem sink (go-metrics
#: FanoutSink: inmem + statsd/statsite/datadog per telemetry config)
_sinks: List[object] = []
_sinks_lock = module_witness_lock("metrics._sinks_lock")


def register_sink(sink) -> None:
    with _sinks_lock:
        _sinks.append(sink)


def deregister_sink(sink) -> None:
    with _sinks_lock:
        if sink in _sinks:
            _sinks.remove(sink)
    close = getattr(sink, "close", None)
    if close is not None:
        close()


def _fanout(method: str, name: str, value: float) -> None:
    with _sinks_lock:
        sinks = list(_sinks)
    for sink in sinks:
        try:
            getattr(sink, method)(name, value)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass


def global_sink() -> InmemSink:
    return _global


def incr_counter(name: str, value: float = 1.0) -> None:
    _global.incr_counter(name, value)
    if _sinks:
        _fanout("incr_counter", name, value)


def add_sample(name: str, value: float) -> None:
    _global.add_sample(name, value)
    if _sinks:
        _fanout("add_sample", name, value)


def set_gauge(name: str, value: float) -> None:
    _global.set_gauge(name, value)
    if _sinks:
        _fanout("set_gauge", name, value)


def measure_since(name: str, start: float) -> None:
    elapsed_ms = (time.monotonic() - start) * 1000.0
    _global.add_sample(name, elapsed_ms)
    if _sinks:
        _fanout("add_sample", name, elapsed_ms)


def now() -> float:
    """Monotonic start stamp for measure_since."""
    return time.monotonic()
