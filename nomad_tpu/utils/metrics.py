"""In-memory telemetry (reference: armon/go-metrics InmemSink wired in
command/agent/command.go:937 setupTelemetry; surfaced at /v1/metrics
http.go:189).

Same model as the reference: fixed-duration aggregation intervals (default
10s, retain 6); counters and samples aggregate {count, sum, min, max, mean};
gauges keep the last value. Metric names are dotted strings and match the
reference's instrumentation (e.g. ``nomad.worker.invoke_scheduler.service``,
``nomad.plan.evaluate``, ``nomad.plan.apply``) so dashboards transfer.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional
from .lock_witness import witness_lock


class _Aggregate:
    __slots__ = ("count", "sum", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def ingest(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def summary(self, name: str, rate_interval: float) -> dict:
        return {
            "Name": name,
            "Count": self.count,
            "Sum": round(self.sum, 6),
            "Min": round(self.min, 6) if self.count else 0,
            "Max": round(self.max, 6) if self.count else 0,
            "Mean": round(self.mean, 6),
            "Rate": round(self.sum / rate_interval, 6) if rate_interval else 0,
        }


class _Interval:
    def __init__(self, start: float) -> None:
        self.start = start
        self.counters: Dict[str, _Aggregate] = {}
        self.samples: Dict[str, _Aggregate] = {}
        self.gauges: Dict[str, float] = {}


class InmemSink:
    def __init__(self, interval: float = 10.0, retain: int = 6) -> None:
        self.interval = interval
        self.retain = retain
        self._lock = witness_lock("metrics.InmemSink._lock")
        self._intervals: List[_Interval] = [_Interval(time.time())]

    def _current(self) -> _Interval:
        now = time.time()
        cur = self._intervals[-1]
        if now - cur.start >= self.interval:
            cur = _Interval(now - (now % self.interval))
            self._intervals.append(cur)
            if len(self._intervals) > self.retain:
                del self._intervals[: len(self._intervals) - self.retain]
        return cur

    # -- instrumentation api ---------------------------------------------

    def incr_counter(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._current().counters.setdefault(name, _Aggregate()).ingest(value)

    def add_sample(self, name: str, value: float) -> None:
        with self._lock:
            self._current().samples.setdefault(name, _Aggregate()).ingest(value)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._current().gauges[name] = value

    def measure_since(self, name: str, start: float) -> None:
        """Record elapsed milliseconds, go-metrics MeasureSince style."""
        self.add_sample(name, (time.monotonic() - start) * 1000.0)

    # -- query api --------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        """Merged gauge dict across retained intervals — the cheap
        accessor the flight recorder samples every tick (summary()
        sorts everything; this is one dict merge under the lock)."""
        with self._lock:
            merged: Dict[str, float] = {}
            for itv in self._intervals:
                merged.update(itv.gauges)
            return merged

    def counter_sums(self) -> Dict[str, float]:
        """Current-interval counter sums, unsorted (flight-frame cheap
        accessor; deltas between frames give per-tick rates)."""
        with self._lock:
            cur = self._intervals[-1]
            return {k: round(a.sum, 6) for k, a in cur.counters.items()}

    def summary(self) -> dict:
        """Aggregated view of the most recent *complete-ish* interval,
        matching the reference's /v1/metrics InmemSink DisplayMetrics."""
        with self._lock:
            cur = self._intervals[-1]
            merged_gauges: Dict[str, float] = {}
            for itv in self._intervals:
                merged_gauges.update(itv.gauges)
            return {
                "Timestamp": time.strftime(
                    "%Y-%m-%d %H:%M:%S +0000 UTC", time.gmtime(cur.start)
                ),
                "Gauges": [
                    {"Name": k, "Value": v} for k, v in sorted(merged_gauges.items())
                ],
                "Counters": [
                    cur.counters[k].summary(k, self.interval)
                    for k in sorted(cur.counters)
                ],
                "Samples": [
                    cur.samples[k].summary(k, self.interval)
                    for k in sorted(cur.samples)
                ],
            }

    def prometheus(self) -> str:
        """Text exposition format (reference supports a prometheus sink)."""
        out: List[str] = []

        def esc(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        with self._lock:
            merged_gauges: Dict[str, float] = {}
            for itv in self._intervals:
                merged_gauges.update(itv.gauges)
            cur = self._intervals[-1]
            for k, v in sorted(merged_gauges.items()):
                out.append(f"# TYPE {esc(k)} gauge")
                out.append(f"{esc(k)} {v}")
            for k in sorted(cur.counters):
                agg = cur.counters[k]
                out.append(f"# TYPE {esc(k)} counter")
                out.append(f"{esc(k)} {agg.sum}")
            for k in sorted(cur.samples):
                agg = cur.samples[k]
                n = esc(k)
                out.append(f"# TYPE {n} summary")
                out.append(f"{n}_sum {agg.sum}")
                out.append(f"{n}_count {agg.count}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._intervals = [_Interval(time.time())]


class StatsdSink:
    """Push sink speaking the statsd line protocol over UDP — covers the
    reference's statsd AND statsite sinks (statsite is line-compatible),
    and with ``datadog=True`` emits DogStatsD tag suffixes (the DataDog
    sink slot, command/agent/command.go:976-1018). Fire-and-forget UDP:
    a down collector never blocks or fails the server."""

    def __init__(self, address: str, prefix: str = "",
                 datadog: bool = False, tags: Optional[Dict[str, str]] = None) -> None:
        import socket

        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.prefix = prefix
        self.datadog = datadog
        self._tag_suffix = ""
        if datadog and tags:
            pairs = ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
            self._tag_suffix = f"|#{pairs}"

    def _emit(self, name: str, value: float, kind: str) -> None:
        if self.prefix:
            name = f"{self.prefix}.{name}"
        line = f"{name}:{value:g}|{kind}{self._tag_suffix}"
        try:
            self._sock.sendto(line.encode(), self._addr)
        except OSError:
            pass  # telemetry is never load-bearing

    def incr_counter(self, name: str, value: float = 1.0) -> None:
        self._emit(name, value, "c")

    def add_sample(self, name: str, value: float) -> None:
        self._emit(name, value, "ms")

    def set_gauge(self, name: str, value: float) -> None:
        self._emit(name, value, "g")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


#: process-global sink, like go-metrics' global Default registry
_global = InmemSink()

#: external push sinks fanned out alongside the inmem sink (go-metrics
#: FanoutSink: inmem + statsd/statsite/datadog per telemetry config)
_sinks: List[object] = []
_sinks_lock = witness_lock("metrics._sinks_lock")


def register_sink(sink) -> None:
    with _sinks_lock:
        _sinks.append(sink)


def deregister_sink(sink) -> None:
    with _sinks_lock:
        if sink in _sinks:
            _sinks.remove(sink)
    close = getattr(sink, "close", None)
    if close is not None:
        close()


def _fanout(method: str, name: str, value: float) -> None:
    with _sinks_lock:
        sinks = list(_sinks)
    for sink in sinks:
        try:
            getattr(sink, method)(name, value)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass


def global_sink() -> InmemSink:
    return _global


def incr_counter(name: str, value: float = 1.0) -> None:
    _global.incr_counter(name, value)
    if _sinks:
        _fanout("incr_counter", name, value)


def add_sample(name: str, value: float) -> None:
    _global.add_sample(name, value)
    if _sinks:
        _fanout("add_sample", name, value)


def set_gauge(name: str, value: float) -> None:
    _global.set_gauge(name, value)
    if _sinks:
        _fanout("set_gauge", name, value)


def measure_since(name: str, start: float) -> None:
    elapsed_ms = (time.monotonic() - start) * 1000.0
    _global.add_sample(name, elapsed_ms)
    if _sinks:
        _fanout("add_sample", name, elapsed_ms)


def now() -> float:
    """Monotonic start stamp for measure_since."""
    return time.monotonic()
