"""Wall-clock phase attribution for the end-to-end scheduling pipeline.

The reference measures pipeline stages with per-call timers
(``nomad.plan.evaluate``, ``nomad.plan.apply`` — plan_apply.go:369/:400,
``nomad.worker.invoke_scheduler`` — worker.go:245); summing those across
worker THREADS overstates wall time badly under the GIL (concurrent
threads' intervals overlap). This module records raw [start, end) spans
per phase and reports the UNION length inside a measurement window: "how
much wall time had >= 1 thread inside phase X". That is the number that
answers "where does the end-to-end second go" (VERDICT r4: the bench
must publish measured phase shares, and the multi-chip extrapolation
must be computed from them).

Zero overhead unless enabled; the bench enables it around its timed
window. Phases tracked across the system path:

  encode         per-eval problem encoding (engine.encode_eval, GIL)
  device         batched scan dispatch + result fetch (device + tunnel)
  pad_stack      batch padding/stacking before dispatch (host)
  apply          decode results -> plan blocks (engine._apply_*, GIL)
  plan_evaluate  applier re-check against snapshot (plan_apply, GIL)
  raft_fsm       raft apply -> FSM -> state store commit (GIL)
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple

_lock = threading.Lock()
_intervals: Dict[str, List[Tuple[float, float]]] = {}
_enabled = False


def enable() -> None:
    """Clear history and start recording."""
    global _enabled
    with _lock:
        _intervals.clear()
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


@contextmanager
def track(name: str):
    """Record one [start, end) span under ``name`` (no-op when disabled)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        with _lock:
            if _enabled:
                _intervals.setdefault(name, []).append((t0, t1))


def now() -> float:
    """The clock phase spans are recorded on (perf_counter)."""
    return time.perf_counter()


def _union_len(spans: List[Tuple[float, float]], lo: float, hi: float) -> float:
    clipped = sorted(
        (max(a, lo), min(b, hi)) for a, b in spans if b > lo and a < hi
    )
    total = 0.0
    cur_a = cur_b = None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def wall_shares(t0: float, t1: float) -> Dict[str, float]:
    """Seconds of the [t0, t1] window during which >= 1 thread was inside
    each phase (interval union — NOT a thread-sum), plus:

      any_host   union over every host-side phase (all but ``device``)
      busy       union over every phase
      window     t1 - t0
    """
    with _lock:
        snap = {k: list(v) for k, v in _intervals.items()}
    out = {k: round(_union_len(v, t0, t1), 3) for k, v in snap.items()}
    host = [s for k, v in snap.items() if k != "device" for s in v]
    every = [s for v in snap.values() for s in v]
    out["any_host"] = round(_union_len(host, t0, t1), 3)
    out["busy"] = round(_union_len(every, t0, t1), 3)
    out["window"] = round(t1 - t0, 3)
    return out
