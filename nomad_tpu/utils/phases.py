"""Wall-clock phase attribution for the end-to-end scheduling pipeline.

The reference measures pipeline stages with per-call timers
(``nomad.plan.evaluate``, ``nomad.plan.apply`` — plan_apply.go:369/:400,
``nomad.worker.invoke_scheduler`` — worker.go:245); summing those across
worker THREADS overstates wall time badly under the GIL (concurrent
threads' intervals overlap). This module records raw [start, end) spans
per phase and reports the UNION length inside a measurement window: "how
much wall time had >= 1 thread inside phase X". That is the number that
answers "where does the end-to-end second go" (VERDICT r4: the bench
must publish measured phase shares, and the multi-chip extrapolation
must be computed from them).

Zero overhead unless enabled; the bench enables it around its timed
window. Phases tracked across the system path:

  encode         per-eval problem encoding (engine.encode_eval, GIL)
  device         batched scan dispatch + result fetch (device + tunnel)
  pad_stack      batch padding/stacking before dispatch (host)
  apply          decode results -> plan blocks (engine._apply_*, GIL)
  plan_evaluate  applier re-check against snapshot (plan_apply, GIL)
  raft_fsm       raft apply -> FSM -> state store commit (GIL)
  snapshot       worker's shared state-snapshot clone (worker._process)
  reconcile      desired-vs-existing alloc diff (generic_sched)
  rank           host placement iterator stack pull: feasibility +
                 scoring per candidate (rank.BinPackIterator.next —
                 covers the whole upstream iterator chain)
  proposed       per-candidate proposed-alloc rebuild (context.py)
  dense_mat      dense-block slot materialization (state_store)
  place          host placement loop: select + alloc construction glue
                 around the rank pulls (generic/system scheduler)
  engine_gate    device-path gate checks + encode attempts + fallback
                 decision (tpu/integration.py; engine phases nest inside)
  device_wait    worker parked in the device dispatch block — the
                 batcher's gather window + queue + device round trip
                 (or the chunked-tier scan) until its wave's results
                 land. r05's ~500s busy-vs-window gap lived here,
                 untracked; device/pad_stack nest inside its union.
  plan_submit    worker parked on the plan queue future (worker)
  wait_index     worker parked on raft replication before snapshotting
  raft_fsm       raft log append -> FSM -> state store commit (every
                 Server.raft_apply, plan commits included)

META-PHASES (excluded from ``any_host``/``busy``, which aggregate only
fine phases): ``worker_busy`` brackets the whole of a worker's eval
processing and exists so ``coverage()`` can answer "what fraction of
measured worker busy time do the fine phases explain" — the ISSUE 4
self-check against round 5's 17%-busy blindness, where the host
iterator stack burned wall time no phase accounted for.

Hot-loop spans (rank/proposed/dense_mat run per candidate, thousands of
times per eval) COALESCE: a span starting within _COALESCE_GAP of the
previous same-phase span's end merges into it, bounding memory at
O(distinct bursts) instead of O(calls) with at most _COALESCE_GAP of
union-length overestimate per merge.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple
from .lock_witness import module_witness_lock

_lock = module_witness_lock("phases._lock")
_intervals: Dict[str, List[Tuple[float, float]]] = {}
_enabled = False

# phases that measure a measurement (a window, not work); never summed
# into the busy/any_host aggregates
_META = frozenset({"worker_busy"})

# merge same-phase spans closer than this (seconds); ~10k coalesced
# hot-loop calls collapse into a handful of burst intervals
_COALESCE_GAP = 2e-4


def enable() -> None:
    """Clear history and start recording."""
    global _enabled
    with _lock:
        _intervals.clear()
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


@contextmanager
def track(name: str):
    """Record one [start, end) span under ``name`` (no-op when disabled)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        with _lock:
            if _enabled:
                spans = _intervals.setdefault(name, [])
                if spans and t0 - spans[-1][1] < _COALESCE_GAP:
                    last = spans[-1]
                    spans[-1] = (min(last[0], t0), max(last[1], t1))
                else:
                    spans.append((t0, t1))


def now() -> float:
    """The clock phase spans are recorded on (perf_counter)."""
    return time.perf_counter()


def _union_len(spans: List[Tuple[float, float]], lo: float, hi: float) -> float:
    clipped = sorted(
        (max(a, lo), min(b, hi)) for a, b in spans if b > lo and a < hi
    )
    total = 0.0
    cur_a = cur_b = None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def wall_shares(t0: float, t1: float) -> Dict[str, float]:
    """Seconds of the [t0, t1] window during which >= 1 thread was inside
    each phase (interval union — NOT a thread-sum), plus:

      any_host   union over every host-side fine phase (all but
                 ``device`` and meta-phases)
      busy       union over every fine phase (meta-phases excluded)
      window     t1 - t0
      untracked  window - busy: wall seconds during which NO fine phase
                 had a thread inside it. r05 shipped a headline where
                 this residual was 498s of a 600s window and invisible —
                 the gap is now an explicit row so a busy-vs-window
                 mismatch can never again go unreported.
    """
    with _lock:
        snap = {k: list(v) for k, v in _intervals.items()}
    out = {k: round(_union_len(v, t0, t1), 3) for k, v in snap.items()}
    host = [s for k, v in snap.items()
            if k != "device" and k not in _META for s in v]
    every = [s for k, v in snap.items() if k not in _META for s in v]
    out["any_host"] = round(_union_len(host, t0, t1), 3)
    out["busy"] = round(_union_len(every, t0, t1), 3)
    out["window"] = round(t1 - t0, 3)
    out["untracked"] = round(max(0.0, out["window"] - out["busy"]), 3)
    return out


def _merged(spans: List[Tuple[float, float]], lo: float,
            hi: float) -> List[Tuple[float, float]]:
    """Sorted, disjoint, window-clipped intervals."""
    clipped = sorted(
        (max(a, lo), min(b, hi)) for a, b in spans if b > lo and a < hi
    )
    out: List[Tuple[float, float]] = []
    for a, b in clipped:
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _intersect_len(xs: List[Tuple[float, float]],
                   ys: List[Tuple[float, float]]) -> float:
    """Total overlap length of two disjoint-sorted interval lists."""
    total = 0.0
    i = j = 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if b > a:
            total += b - a
        if xs[i][1] < ys[j][1]:
            i += 1
        else:
            j += 1
    return total


def coverage(t0: float, t1: float) -> Dict[str, float]:
    """Phase-attribution coverage self-check (ISSUE 4): what fraction of
    measured worker busy wall time (the ``worker_busy`` meta-phase
    union) do the fine phases explain?

      worker_busy   union seconds any worker spent processing an eval
      tracked_busy  seconds of that during which >= 1 fine phase was
                    also active (anywhere — the device phase runs on the
                    dispatcher thread while the worker blocks, and still
                    explains the worker's wait)
      coverage      tracked_busy / worker_busy  (1.0 when never busy)

    Round 5's blindness was coverage ~0.17: the host iterator stack
    burned wall time no phase claimed. The stress suite asserts >= 0.9.
    """
    with _lock:
        snap = {k: list(v) for k, v in _intervals.items()}
    busy = _merged(snap.get("worker_busy", []), t0, t1)
    fine = [s for k, v in snap.items() if k not in _META for s in v]
    tracked = _intersect_len(_merged(fine, t0, t1), busy)
    busy_len = sum(b - a for a, b in busy)
    return {
        "worker_busy": round(busy_len, 3),
        "tracked_busy": round(tracked, 3),
        "coverage": round(tracked / busy_len, 4) if busy_len else 1.0,
    }
