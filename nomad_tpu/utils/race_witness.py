"""nomad-race's dynamic side: an opt-in Eraser-style lockset witness.

The static half (``nomad_tpu/analysis/shared_state.py``) *infers* which
attributes are shared across thread roots and proves every write is
guarded. This module is the runtime cross-check: hot shared containers
are created through the factories here (``tracked_dict`` /
``tracked_list`` / ``tracked_deque``), naming each field with the SAME
``module.Class.attr`` key the static analyzer derives for it. When the
witness is DISARMED (the default) the factories return plain builtin
containers — production pays nothing, not even an isinstance check per
access. When ARMED (``NOMAD_RACE_WITNESS=1`` at import, or ``arm()``
before the containers are constructed) they return instrumented
subclasses that report every read and mutation to the witness.

Per field the witness runs the classic Eraser state machine:

* first thread only  -> **exclusive** (no lockset yet; initialisation
  writes are fine, there is a happens-before on thread start)
* second thread reads, no writes since -> **shared** (read-only sharing
  is benign; lockset tracked but empty lockset not reported)
* any write once two threads are involved -> **shared-modified**: the
  candidate lockset — seeded from the held set of the access that first
  made the field shared, then intersected with every subsequent
  accessor's held set — must stay non-empty. Held sets come from the
  lock witness's per-thread bookkeeping (``held_names_current``), so
  arming the race witness arms the lock witness too.

An empty lockset in shared-modified fails FAST with
:class:`RaceViolation` carrying both access stacks (this access's, plus
the last recorded access from a different thread). At teardown
:func:`RaceWitness.cross_check` verifies every runtime-witnessed shared
field is in the static pass's inferred-shared set: the dynamic run
validates that the static inference is a sound over-approximation.

Locksets are keyed by lock NAME (lock-class semantics, like the lock
witness): two instances of the same class share lock and field names, so
cross-instance false negatives are possible — the static pass, which
reasons per-class anyway, covers that direction.
"""
from __future__ import annotations

import collections
import os
import sys
import threading
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from . import lock_witness as _lw


class RaceViolation(RuntimeError):
    """A write to a multi-thread-shared field happened with a candidate
    lockset that intersected down to empty — no single lock protects
    every access to this field."""


def _fast_stack(limit: int = 12) -> Tuple[Tuple[str, int, str], ...]:
    """Cheap stack capture: (filename, lineno, funcname) triples, no
    source-line formatting. Formatting happens only on violation."""
    frames: List[Tuple[str, int, str]] = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        co = f.f_code
        frames.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    return tuple(frames)


def _format_stack(frames: Tuple[Tuple[str, int, str], ...]) -> str:
    return "\n".join(
        f'  File "{fn}", line {ln}, in {fun}' for fn, ln, fun in frames
    )


class _FieldState:
    __slots__ = (
        "name", "state", "owner", "lockset", "dirty",
        "reads", "writes", "threads", "last_other",
    )

    def __init__(self, name: str, owner: int) -> None:
        self.name = name
        self.state = "exclusive"  # exclusive | shared | shared-modified
        self.owner = owner
        self.lockset: Optional[FrozenSet[str]] = None
        self.dirty = False        # any write while still exclusive
        self.reads = 0
        self.writes = 0
        self.threads: Set[int] = {owner}
        # (thread name, is_write, stack) of the most recent access — kept
        # so a violation can show the OTHER side's stack too
        self.last_other: Optional[Tuple[str, bool, Tuple]] = None


class RaceWitness:
    """Global witness state: per-field Eraser state machines."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._fields: Dict[str, _FieldState] = {}
        self.accesses = 0
        self.violations = 0

    # -- bookkeeping (called from the tracked containers) ----------------

    def note(self, name: str, is_write: bool) -> None:
        if _ACTIVE is not self:
            # tracked containers outlive the witness session: once this
            # witness is disarmed the lock witness's held sets are gone
            # too, so evaluating teardown accesses would report phantom
            # races ("holding no locks" on a properly locked access)
            return
        ident = threading.get_ident()
        lw = _lw.active()
        held = lw.held_names_current() if lw is not None else ()
        stack = _fast_stack()
        with self._mu:
            self.accesses += 1
            st = self._fields.get(name)
            if st is None:
                st = self._fields[name] = _FieldState(name, ident)
            st.threads.add(ident)
            if is_write:
                st.writes += 1
            else:
                st.reads += 1
            if st.state == "exclusive":
                if ident == st.owner:
                    st.dirty = st.dirty or is_write
                    st.last_other = (
                        threading.current_thread().name, is_write, stack)
                    return
                # a second thread arrived: seed the candidate lockset from
                # THIS access's held set (Eraser's initialisation refinement
                # — unlocked writes during single-threaded init are benign)
                st.lockset = frozenset(held)
                st.state = ("shared-modified"
                            if is_write or st.dirty else "shared")
            else:
                assert st.lockset is not None
                st.lockset = st.lockset & frozenset(held)
                if is_write and st.state == "shared":
                    st.state = "shared-modified"
            prior = st.last_other
            st.last_other = (threading.current_thread().name, is_write, stack)
            if st.state == "shared-modified" and not st.lockset:
                self.violations += 1
                st.state = "reported"  # one violation per field, not a storm
                raise self._violation(st, is_write, held, stack, prior)

    def _violation(self, st: _FieldState, is_write: bool,
                   held: Tuple[str, ...],
                   stack: Tuple, prior: Optional[Tuple]) -> RaceViolation:
        kind = "write" if is_write else "read"
        other = ("no prior access stack recorded" if prior is None else
                 f"last access from thread {prior[0]!r} "
                 f"({'write' if prior[1] else 'read'}):\n"
                 f"{_format_stack(prior[2])}")
        return RaceViolation(
            f"data race on {st.name!r}: candidate lockset is EMPTY after "
            f"{kind} on thread {threading.current_thread().name!r} "
            f"(holding {list(held) or 'no locks'}); {len(st.threads)} "
            f"threads have touched this field "
            f"({st.reads} reads / {st.writes} writes).\n"
            f"this access:\n{_format_stack(stack)}\n{other}"
        )

    # -- read side -------------------------------------------------------

    def shared_fields(self) -> List[str]:
        """Fields witnessed as touched by >= 2 threads."""
        with self._mu:
            return sorted(
                name for name, st in self._fields.items()
                if len(st.threads) > 1
            )

    def field_report(self) -> Dict[str, Dict[str, object]]:
        with self._mu:
            return {
                name: {
                    "state": st.state,
                    "threads": len(st.threads),
                    "reads": st.reads,
                    "writes": st.writes,
                    "lockset": sorted(st.lockset or ()),
                }
                for name, st in sorted(self._fields.items())
            }

    def stats(self) -> Dict[str, object]:
        with self._mu:
            shared = sum(1 for st in self._fields.values()
                         if len(st.threads) > 1)
            return {
                "armed": 1,
                "fields": len(self._fields),
                "shared_fields": shared,
                "accesses": self.accesses,
                "violations": self.violations,
            }

    def cross_check(self, static_shared: Iterable[str]) -> List[str]:
        """Runtime-witnessed shared fields MISSING from the static
        analyzer's inferred-shared set — each one is a field the static
        root inventory / call graph failed to see as concurrent. Empty
        list == the static pass over-approximates runtime sharing."""
        allowed = set(static_shared)
        return [f for f in self.shared_fields() if f not in allowed]


# -- instrumented containers -------------------------------------------------
#
# Subclasses of the builtins so everything (repr, json, copy, isinstance
# checks in callers) keeps working. Only Python-level method calls are
# noted; C-level fast paths that bypass the overrides (e.g. dict.copy on
# the subclass) are unwitnessed reads — acceptable, the witness targets
# mutation discipline.


class _TrackedDict(dict):
    __slots__ = ("_rw_name", "_rw")

    def __init__(self, name: str, witness: RaceWitness, init=None) -> None:
        super().__init__(init or {})
        self._rw_name = name
        self._rw = witness

    def __getitem__(self, k):
        self._rw.note(self._rw_name, False)
        return super().__getitem__(k)

    def get(self, k, default=None):
        self._rw.note(self._rw_name, False)
        return super().get(k, default)

    def __contains__(self, k):
        self._rw.note(self._rw_name, False)
        return super().__contains__(k)

    def __iter__(self):
        self._rw.note(self._rw_name, False)
        return super().__iter__()

    def items(self):
        self._rw.note(self._rw_name, False)
        return super().items()

    def values(self):
        self._rw.note(self._rw_name, False)
        return super().values()

    def keys(self):
        self._rw.note(self._rw_name, False)
        return super().keys()

    def __setitem__(self, k, v):
        self._rw.note(self._rw_name, True)
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._rw.note(self._rw_name, True)
        super().__delitem__(k)

    def pop(self, *a):
        self._rw.note(self._rw_name, True)
        return super().pop(*a)

    def popitem(self):
        self._rw.note(self._rw_name, True)
        return super().popitem()

    def clear(self):
        self._rw.note(self._rw_name, True)
        super().clear()

    def update(self, *a, **kw):
        self._rw.note(self._rw_name, True)
        super().update(*a, **kw)

    def setdefault(self, k, default=None):
        self._rw.note(self._rw_name, True)
        return super().setdefault(k, default)

    def __reduce__(self):  # pickle/deepcopy as a plain dict payload
        return (dict, (dict(self),))


class _TrackedList(list):
    __slots__ = ("_rw_name", "_rw")

    def __init__(self, name: str, witness: RaceWitness, init=()) -> None:
        super().__init__(init)
        self._rw_name = name
        self._rw = witness

    def __getitem__(self, i):
        self._rw.note(self._rw_name, False)
        return super().__getitem__(i)

    def __iter__(self):
        self._rw.note(self._rw_name, False)
        return super().__iter__()

    def __contains__(self, v):
        self._rw.note(self._rw_name, False)
        return super().__contains__(v)

    def __setitem__(self, i, v):
        self._rw.note(self._rw_name, True)
        super().__setitem__(i, v)

    def __delitem__(self, i):
        self._rw.note(self._rw_name, True)
        super().__delitem__(i)

    def append(self, v):
        self._rw.note(self._rw_name, True)
        super().append(v)

    def extend(self, it):
        self._rw.note(self._rw_name, True)
        super().extend(it)

    def insert(self, i, v):
        self._rw.note(self._rw_name, True)
        super().insert(i, v)

    def pop(self, *a):
        self._rw.note(self._rw_name, True)
        return super().pop(*a)

    def remove(self, v):
        self._rw.note(self._rw_name, True)
        super().remove(v)

    def clear(self):
        self._rw.note(self._rw_name, True)
        super().clear()

    def sort(self, **kw):
        self._rw.note(self._rw_name, True)
        super().sort(**kw)

    def __reduce__(self):
        return (list, (list(self),))


class _TrackedDeque(collections.deque):
    # deque has no __dict__-free subclassing restriction; __slots__ not
    # supported together with deque's layout on all builds, keep plain
    def __init__(self, name: str, witness: RaceWitness,
                 init=(), maxlen=None) -> None:
        super().__init__(init, maxlen)
        self._rw_name = name
        self._rw = witness

    def __iter__(self):
        self._rw.note(self._rw_name, False)
        return super().__iter__()

    def __getitem__(self, i):
        self._rw.note(self._rw_name, False)
        return super().__getitem__(i)

    def append(self, v):
        self._rw.note(self._rw_name, True)
        super().append(v)

    def appendleft(self, v):
        self._rw.note(self._rw_name, True)
        super().appendleft(v)

    def extend(self, it):
        self._rw.note(self._rw_name, True)
        super().extend(it)

    def pop(self):
        self._rw.note(self._rw_name, True)
        return super().pop()

    def popleft(self):
        self._rw.note(self._rw_name, True)
        return super().popleft()

    def clear(self):
        self._rw.note(self._rw_name, True)
        super().clear()

    def __reduce__(self):
        return (collections.deque, (list(self), self.maxlen))


# -- the production-facing factories ----------------------------------------

_ACTIVE: Optional[RaceWitness] = None
_active_mu = threading.Lock()
_auto_armed_lw = False


def arm(witness: Optional[RaceWitness] = None) -> RaceWitness:
    """Install a witness. Containers created BEFORE arming stay plain —
    arm before constructing the servers under test (and re-mint module
    tables via their ``reset()`` hooks). Arms the lock witness too if it
    is not already armed: locksets come from its per-thread held sets."""
    global _ACTIVE, _auto_armed_lw
    with _active_mu:
        if _ACTIVE is not None and witness is not None and _ACTIVE is not witness:
            raise RuntimeError("another RaceWitness is already armed; disarm first")
        if _ACTIVE is None:
            _ACTIVE = witness or RaceWitness()
            if _lw.active() is None:
                _lw.arm()
                _auto_armed_lw = True
        return _ACTIVE


def disarm() -> None:
    """Remove the witness. Disarms the lock witness only if :func:`arm`
    armed it implicitly."""
    global _ACTIVE, _auto_armed_lw
    with _active_mu:
        _ACTIVE = None
        if _auto_armed_lw:
            _lw.disarm()
            _auto_armed_lw = False


def active() -> Optional[RaceWitness]:
    return _ACTIVE


def tracked_dict(name: str, init=None) -> dict:
    """A ``dict`` — instrumented iff a race witness is armed. ``name``
    must be the static analyzer's key for the field
    (``module.Class.attr`` / ``module._global``)."""
    w = _ACTIVE
    if w is None:
        return dict(init or {})
    return _TrackedDict(name, w, init)


def tracked_list(name: str, init=()) -> list:
    """A ``list`` — instrumented iff a race witness is armed."""
    w = _ACTIVE
    if w is None:
        return list(init)
    return _TrackedList(name, w, init)


def tracked_deque(name: str, init=(), maxlen=None):
    """A ``collections.deque`` — instrumented iff a race witness is
    armed."""
    w = _ACTIVE
    if w is None:
        return collections.deque(init, maxlen)
    return _TrackedDeque(name, w, init, maxlen)


def stats() -> Dict[str, object]:
    """Flight-recorder probe: cheap, never raises."""
    w = _ACTIVE
    if w is None:
        return {"armed": 0}
    return w.stats()


if os.environ.get("NOMAD_RACE_WITNESS") == "1":  # pragma: no cover - env gate
    arm()
