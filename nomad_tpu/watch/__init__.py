"""nomad-watch: the read-serving layer — watch hub, blocking queries,
follower stale reads.

Fills the role of the reference read path: ``state_store.go`` watchsets
(per-table/per-key notification channels), ``blocking_query.go``
(``blockingOptions``/``SnapshotMinIndex`` park-and-requery), and
``rpc.go``'s ``allowStaleRead`` forwarding bypass. The hub hangs off
``NomadFSM`` so every applied raft entry notifies the tables it
touched; ``blocking_read`` is the one wrapper every read endpoint
funnels through (lint-enforced: ``blocking-read-discipline``)."""
from .hub import WatchHandle, WatchHub, WatchLimitError, WATCH_TABLES
from .blocking import blocking_read, DEFAULT_MAX_QUERY_TIME, MAX_QUERY_TIME_CAP
from .stale import StaleReader, follower_lag_ms, read_meta

__all__ = [
    "WatchHandle",
    "WatchHub",
    "WatchLimitError",
    "WATCH_TABLES",
    "blocking_read",
    "DEFAULT_MAX_QUERY_TIME",
    "MAX_QUERY_TIME_CAP",
    "StaleReader",
    "follower_lag_ms",
    "read_meta",
]
