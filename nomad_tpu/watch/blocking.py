"""Blocking-query engine (reference ``blocking_query.go`` semantics).

``blocking_read`` is the ONE wrapper the read endpoints funnel through
(lint: ``blocking-read-discipline``): run the query, return immediately
when the store has moved past the client's ``min_query_index``, else
subscribe on the watch hub, park until notify or deadline, and re-run.
Every response carries a stamped :class:`QueryMeta` so clients chain
``meta.index`` back as the next ``min_query_index``.

Ordering is the load-bearing part: the hub handle is subscribed BEFORE
the query runs, so a write landing between the read and the park sets
the already-registered handle's event — the same ordering memdb
watchsets give the reference (acquire the watch channel inside the read
transaction, select on it after). A deadline expiry re-runs the query
one final time, so a deadline return still reports the CURRENT index —
that is what makes a dropped ``watch_notify`` degrade to a late answer
instead of a stale one.

The store is re-resolved through ``state_fn`` on every iteration: a
snapshot install on a rejoining replica REPLACES the FSM's StateStore,
and a watcher parked across the install must re-query the new store,
not the orphaned one.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ..structs.structs import QueryMeta, QueryOptions
from .hub import WatchHub, WatchLimitError

# a blocking request that names no max_query_time waits this long
# (reference defaultQueryTime=300s is sized for production agents; the
# harness-scale default keeps an abandoned watcher's server thread
# bounded to one test timeout)
DEFAULT_MAX_QUERY_TIME = 10.0
# hard cap regardless of what the client asked for (queryTimeLimit)
MAX_QUERY_TIME_CAP = 300.0


def blocking_read(
    state_fn: Callable[[], object],
    hub: Optional[WatchHub],
    run: Callable[[object], object],
    table: str,
    query_opts: Optional[QueryOptions] = None,
    key=None,
    meta: Optional[QueryMeta] = None,
):
    """Serve one read with reference blocking semantics.

    Returns ``[result, meta]``. ``run(store)`` must be a pure read —
    it executes under the store's read lock via ``read_with_index`` so
    the result and ``meta.index`` are exactly consistent. ``key`` narrows
    the hub subscription to one row (Get* endpoints); table-level reads
    pass ``key=None`` and wake on any write to the table.
    """
    opts = query_opts or QueryOptions()
    meta = meta if meta is not None else QueryMeta()
    blocking = opts.min_query_index > 0 and hub is not None
    max_t = opts.max_query_time if opts.max_query_time > 0 else DEFAULT_MAX_QUERY_TIME
    deadline = time.monotonic() + min(max_t, MAX_QUERY_TIME_CAP)
    while True:
        handle = None
        if blocking:
            try:
                # subscribe BEFORE reading (see module docstring)
                handle = hub.subscribe(table, key)
            except WatchLimitError:
                # registry full: degrade to a plain read — a bounded
                # answer now beats an unbounded park
                blocking = False
        result, index = state_fn().read_with_index(run)
        meta.index = index
        if not blocking or index > opts.min_query_index:
            if handle is not None:
                hub.unsubscribe(handle)
            return [result, meta]
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            # deadline: the read above already re-ran, so the client gets
            # the current index (its next min_query_index) even when every
            # notify in between was dropped
            hub.unsubscribe(handle)
            return [result, meta]
        handle.wait(remaining)
        hub.unsubscribe(handle)
