"""Watch hub: per-table / per-key wakeup registry for blocking queries.

Fills the role of the reference's memdb watchsets (``state_store.go``
``ws.Add`` channels): every raft apply notifies the hub with the
(table, key) pairs it touched, and parked blocking queries wake when
their table — or their specific key — moves. Two deliberate departures
from channel-per-row watchsets:

* **Coalesced wakeups.** Notifies stage into a pending set that one
  persistent flusher thread drains after a short window
  (``coalesce_ms``), so an apply storm (a plan-results batch, an
  unblock storm) wakes each watcher ONCE per window instead
  of once per write. The blocked-evals flusher uses the same shape for
  the same reason (blocked_evals.py ``_flush_pending_locked``).
* **Bounded registry.** ``subscribe`` refuses past ``max_watchers``
  (:class:`WatchLimitError`) — a million clients must degrade to plain
  polling, not park unbounded server threads.

Handles are one-shot: a flush that wakes a handle also removes it from
the registry; the blocking engine re-subscribes before every re-query
(subscribe BEFORE read, park after — the watchset ordering that makes
missed-wakeup races impossible: a write landing between the read and
the park still sets the already-registered handle's event).

The ``watch_notify`` chaos point fires at the top of :meth:`notify`: a
dropped notify loses AT MOST one flush window of wakeups, and parked
watchers degrade to their ``max_query_time`` deadline re-query — the
fault-armed test in tests/test_watch.py holds the never-wedge bound.

Callbacks registered with :meth:`add_callback` run on the flusher
thread OUTSIDE the hub lock and must be read-only observers — no state
writes, no store-lock acquisition (lint: ``blocking-read-discipline``).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..chaos.injector import fire as chaos_fire
from ..utils import metrics
from ..utils.lock_witness import witness_lock
from ..utils.race_witness import tracked_dict

# the watched table namespace (one name per StateStore table with a
# read endpoint; blocking_read validates against this set)
WATCH_TABLES = ("nodes", "jobs", "evals", "allocs", "deployments")


class WatchLimitError(RuntimeError):
    """subscribe() past ``max_watchers`` — callers fall back to polling."""


class WatchHandle:
    """One parked watcher. ``wait`` blocks until the hub's flusher sets
    the event (or timeout). ``wake_index``/``wake_time`` are stamped by
    the flusher BEFORE the event is set, so a waiter that observed
    ``wait() == True`` reads them race-free (Event provides the
    happens-before edge)."""

    __slots__ = ("table", "key", "_event", "wake_index", "wake_time")

    def __init__(self, table: str, key=None) -> None:
        self.table = table
        self.key = key
        self._event = threading.Event()
        # written by the flusher before Event.set, read by the waiter
        # after wait() returns True — Event is the happens-before edge
        self.wake_index = 0
        self.wake_time = 0.0

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def triggered(self) -> bool:
        return self._event.is_set()


class WatchHub:
    """Notification registry keyed on (table, key); ``key=None`` rows are
    table-level watchers (List endpoints), concrete keys are row-level
    (Get* endpoints). All registry state is guarded by ``_lock``; event
    sets and callbacks run outside it so a slow waiter thread never
    serializes the FSM apply path."""

    def __init__(self, coalesce_ms: float = 5.0,
                 max_watchers: int = 100_000) -> None:
        self.coalesce_s = max(float(coalesce_ms), 0.0) / 1000.0
        self.max_watchers = int(max_watchers)
        self._lock = witness_lock("watch.WatchHub._lock")
        self._cond = threading.Condition(self._lock)
        # (table, key) -> set of WatchHandle   # guarded-by: _lock
        self._watchers: Dict[Tuple[str, object], Set[WatchHandle]] = (
            tracked_dict("watch.WatchHub._watchers", {})
        )
        self._n_watchers = 0  # guarded-by: _lock
        # staged notifies: table -> set of keys, or None = whole table
        self._pending: Dict[str, Optional[set]] = {}  # guarded-by: _lock
        self._pending_index = 0  # guarded-by: _lock
        # ONE persistent flusher thread services every coalesce window.
        # notify() runs inside the FSM apply path (often under the raft
        # lock) — spawning a thread there per window is tens of ms of
        # apply latency on a loaded box, which is exactly the budget a
        # synchronous replication loop doesn't have. The flusher starts
        # lazily on the first staged notify and exits on close().
        self._flusher: Optional[threading.Thread] = None  # guarded-by: _lock
        self._flush_deadline: Optional[float] = None  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._callbacks: List[Callable] = []  # guarded-by: _lock
        # counters (all guarded-by: _lock)
        self.stats_notifies = 0
        self.stats_flushes = 0
        self.stats_wakeups = 0
        self.stats_dropped_notifies = 0
        self.stats_rejected = 0
        self.stats_subscribes = 0

    # -- registry --------------------------------------------------------

    def subscribe(self, table: str, key=None) -> WatchHandle:
        handle = WatchHandle(table, key)
        with self._lock:
            if self._n_watchers >= self.max_watchers:
                self.stats_rejected += 1
                metrics.incr_counter("nomad.watch.rejected")
                raise WatchLimitError(
                    f"watch registry full ({self._n_watchers} >= "
                    f"{self.max_watchers})"
                )
            self._watchers.setdefault((table, key), set()).add(handle)
            self._n_watchers += 1
            self.stats_subscribes += 1
            depth = self._n_watchers
        metrics.set_gauge("nomad.watch.watchers", depth)
        return handle

    def unsubscribe(self, handle: WatchHandle) -> None:
        """Idempotent removal (a handle woken by a flush is already gone)."""
        with self._lock:
            self._discard_locked(handle)
            depth = self._n_watchers
        metrics.set_gauge("nomad.watch.watchers", depth)

    def _discard_locked(self, handle: WatchHandle) -> None:
        slot = self._watchers.get((handle.table, handle.key))
        if slot is not None and handle in slot:
            slot.discard(handle)
            self._n_watchers -= 1
            if not slot:
                del self._watchers[(handle.table, handle.key)]

    def add_callback(self, fn: Callable[[Tuple[str, ...], int], None]) -> None:
        """``fn(tables, index)`` runs on every flush, outside the hub
        lock. Callbacks are observers ONLY: writing state or taking the
        store lock from here deadlocks the apply path (lint-enforced)."""
        with self._lock:
            self._callbacks.append(fn)

    # -- notify (FSM apply side) ----------------------------------------

    def notify(self, index: int, touched: Iterable[Tuple[str, object]]) -> None:
        """Stage wakeups for the (table, key) pairs a raft apply touched
        (``key=None`` = bulk write, wakes the whole table). Called from
        ``NomadFSM.apply`` on every replica."""
        touched = tuple(touched)
        if not touched:
            return
        try:
            # ChaosFault subclasses RuntimeError; a dropped notify must
            # degrade to the watchers' deadline re-query, never corrupt
            # the apply path that called us
            chaos_fire("watch_notify", index=index)
        except RuntimeError:
            with self._lock:
                self.stats_dropped_notifies += 1
            metrics.incr_counter("nomad.watch.dropped_notifies")
            return
        wake: List[WatchHandle] = []
        cbs: List[Callable] = []
        tables: Tuple[str, ...] = ()
        with self._lock:
            self.stats_notifies += len(touched)
            self._pending_index = max(self._pending_index, int(index))
            for table, key in touched:
                staged = self._pending.get(table, _ABSENT)
                if staged is None:
                    continue  # whole table already staged
                if key is None:
                    self._pending[table] = None
                elif staged is _ABSENT:
                    self._pending[table] = {key}
                else:
                    staged.add(key)
            if self.coalesce_s <= 0:
                wake, cbs, tables, index = self._drain_locked()
            else:
                self._schedule_flush_locked(self.coalesce_s)
                return
        self._wake(wake, cbs, tables, index)

    def notify_all(self, index: int) -> None:
        """Wake every watcher (snapshot restore replaced the whole store)."""
        self.notify(index, [(t, None) for t in WATCH_TABLES])

    # -- coalesced flush -------------------------------------------------

    def _schedule_flush_locked(self, delay: float) -> None:
        if self._closed:
            return
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flusher_main, name="watch-flush", daemon=True
            )
            self._flusher.start()
        if self._flush_deadline is None:
            self._flush_deadline = time.monotonic() + delay
            self._cond.notify()

    def _flusher_main(self) -> None:
        while True:
            with self._lock:
                while not self._closed and self._flush_deadline is None:
                    self._cond.wait()
                # sleep out the coalesce window; notifies landing inside
                # it merge into this flush without moving the deadline
                while not self._closed:
                    remaining = self._flush_deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                if self._closed:
                    return
                self._flush_deadline = None
                wake, cbs, tables, index = self._drain_locked()
            self._wake(wake, cbs, tables, index)

    def _drain_locked(self):
        """Collect the handles the staged notifies wake (removing them —
        handles are one-shot) and reset the pending set."""
        if not self._pending:
            return [], [], (), 0
        wake: Set[WatchHandle] = set()
        for table, keys in self._pending.items():
            # table-level watchers wake on ANY touched key of their table
            wake.update(self._watchers.get((table, None), ()))
            if keys is None:
                # bulk write: every row-level watcher of this table too
                for (t, k), handles in self._watchers.items():
                    if t == table and k is not None:
                        wake.update(handles)
            else:
                for key in keys:
                    wake.update(self._watchers.get((table, key), ()))
        tables = tuple(sorted(self._pending))
        index = self._pending_index
        self._pending = {}
        self._pending_index = 0
        self.stats_flushes += 1
        self.stats_wakeups += len(wake)
        for handle in wake:
            self._discard_locked(handle)
        return list(wake), list(self._callbacks), tables, index

    def _wake(self, handles: List[WatchHandle], cbs: List[Callable],
              tables: Tuple[str, ...], index: int) -> None:
        if handles:
            now = time.monotonic()
            metrics.incr_counter("nomad.watch.wakeups", len(handles))
            for handle in handles:
                handle.wake_index = index
                handle.wake_time = now
                handle._event.set()
        for cb in cbs:
            try:
                cb(tables, index)
            except Exception:  # noqa: BLE001 — observer bug stays its own
                pass

    # -- observability ---------------------------------------------------

    def watcher_count(self) -> int:
        with self._lock:
            return self._n_watchers

    def stats(self) -> Dict[str, object]:
        """Depth/wakeup gauges (flight-recorder ``watch`` probe and the
        ``Watch.Stats`` RPC — per-replica, callers pass no_forward)."""
        with self._lock:
            per_table: Dict[str, int] = {}
            for (table, _key), handles in self._watchers.items():
                per_table[table] = per_table.get(table, 0) + len(handles)
            flushes = self.stats_flushes
            return {
                "watchers": self._n_watchers,
                "max_watchers": self.max_watchers,
                "per_table": per_table,
                "subscribes": self.stats_subscribes,
                "notifies": self.stats_notifies,
                "flushes": flushes,
                "wakeups": self.stats_wakeups,
                "coalesce_ratio": (
                    self.stats_notifies / flushes if flushes else 0.0
                ),
                "dropped_notifies": self.stats_dropped_notifies,
                "rejected": self.stats_rejected,
                "pending_tables": len(self._pending),
            }

    def close(self) -> None:
        """Flush what's staged, wake everything parked, stop the flusher.
        The hub is unusable afterwards (notifies no-op into drops)."""
        with self._lock:
            self._closed = True
            self._flush_deadline = None
            self._cond.notify_all()
            flusher = self._flusher
            self._flusher = None
            wake, cbs, tables, index = self._drain_locked()
        self._wake(wake, cbs, tables, index)
        if flusher is not None and flusher is not threading.current_thread():
            flusher.join(timeout=2.0)


_ABSENT = object()  # sentinel distinguishing "no staged keys" from wildcard
