"""serve-100Kwatch harness: a blocking-watcher army over real RPC.

:class:`ServeReplay` extends the crash harness's 3-process wire-raft
cluster with a **serving workload** that runs concurrently with the
churn trace:

- a **watcher army** (default 5120 threads, 256 KiB stacks) each parked
  in a real ``Eval.GetEval`` blocking query against one replica — 2/3
  pinned to followers as ``allow_stale`` reads served by the follower's
  own FSM + hub, 1/3 to the leader. Every watcher is a
  :class:`~nomad_tpu.watch.stale.StaleReader` chaining ``meta.index``
  back as the next ``min_query_index``, exactly like a reference agent;
- a **beacon writer** committing a rotating group of beacon evals
  through ``Eval.Update`` (which returns the raft index) once per tick,
  recording ``(index, commit_time)`` into a ledger;
- **throughput readers** issuing plain (non-blocking) list reads so the
  leader-vs-follower read split is measured on both query shapes.

The ledger is the ground truth that turns watch returns into verdicts:
a return whose index covers a ledger commit is a **wakeup** (latency =
return − max(park, commit)); a deadline-shaped return that sat on an
old covered commit is a **lost wakeup** (the acceptance gate requires
zero); an index move with no ledger entry for the key is a **spurious**
wakeup (bulk table writes from churn — correct, just not ours). The
deadline re-query inside ``blocking_read`` is what keeps "lost" honest:
even a dropped notify returns the CURRENT index, so losing a wakeup is
only ever visible as lateness, which is exactly what we measure.

Concurrency proof is sampled, not assumed: each writer tick polls every
replica's ``Watch.Stats`` (no_forward) and records the summed parked
depth; the bench gates on the peak. Per-replica hub stats at stop time
supply the cluster coalescing ratio (notifies / flushes).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..chaos.crash import CrashReplay, ServerProcess
from ..rpc.transport import RPCClient, RPCError
from ..structs.structs import EVAL_STATUS_COMPLETE, Evaluation, QueryOptions
from .stale import StaleReader

# a return this close to max_query_time is deadline-shaped, not a wakeup
_DEADLINE_SLACK_S = 0.5
# a covered commit this much older than a deadline-shaped return means
# the notify was lost (vs merely coalesced/late)
_LOST_GRACE_S = 5.0
# watcher threads park, they don't compute: small stacks keep a 5K-thread
# army's virtual footprint bounded
_WATCHER_STACK_BYTES = 256 * 1024
# connect storms are gated so the accept queue never sees 5K SYNs at once
_CONNECT_GATE = 64


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _beacon_eval(key: str, tick: int) -> Evaluation:
    ev = Evaluation(id=key, job_id="serve-beacon", type="service")
    ev.status = EVAL_STATUS_COMPLETE   # terminal: the broker ignores it
    ev.status_description = f"tick-{tick}"
    return ev


class _WatcherStats:
    """One watcher thread's counters — thread-local while running,
    aggregated by the parent after join, so the hot loop takes no shared
    lock except the ledger read on an index move."""

    __slots__ = ("role", "seeds", "wakeups", "lost", "spurious",
                 "deadline_idle", "drain", "errors", "latencies_ms")

    def __init__(self, role: str) -> None:
        self.role = role
        self.seeds = 0
        self.wakeups = 0
        self.lost = 0
        self.spurious = 0
        self.deadline_idle = 0
        self.drain = 0
        self.errors = 0
        self.latencies_ms: List[float] = []


class ServeReplay(CrashReplay):
    """Churn replay + concurrent blocking-watch serving workload.

    Construction kwargs beyond :class:`CrashReplay`:

    - ``n_watchers``: army size (default 5120; ``>= 5000`` parked
      concurrently is the bench gate);
    - ``n_beacons`` / ``beacon_group`` / ``beacon_tick_s``: ledger key
      space, keys committed per tick, tick period. The schedule's
      arithmetic is load-bearing on one core: the per-key commit period
      ``(n_beacons / beacon_group) * beacon_tick_s`` must sit UNDER
      ``watch_query_time`` (else parks deadline out instead of waking),
      which fixes the total wakeup rate at ``n_watchers / period``. The
      free knob is burst shape, and both extremes lose: one big burst
      per second convoys the woken clients behind each other's GIL
      slices (seconds of tail), while tiny continuous bursts leave the
      replica schedulers no quiet gap and starve placement (the
      I/O-bound handler flood preempts CPU-bound scheduler slices).
      Defaults: ~107 watchers every 500ms;
    - ``watch_query_time``: each park's ``max_query_time`` — also the
      bound on army drain at stop;
    - ``n_readers``: plain-read throughput threads.

    The trace must not carry ``leader_kill``: watchers pin replicas by
    role for the follower-share measurement, and a mid-run re-election
    would silently turn a follower pin into a leader pin.
    """

    def __init__(self, *, n_watchers: int = 5120, n_beacons: int = 96,
                 beacon_group: int = 2, beacon_tick_s: float = 0.5,
                 watch_query_time: float = 30.0, n_readers: int = 6,
                 ramp_timeout_s: float = 150.0, **kwargs) -> None:
        super().__init__(**kwargs)
        if any(ev.kind == "leader_kill" for ev in self.trace):
            raise ValueError(
                "serve traces cannot carry leader_kill: watchers pin "
                "replicas by role; use CrashReplay for failover scenarios"
            )
        self.n_watchers = int(n_watchers)
        self.n_beacons = int(n_beacons)
        self.beacon_group = max(1, int(beacon_group))
        self.beacon_tick_s = float(beacon_tick_s)
        self.watch_query_time = float(watch_query_time)
        self.n_readers = int(n_readers)
        self.ramp_timeout_s = float(ramp_timeout_s)
        self.ramp_s: Optional[float] = None
        self.ramp_parked = 0
        self._serve_stop = threading.Event()
        self._serve_threads: List[threading.Thread] = []
        self._serve_clients: List[RPCClient] = []
        self._connect_gate = threading.Semaphore(_CONNECT_GATE)
        # beacon key -> [(raft index, commit monotonic)]  # guarded-by: _ledger_lock
        self._ledger: Dict[str, List[Tuple[int, float]]] = {}
        self._ledger_lock = threading.Lock()
        self._watcher_stats: List[_WatcherStats] = []
        self._stats_lock = threading.Lock()   # guards _watcher_stats/_reads
        # plain-read throughput counters: role -> count  # guarded-by: _stats_lock
        self._reads: Dict[str, int] = {"leader": 0, "follower": 0}
        self.beacon_commits = 0          # writer thread only
        self.writer_errors = 0           # writer thread only
        self.peak_watchers = 0           # writer thread only
        self.stragglers = 0              # parent, after join
        self._final_watch_stats: Dict[str, Dict[str, object]] = {}

    # -- lifecycle ---------------------------------------------------------

    def _boot(self) -> None:
        super()._boot()
        self._serve_start()

    def _post_trace(self) -> None:
        self._serve_halt()
        super()._post_trace()

    def _extra_result(self) -> Dict[str, object]:
        out = super()._extra_result()
        out["serve"] = self._serve_result()
        return out

    def _shutdown(self) -> None:
        self._serve_halt()   # idempotent; normal path already ran it
        for c in self._serve_clients:
            try:
                c.close()
            except OSError:
                pass
        self._serve_clients.clear()
        super()._shutdown()

    # -- army --------------------------------------------------------------

    def _beacon_key(self, i: int) -> str:
        return f"serve-beacon-{i:04d}"

    def _serve_start(self) -> None:
        leader = self._find_leader_proc()
        followers = [sp for sp in self.procs.values() if sp is not leader]
        # seed the ledger: one registration commit covering every key
        writer_client = RPCClient("127.0.0.1", leader.port, timeout=15.0)
        self._serve_clients.append(writer_client)
        evals = [_beacon_eval(self._beacon_key(i), 0)
                 for i in range(self.n_beacons)]
        idx = writer_client.call("Eval.Update", evals, timeout=15.0)
        now = time.monotonic()
        with self._ledger_lock:
            for i in range(self.n_beacons):
                self._ledger[self._beacon_key(i)] = [(int(idx), now)]
        self.beacon_commits = 1

        old_stack = threading.stack_size(_WATCHER_STACK_BYTES)
        try:
            for i in range(self.n_watchers):
                if i % 3 == 0 or not followers:
                    proc, role, stale = leader, "leader", False
                else:
                    proc = followers[i % len(followers)]
                    role, stale = "follower", True
                t = threading.Thread(
                    target=self._watcher_main,
                    args=(self._beacon_key(i % self.n_beacons),
                          proc, role, stale),
                    name=f"serve-watch-{i}", daemon=True,
                )
                t.start()
                self._serve_threads.append(t)
        finally:
            threading.stack_size(old_stack)
        # ramp barrier: the trace must drive a FULLY parked army, not a
        # spawning one — every watcher seed-reads then parks (no beacon
        # commits happen yet, so parked threads stay parked), and the
        # measurement window starts only once the hubs report the whole
        # army registered
        t0 = time.monotonic()
        deadline = t0 + self.ramp_timeout_s
        while time.monotonic() < deadline:
            depth = self._sample_depth()
            self.ramp_parked = max(self.ramp_parked, depth)
            if depth >= self.n_watchers:
                break
            time.sleep(0.5)
        self.ramp_s = round(time.monotonic() - t0, 1)
        self.peak_watchers = self.ramp_parked
        if self.ramp_parked < self.n_watchers:
            self.errors.append(  # race-ok: GIL-atomic append; harness list, read after threads settle
                f"serve ramp: {self.ramp_parked}/{self.n_watchers} watchers "
                f"parked after {self.ramp_timeout_s:.0f}s"
            )
        replicas = [leader] + followers
        for j in range(self.n_readers):
            proc = replicas[j % len(replicas)]
            role = "leader" if proc is leader else "follower"
            t = threading.Thread(
                target=self._reader_main,
                args=(proc, role, proc is not leader),
                name=f"serve-read-{j}", daemon=True,
            )
            t.start()
            self._serve_threads.append(t)
        sampler = threading.Thread(
            target=self._sampler_main, name="serve-sampler", daemon=True)
        sampler.start()
        self._serve_threads.append(sampler)
        writer = threading.Thread(
            target=self._writer_main, args=(writer_client,),
            name="serve-writer", daemon=True)
        writer.start()
        self._serve_threads.append(writer)

    def _serve_halt(self) -> None:
        if self._serve_stop.is_set():
            return
        self._serve_stop.set()
        # one final commit touching EVERY beacon key wakes the whole army
        # promptly instead of waiting out max_query_time deadlines
        try:
            lp = self._leader_proc or self._find_leader_proc()
            flush = RPCClient("127.0.0.1", lp.port, timeout=15.0)
            self._serve_clients.append(flush)
            idx = flush.call(
                "Eval.Update",
                [_beacon_eval(self._beacon_key(i), -1)
                 for i in range(self.n_beacons)],
                timeout=15.0,
            )
            now = time.monotonic()
            with self._ledger_lock:
                for i in range(self.n_beacons):
                    self._ledger.setdefault(
                        self._beacon_key(i), []).append((int(idx), now))
            self.beacon_commits += 1
        except (RPCError, OSError, RuntimeError) as e:
            self.errors.append(f"serve halt flush: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
        deadline = time.monotonic() + self.watch_query_time + 30.0
        for t in self._serve_threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self.stragglers = sum(1 for t in self._serve_threads if t.is_alive())
        if self.stragglers:
            self.errors.append(  # race-ok: GIL-atomic append; harness list, read after threads settle
                f"serve: {self.stragglers} army threads still parked after "
                f"{self.watch_query_time + 30.0:.0f}s drain window"
            )
        for nid, sp in sorted(self.procs.items()):
            if not sp.alive():
                continue
            try:
                self._final_watch_stats[nid] = sp.call(
                    "Watch.Stats", no_forward=True, timeout=2.0)
            except (RPCError, OSError):
                pass

    # -- threads -----------------------------------------------------------

    def _watcher_main(self, key: str, proc: ServerProcess, role: str,
                      stale: bool) -> None:
        stats = _WatcherStats(role)
        client = RPCClient("127.0.0.1", proc.port,
                           timeout=self.watch_query_time + 15.0)
        reader = StaleReader(client, stale=stale)
        connected = False
        try:
            while not self._serve_stop.is_set():
                min_index = reader.last_index
                t_park = time.monotonic()
                try:
                    if not connected:
                        # the first call dials: gate it so the accept
                        # queue never sees the whole army's SYNs at once
                        with self._connect_gate:
                            _, meta = reader.watch(
                                "Eval.GetEval", key,
                                max_query_time=self.watch_query_time)
                        connected = True
                    else:
                        _, meta = reader.watch(
                            "Eval.GetEval", key,
                            max_query_time=self.watch_query_time)
                except (RPCError, OSError):
                    stats.errors += 1
                    if self._serve_stop.is_set():
                        break
                    time.sleep(0.2)
                    continue
                now = time.monotonic()
                elapsed = now - t_park
                if self._serve_stop.is_set():
                    # the halt path wakes the WHOLE army at once to drain
                    # it fast; that storm is a teardown mechanism, not
                    # the serving workload — keep it out of the latency
                    # distribution
                    stats.drain += 1
                    break
                if min_index == 0:
                    stats.seeds += 1   # first call is non-blocking by design
                    continue
                self._classify(stats, key, min_index, meta.index,
                               t_park, now, elapsed)
        finally:
            with self._stats_lock:
                self._watcher_stats.append(stats)
            try:
                client.close()
            except OSError:
                pass

    def _classify(self, stats: _WatcherStats, key: str, min_index: int,
                  index: int, t_park: float, now: float,
                  elapsed: float) -> None:
        deadline_shaped = elapsed >= self.watch_query_time - _DEADLINE_SLACK_S
        first_commit: Optional[float] = None
        with self._ledger_lock:
            for c_idx, c_time in self._ledger.get(key, ()):
                if min_index < c_idx <= max(index, min_index):
                    first_commit = c_time if first_commit is None else min(
                        first_commit, c_time)
        if index > min_index:
            if first_commit is None:
                stats.spurious += 1   # bulk table write from churn, not ours
            elif deadline_shaped and now - first_commit >= _LOST_GRACE_S:
                stats.lost += 1       # covered commit sat un-notified
            else:
                stats.wakeups += 1
                stats.latencies_ms.append(
                    max(0.0, now - max(first_commit, t_park)) * 1000.0)
        else:
            # index did not move past min: a pure deadline. If the ledger
            # says this key DID move long ago, replication/notify stalled.
            stalled = False
            with self._ledger_lock:
                for c_idx, c_time in self._ledger.get(key, ()):
                    if c_idx > min_index and now - c_time >= _LOST_GRACE_S:
                        stalled = True
                        break
            if stalled:
                stats.lost += 1
            else:
                stats.deadline_idle += 1

    def _sample_depth(self) -> int:
        """Summed parked-watcher depth across replica hubs (Watch.Stats,
        no_forward — each replica answers for its own registry)."""
        depth = 0
        for sp in self.procs.values():
            if not sp.alive():
                continue
            try:
                st = sp.call("Watch.Stats", no_forward=True, timeout=2.0)
                depth += int(st.get("watchers", 0))
            except (RPCError, OSError):
                pass
        return depth

    def _sampler_main(self) -> None:
        while not self._serve_stop.is_set():
            self.peak_watchers = max(self.peak_watchers, self._sample_depth())
            self._serve_stop.wait(1.0)

    def _writer_main(self, client: RPCClient) -> None:
        tick = 0
        cursor = 0
        while not self._serve_stop.is_set():
            t0 = time.monotonic()
            tick += 1
            keys = [self._beacon_key((cursor + j) % self.n_beacons)
                    for j in range(self.beacon_group)]
            cursor = (cursor + self.beacon_group) % self.n_beacons
            try:
                idx = client.call(
                    "Eval.Update", [_beacon_eval(k, tick) for k in keys],
                    timeout=10.0,
                )
            except (RPCError, OSError):
                self.writer_errors += 1
                self._serve_stop.wait(0.5)
                continue
            now = time.monotonic()
            with self._ledger_lock:
                for k in keys:
                    self._ledger.setdefault(k, []).append((int(idx), now))
            self.beacon_commits += 1
            self._serve_stop.wait(
                max(0.05, self.beacon_tick_s - (time.monotonic() - t0)))

    def _reader_main(self, proc: ServerProcess, role: str,
                     stale: bool) -> None:
        client = RPCClient("127.0.0.1", proc.port, timeout=10.0)
        reader = StaleReader(client, stale=stale)
        n = 0
        try:
            while not self._serve_stop.is_set():
                try:
                    # row reads, not Eval.List: a full-table serialize per
                    # poll would measure pickling, not the serving path
                    reader.read("Eval.GetEval",
                                self._beacon_key(n % self.n_beacons),
                                timeout=10.0)
                    n += 1
                except (RPCError, OSError):
                    if self._serve_stop.is_set():
                        break
                self._serve_stop.wait(0.1)
        finally:
            with self._stats_lock:
                self._reads[role] = self._reads.get(role, 0) + n
            try:
                client.close()
            except OSError:
                pass

    # -- result ------------------------------------------------------------

    def _serve_result(self) -> Dict[str, object]:
        lat: List[float] = []
        by_role = {"leader": 0, "follower": 0}
        wakeups = lost = spurious = idle = errors = seeds = drain = 0
        with self._stats_lock:
            stats = list(self._watcher_stats)
            plain_reads = dict(self._reads)
        for s in stats:
            lat.extend(s.latencies_ms)
            # every completed watch return is one served read
            by_role[s.role] = by_role.get(s.role, 0) + (
                s.seeds + s.wakeups + s.lost + s.spurious
                + s.deadline_idle + s.drain)
            wakeups += s.wakeups
            lost += s.lost
            spurious += s.spurious
            idle += s.deadline_idle
            drain += s.drain
            errors += s.errors
            seeds += s.seeds
        for role, n in plain_reads.items():
            by_role[role] = by_role.get(role, 0) + n
        total_reads = sum(by_role.values())
        lat.sort()
        notifies = sum(int(st.get("notifies", 0))
                       for st in self._final_watch_stats.values())
        flushes = sum(int(st.get("flushes", 0))
                      for st in self._final_watch_stats.values())
        return {
            "n_watchers": self.n_watchers,
            "peak_concurrent_watchers": self.peak_watchers,
            "ramp_s": self.ramp_s,
            "ramp_parked": self.ramp_parked,
            "stragglers": self.stragglers,
            "wakeups": wakeups,
            "lost_wakeups": lost,
            "spurious_wakeups": spurious,
            "deadline_idle": idle,
            "drain_wakeups": drain,
            "seed_reads": seeds,
            "watcher_errors": errors,
            "wakeup_ms": {
                "p50": round(_percentile(lat, 0.50), 1),
                "p95": round(_percentile(lat, 0.95), 1),
                "p99": round(_percentile(lat, 0.99), 1),
                "max": round(lat[-1], 1) if lat else 0.0,
                "samples": len(lat),
            },
            "beacon_commits": self.beacon_commits,
            "writer_errors": self.writer_errors,
            "reads_total": total_reads,
            "reads_by_role": by_role,
            "follower_read_share": (
                round(by_role.get("follower", 0) / total_reads, 4)
                if total_reads else 0.0
            ),
            "plain_reads_by_role": plain_reads,
            "coalesce_ratio": (
                round(notifies / flushes, 2) if flushes else 0.0
            ),
            "watch_stats": dict(self._final_watch_stats),
        }
