"""Follower stale reads (reference ``rpc.go`` allowStale forwarding bypass).

Server side: :func:`read_meta` builds the :class:`QueryMeta` prototype a
read endpoint stamps — ``known_leader`` / ``last_contact_ms`` on every
read, plus the measured ``follower_lag_ms`` when a follower serves
locally instead of forwarding. The transport carries ``allow_stale`` as
an envelope flag (``RPCClient.call(..., stale=True)``): ``_dispatch``
skips leader forwarding for flagged requests, so the follower's own FSM
answers. Index consistency is preserved the same way it is on the
leader — the client's ``min_query_index`` parks on the FOLLOWER's hub
until the follower's replication stream catches up, so a stale read is
stale-but-index-consistent, never time-traveling backwards for a client
that chains ``meta.index``.

Client side: :class:`StaleReader` pins one replica and chains
``min_query_index`` across calls — the serving bench's watcher army and
follower-throughput readers are built on it.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

from ..structs.structs import QueryMeta, QueryOptions


def follower_lag_ms(server) -> float:
    """Measured replication-stream age on this replica: ms since the
    last leader contact (AppendEntries/InstallSnapshot). 0 on the leader
    and on raft implementations without contact tracking (in-proc)."""
    if server.is_leader:
        return 0.0
    age_fn = getattr(server.raft, "last_contact_age_s", None)
    if age_fn is None:
        return 0.0
    return max(age_fn(), 0.0) * 1000.0


def read_meta(server, rpc=None) -> QueryMeta:
    """QueryMeta prototype for one read served by ``server``. The caller
    (the endpoint's blocking_read) fills ``index``."""
    leader_known = server.is_leader or (
        rpc is not None and rpc.leader_addr is not None
    )
    lag = follower_lag_ms(server)
    return QueryMeta(
        index=0,
        known_leader=bool(leader_known),
        last_contact_ms=lag,
        follower_lag_ms=lag,
    )


class StaleReader:
    """Client helper pinned to ONE replica: issues ``allow_stale`` reads
    with a chained ``min_query_index``. ``read`` returns
    ``(result, meta)``; ``watch`` is the blocking form the watcher army
    uses (park until the key moves or ``max_query_time``)."""

    def __init__(self, client, stale: bool = True) -> None:
        self.client = client
        self.stale = stale
        self.last_index = 0

    def read(self, method: str, *args: Any,
             timeout: Optional[float] = None) -> Tuple[Any, QueryMeta]:
        opts = QueryOptions(allow_stale=self.stale)
        result, meta = self.client.call(
            method, *args, opts, stale=self.stale, timeout=timeout
        )
        self.last_index = max(self.last_index, meta.index)
        return result, meta

    def watch(self, method: str, *args: Any, max_query_time: float = 10.0,
              timeout: Optional[float] = None) -> Tuple[Any, QueryMeta]:
        opts = QueryOptions(
            min_query_index=self.last_index,
            max_query_time=max_query_time,
            allow_stale=self.stale,
        )
        result, meta = self.client.call(
            method, *args, opts, stale=self.stale,
            timeout=timeout if timeout is not None else max_query_time + 15.0,
        )
        self.last_index = max(self.last_index, meta.index)
        return result, meta
