"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(mesh/pjit/shard_map) is exercised without TPU hardware. Must run before jax
is imported anywhere.
"""
import os
import sys

# Hard-set (not setdefault): parity tests require the CPU backend's exact
# IEEE float64 — TPU emulated f64 (double-double) rounds differently and can
# flip exact-tie orderings by <=2 ULP. Benchmarks run on the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize may re-register the hardware TPU plugin regardless of the
# env var; override at the config level too (must happen pre-backend-init).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # host-only install: TPU tests will fall back/skip
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
