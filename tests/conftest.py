"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(mesh/pjit/shard_map) is exercised without TPU hardware. Must run before jax
is imported anywhere.
"""
import os
import sys

# Default to the virtual 8-device CPU platform (multi-chip sharding without
# hardware). Since the engine's deterministic mode moved to the exact
# INTEGER spec (tpu/intscore.py), its selections are bit-identical on every
# backend — so the parity suite may also run on real hardware:
#   NOMAD_TPU_TEST_PLATFORM=axon python -m pytest tests/test_tpu_parity.py
# runs the device side on the chip while the host pipeline stays pure
# Python float64, asserting plan parity ON the TPU.
_platform = os.environ.get("NOMAD_TPU_TEST_PLATFORM", "cpu")
if _platform != "cpu":
    # keep the CPU backend registered alongside the chip: the
    # cross-backend bit-equality test runs both in ONE process (the
    # tunneled chip registers as "axon"; use NOMAD_TPU_TEST_PLATFORM=axon)
    _platform = f"{_platform},cpu"
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if _platform == "cpu" and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize may re-register the hardware TPU plugin regardless of the
# env var; override at the config level too (must happen pre-backend-init).
try:
    import jax

    jax.config.update("jax_platforms", _platform)
except ImportError:  # host-only install: TPU tests will fall back/skip
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Concurrency hygiene (the -race / goroutine-leak analog this runtime can
# give): every Thread.start records its creation site; at session end any
# surviving thread is reported WITH the stack that started it, and leaked
# NON-daemon threads (which would hang interpreter exit) fail the run.
# faulthandler gives C-level stack dumps if the suite wedges.
# ---------------------------------------------------------------------------
import faulthandler as _faulthandler
import threading as _threading
import traceback as _traceback
import weakref as _weakref

_faulthandler.enable()

# weak keys: dead threads (and their target closures) must not be pinned
# for the whole session just to keep a leak report we will never print
_thread_origins = _weakref.WeakKeyDictionary()
_orig_thread_start = _threading.Thread.start


def _tracking_start(self):
    try:
        _thread_origins[self] = "".join(_traceback.format_stack(limit=6)[:-1])
    except Exception:
        pass
    return _orig_thread_start(self)


_threading.Thread.start = _tracking_start


def pytest_sessionfinish(session, exitstatus):
    import sys
    import time as _time

    _time.sleep(0.3)  # grace for teardown threads to wind down
    main = _threading.main_thread()
    leaked = [
        t for t in _threading.enumerate()
        if t is not main and t.is_alive()
    ]
    non_daemon = [t for t in leaked if not t.daemon]
    if leaked:
        print(f"\n[thread-hygiene] {len(leaked)} thread(s) alive at session "
              f"end ({len(non_daemon)} non-daemon):", file=sys.stderr)
        for t in leaked[:10]:
            origin = _thread_origins.get(t, "  <origin unknown>\n")
            print(f"  - {t.name} (daemon={t.daemon})\n{origin}",
                  file=sys.stderr)
    if non_daemon:
        # a non-daemon leak blocks interpreter exit: that is a real bug
        session.exitstatus = 1
        print("[thread-hygiene] FAILING: non-daemon threads leaked",
              file=sys.stderr)
