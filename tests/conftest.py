"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(mesh/pjit/shard_map) is exercised without TPU hardware. Must run before jax
is imported anywhere.
"""
import os
import sys

# Hard-set (not setdefault): parity tests require the CPU backend's exact
# IEEE float64 — TPU emulated f64 (double-double) rounds differently and can
# flip exact-tie orderings by <=2 ULP. Benchmarks run on the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# A sitecustomize may re-register the hardware TPU plugin regardless of the
# env var; override at the config level too (must happen pre-backend-init).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # host-only install: TPU tests will fall back/skip
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Concurrency hygiene (the -race / goroutine-leak analog this runtime can
# give): every Thread.start records its creation site; at session end any
# surviving thread is reported WITH the stack that started it, and leaked
# NON-daemon threads (which would hang interpreter exit) fail the run.
# faulthandler gives C-level stack dumps if the suite wedges.
# ---------------------------------------------------------------------------
import faulthandler as _faulthandler
import threading as _threading
import traceback as _traceback
import weakref as _weakref

_faulthandler.enable()

# weak keys: dead threads (and their target closures) must not be pinned
# for the whole session just to keep a leak report we will never print
_thread_origins = _weakref.WeakKeyDictionary()
_orig_thread_start = _threading.Thread.start


def _tracking_start(self):
    try:
        _thread_origins[self] = "".join(_traceback.format_stack(limit=6)[:-1])
    except Exception:
        pass
    return _orig_thread_start(self)


_threading.Thread.start = _tracking_start


def pytest_sessionfinish(session, exitstatus):
    import sys
    import time as _time

    _time.sleep(0.3)  # grace for teardown threads to wind down
    main = _threading.main_thread()
    leaked = [
        t for t in _threading.enumerate()
        if t is not main and t.is_alive()
    ]
    non_daemon = [t for t in leaked if not t.daemon]
    if leaked:
        print(f"\n[thread-hygiene] {len(leaked)} thread(s) alive at session "
              f"end ({len(non_daemon)} non-daemon):", file=sys.stderr)
        for t in leaked[:10]:
            origin = _thread_origins.get(t, "  <origin unknown>\n")
            print(f"  - {t.name} (daemon={t.daemon})\n{origin}",
                  file=sys.stderr)
    if non_daemon:
        # a non-daemon leak blocks interpreter exit: that is a real bug
        session.exitstatus = 1
        print("[thread-hygiene] FAILING: non-daemon threads leaked",
              file=sys.stderr)
