"""E2E framework: fork-exec black-box agents driven over HTTP.

Fills the role of reference ``e2e/framework/framework.go`` +
``testutil/server.go`` (TestServer launches the real compiled nomad
binary and drives it over the API): each agent is a real
``python -m nomad_tpu.cli agent`` OS process; tests interact only
through the SDK, exactly like a user.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# shared compile cache: each agent process would otherwise pay the full
# first-jit cost on CPU
JAX_CACHE = "/tmp/nomad-e2e-jax-cache"


def _env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_COMPILATION_CACHE_DIR"] = JAX_CACHE
    return env


class AgentProc:
    """One real agent process (testutil.TestServer)."""

    def __init__(self, *flags: str, name: str = "e2e") -> None:
        import queue
        import threading

        self.name = name
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "nomad_tpu.cli", "agent",
             "-http-port", "0", *flags],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=_env(),
            text=True,
        )
        # a pump thread owns stdout for the process lifetime: the banner
        # wait must be able to time out (readline blocks), and a chatty
        # agent must never stall on a full pipe after the banner
        self.lines: List[str] = []
        self._line_q: "queue.Queue[str]" = queue.Queue()

        def _pump() -> None:
            try:
                for line in self.proc.stdout:
                    self.lines.append(line)
                    self._line_q.put(line)
            except (ValueError, OSError):
                pass

        threading.Thread(target=_pump, daemon=True,
                         name=f"agent-pump-{name}").start()
        self.http_addr = self._await_banner()

    def _await_banner(self, timeout: float = 120.0) -> str:
        import queue

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                line = self._line_q.get(timeout=0.2)
            except queue.Empty:
                if self.proc.poll() is not None:
                    raise RuntimeError(
                        f"agent {self.name} exited {self.proc.returncode}: "
                        + "".join(self.lines[-10:])
                    )
                continue
            if "HTTP at" in line:
                return line.rsplit(" ", 1)[1].strip()
        raise RuntimeError(f"agent {self.name} never printed its address")

    @property
    def api(self):
        from nomad_tpu.api import Client, Config

        return Client(Config(address=self.http_addr))

    def kill_hard(self) -> None:
        """SIGKILL — the clientstate crash-recovery scenario."""
        self.proc.kill()
        self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)


def wait_until(fn, timeout=120.0, msg="condition", interval=0.3):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception as e:  # noqa: BLE001 — agents may still be booting
            last = e
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg} (last error: {last})")


def service_job(job_id: str, count: int = 1, command: str = "sleep",
                args: Optional[list] = None, **tg_extra) -> dict:
    tg = {
        "Name": "g",
        "Count": count,
        "Tasks": [{
            "Name": "t", "Driver": "raw_exec",
            "Config": {"command": "/bin/sh",
                       "args": ["-c", command] if args is None else args},
            "Resources": {"CPU": 50, "MemoryMB": 32},
        }],
    }
    tg.update(tg_extra)
    return {"ID": job_id, "Name": job_id, "Type": "service",
            "Datacenters": ["dc1"], "TaskGroups": [tg]}


def allocs_of(api, job_id: str) -> list:
    allocs, _ = api.jobs.allocations(job_id)
    return allocs or []


def running_allocs(api, job_id: str) -> list:
    return [a for a in allocs_of(api, job_id) if a["ClientStatus"] == "running"]
