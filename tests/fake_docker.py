"""Fake Docker daemon over a unix socket for driver tests — the role the
reference's docker test harness plays (drivers/docker/driver_test.go runs
against a real daemon; zero-egress CI gets this fake)."""
from __future__ import annotations

import json
import socketserver
import struct
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler
from typing import Dict, List


class FakeContainer:
    def __init__(self, name: str, config: dict):
        self.id = uuid.uuid4().hex
        self.name = name
        self.config = config
        self.state = "created"
        self.exit_code = 0
        self.exited = threading.Event()
        self.log_frames: List[bytes] = []
        self.log_cv = threading.Condition()
        self.kill_signals: List[str] = []


class FakeDocker:
    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self.images: Dict[str, int] = {}  # image -> pull count
        self.removed_images: List[str] = []
        self.containers: Dict[str, FakeContainer] = {}
        self.fail_pull = False
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, obj=None, raw=None):
                payload = raw if raw is not None else (
                    json.dumps(obj).encode() if obj is not None else b"")
                self.send_response(code)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/_ping":
                    return self._reply(200, raw=b"OK")
                if path == "/version":
                    return self._reply(200, {"Version": "fake-24.0"})
                if path.startswith("/images/") and path.endswith("/json"):
                    image = urllib.parse.unquote(path[len("/images/"):-len("/json")])
                    if image in outer.images:
                        return self._reply(200, {"Id": "sha256:" + image})
                    return self._reply(404, {"message": "no such image"})
                if path == "/containers/json":
                    out = [
                        {"Id": c.id, "Names": [f"/{c.name}"],
                         "Labels": c.config.get("Labels", {})}
                        for c in outer.containers.values()
                    ]
                    return self._reply(200, out)
                if path.endswith("/json") and path.startswith("/containers/"):
                    cid = path.split("/")[2]
                    c = outer.containers.get(cid)
                    if c is None:
                        return self._reply(404, {"message": "no such container"})
                    return self._reply(200, {
                        "Id": c.id,
                        "State": {"Running": c.state == "running",
                                  "ExitCode": c.exit_code},
                        "Config": c.config,
                    })
                if path.endswith("/stats"):
                    return self._reply(200, {
                        "memory_stats": {"usage": 1024 * 1024},
                        "cpu_stats": {"cpu_usage": {"total_usage": 200},
                                      "system_cpu_usage": 1000},
                        "precpu_stats": {"cpu_usage": {"total_usage": 100},
                                         "system_cpu_usage": 500},
                    })
                if "/logs" in path:
                    cid = path.split("/")[2]
                    c = outer.containers.get(cid)
                    if c is None:
                        return self._reply(404, {"message": "no such container"})
                    # follow semantics like the real daemon: stream frames
                    # as they appear until the container exits
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.end_headers()
                    sent = 0
                    try:
                        while True:
                            with c.log_cv:
                                while sent >= len(c.log_frames) and not c.exited.is_set():
                                    c.log_cv.wait(timeout=0.2)
                                frames = c.log_frames[sent:]
                                sent = len(c.log_frames)
                                done = c.exited.is_set() and sent >= len(c.log_frames)
                            for frame in frames:
                                self.wfile.write(frame)
                                self.wfile.flush()
                            if done:
                                return
                    except (BrokenPipeError, ConnectionResetError):
                        return
                if path.startswith("/exec/") and path.endswith("/json"):
                    return self._reply(200, {"Running": False, "ExitCode": 7})
                return self._reply(404, {"message": f"GET {path}"})

            def do_POST(self):
                path, _, query = self.path.partition("?")
                params = dict(urllib.parse.parse_qsl(query))
                body = self._body()  # always drain: replying with an
                # unread request body makes the client's sendall race a RST
                if path == "/images/create":
                    if outer.fail_pull:
                        return self._reply(500, {"message": "pull failed"})
                    image = params.get("fromImage", "") + ":" + params.get("tag", "latest")
                    with outer._lock:
                        outer.images[image] = outer.images.get(image, 0) + 1
                    return self._reply(200, raw=b'{"status":"Downloaded"}')
                if path == "/containers/create":
                    if body.get("Image") not in outer.images:
                        return self._reply(404, {"message": "no such image"})
                    c = FakeContainer(params.get("name", ""), body)
                    with outer._lock:
                        outer.containers[c.id] = c
                    return self._reply(201, {"Id": c.id})
                parts = path.split("/")
                if len(parts) >= 4 and parts[1] == "containers":
                    cid, action = parts[2], parts[3]
                    c = outer.containers.get(cid)
                    if c is None:
                        return self._reply(404, {"message": "no such container"})
                    if action == "start":
                        c.state = "running"
                        return self._reply(204)
                    if action == "wait":
                        c.exited.wait()
                        return self._reply(200, {"StatusCode": c.exit_code})
                    if action == "stop":
                        outer.finish(cid, 0)
                        return self._reply(204)
                    if action == "kill":
                        c.kill_signals.append(params.get("signal", "SIGKILL"))
                        outer.finish(cid, 137)
                        return self._reply(204)
                    if action == "exec":
                        return self._reply(201, {"Id": "exec-" + cid})
                if path.startswith("/exec/") and path.endswith("/start"):
                    # attached exec: multiplexed stdout frame in the body
                    frame = bytes([1, 0, 0, 0]) + struct.pack(">I", 3) + b"hi\n"
                    return self._reply(200, raw=frame)
                return self._reply(404, {"message": f"POST {path}"})

            def do_DELETE(self):
                path, _, _ = self.path.partition("?")
                if path.startswith("/images/"):
                    image = urllib.parse.unquote(path[len("/images/"):])
                    with outer._lock:
                        outer.images.pop(image, None)
                        outer.removed_images.append(image)
                    return self._reply(200, [])
                if path.startswith("/containers/"):
                    cid = path.split("/")[2]
                    with outer._lock:
                        outer.containers.pop(cid, None)
                    return self._reply(204)
                return self._reply(404, {"message": "delete?"})

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self._srv = Server(socket_path, Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    def finish(self, cid: str, exit_code: int) -> None:
        c = self.containers.get(cid)
        if c is not None and c.state != "exited":
            c.state = "exited"
            c.exit_code = exit_code
            c.exited.set()
            with c.log_cv:
                c.log_cv.notify_all()

    def add_log(self, cid: str, stream: int, data: bytes) -> None:
        c = self.containers[cid]
        with c.log_cv:
            c.log_frames.append(
                bytes([stream, 0, 0, 0]) + struct.pack(">I", len(data)) + data
            )
            c.log_cv.notify_all()

    def preload_image(self, image: str) -> None:
        self.images[image] = 1

    def start(self) -> "FakeDocker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
