"""ACL engine + HTTP enforcement tests (reference acl/acl_test.go,
acl/policy_test.go, nomad/acl_endpoint_test.go)."""

import json
import urllib.error
import urllib.request

import pytest

from nomad_tpu.acl import (
    PermissionDenied,
    management_acl,
    new_acl,
    parse_policy,
)
from nomad_tpu.acl.acl import (
    NS_CAP_DENY,
    NS_CAP_LIST_JOBS,
    NS_CAP_READ_JOB,
    NS_CAP_SUBMIT_JOB,
)
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.jobspec.hcl import HCLError


def call(base, path, method="GET", body=None, token=None):
    data = None
    if body is not None:
        data = json.dumps(body).encode()
    headers = {"X-Nomad-Token": token} if token else {}
    req = urllib.request.Request(base + path, data=data, method=method, headers=headers)
    with urllib.request.urlopen(req, timeout=30) as resp:
        payload = resp.read().decode()
        return json.loads(payload) if payload else None


def call_err(base, path, **kw):
    with pytest.raises(urllib.error.HTTPError) as ei:
        call(base, path, **kw)
    return ei.value.code


# ---------------------------------------------------------------------------
# policy parsing
# ---------------------------------------------------------------------------


def test_parse_policy_shorthands():
    pol = parse_policy(
        """
        namespace "default" {
          policy = "read"
        }
        namespace "ops" {
          policy       = "write"
          capabilities = ["sentinel-override"]
        }
        node     { policy = "write" }
        agent    { policy = "read" }
        operator { policy = "deny" }
        """
    )
    assert len(pol.namespaces) == 2
    default = pol.namespaces[0]
    assert default.name == "default"
    assert NS_CAP_LIST_JOBS in default.capabilities
    assert NS_CAP_READ_JOB in default.capabilities
    assert NS_CAP_SUBMIT_JOB not in default.capabilities
    ops = pol.namespaces[1]
    assert NS_CAP_SUBMIT_JOB in ops.capabilities
    assert "sentinel-override" in ops.capabilities
    assert pol.node == "write"
    assert pol.agent == "read"
    assert pol.operator == "deny"


def test_parse_policy_errors():
    with pytest.raises(HCLError):
        parse_policy('namespace "x" { policy = "admin" }')
    with pytest.raises(HCLError):
        parse_policy('namespace "x" { capabilities = ["fly"] }')
    with pytest.raises(HCLError):
        parse_policy('widget "x" { policy = "read" }')
    with pytest.raises(HCLError):
        parse_policy('namespace "x" { }')  # grants nothing


def test_acl_merge_deny_wins():
    read = parse_policy('namespace "default" { policy = "read" }')
    deny = parse_policy('namespace "default" { policy = "deny" }')
    write = parse_policy('namespace "default" { policy = "write" }')
    acl = new_acl([read, write])
    assert acl.allow_namespace_operation("default", NS_CAP_SUBMIT_JOB)
    acl = new_acl([read, deny, write])
    assert not acl.allow_namespace_operation("default", NS_CAP_READ_JOB)
    assert not acl.allow_namespace("default")


def test_acl_coarse_merge_and_management():
    a = parse_policy("node { policy = \"read\" }")
    b = parse_policy("node { policy = \"write\" }")
    acl = new_acl([a, b])
    assert acl.allow_node_write() and acl.allow_node_read()
    deny = parse_policy("node { policy = \"deny\" }")
    acl = new_acl([a, b, deny])
    assert not acl.allow_node_read()
    m = management_acl()
    assert m.allow_node_write() and m.allow_operator_write()
    assert m.allow_namespace_operation("anything", NS_CAP_SUBMIT_JOB)


def test_acl_namespace_glob():
    pol = parse_policy('namespace "prod-*" { policy = "read" }')
    acl = new_acl([pol])
    assert acl.allow_namespace_operation("prod-web", NS_CAP_READ_JOB)
    assert not acl.allow_namespace_operation("dev", NS_CAP_READ_JOB)


def test_host_volume_policy():
    pol = parse_policy('host_volume "data-*" { policy = "write" }')
    acl = new_acl([pol])
    assert acl.allow_host_volume_operation("data-1", "mount-readwrite")
    assert not acl.allow_host_volume_operation("other", "mount-readonly")


# ---------------------------------------------------------------------------
# HTTP enforcement over a live agent
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def acl_agent():
    a = Agent(
        AgentConfig(
            dev_mode=True,
            num_schedulers=1,
            acl_enabled=True,
            name="acl-dev",
        )
    )
    a.start()
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def root_token(acl_agent):
    out = call(acl_agent.http_addr, "/v1/acl/bootstrap", method="POST")
    assert out["Type"] == "management"
    assert out["SecretID"]
    return out["SecretID"]


def test_anonymous_denied(acl_agent, root_token):
    assert call_err(acl_agent.http_addr, "/v1/jobs") == 403


def test_bootstrap_only_once(acl_agent, root_token):
    assert call_err(acl_agent.http_addr, "/v1/acl/bootstrap", method="POST") == 400


def test_management_token_allows(acl_agent, root_token):
    jobs = call(acl_agent.http_addr, "/v1/jobs", token=root_token)
    assert jobs == []


def test_policy_token_lifecycle(acl_agent, root_token):
    base = acl_agent.http_addr
    # create a read-only policy
    call(
        base,
        "/v1/acl/policy/readonly",
        method="PUT",
        body={
            "Name": "readonly",
            "Description": "read only",
            "Rules": 'namespace "default" { policy = "read" }',
        },
        token=root_token,
    )
    pols = call(base, "/v1/acl/policies", token=root_token)
    assert [p["Name"] for p in pols] == ["readonly"]

    # bad rules are rejected
    assert (
        call_err(
            base,
            "/v1/acl/policy/bad",
            method="PUT",
            body={"Name": "bad", "Rules": 'namespace "x" { policy = "nope" }'},
            token=root_token,
        )
        == 400
    )

    # mint a client token bound to the policy
    tok = call(
        base,
        "/v1/acl/token",
        method="PUT",
        body={"Name": "ro", "Type": "client", "Policies": ["readonly"]},
        token=root_token,
    )
    secret = tok["SecretID"]
    assert secret and tok["AccessorID"]

    # token can read but not write
    assert call(base, "/v1/jobs", token=secret) == []
    err = call_err(
        base,
        "/v1/jobs",
        method="PUT",
        body={"Job": {"ID": "x", "TaskGroups": []}},
        token=secret,
    )
    assert err == 403

    # node writes denied too (no node policy)
    assert call_err(base, "/v1/system/gc", method="PUT", token=secret) in (403, 405)

    # token self
    me = call(base, "/v1/acl/token/self", token=secret)
    assert me["AccessorID"] == tok["AccessorID"]

    # management-only endpoints reject client tokens
    assert call_err(base, "/v1/acl/tokens", token=secret) == 403

    # token listing never leaks secrets
    toks = call(base, "/v1/acl/tokens", token=root_token)
    assert all(t["SecretID"] == "" for t in toks)

    # delete the token; it stops resolving
    call(
        base,
        f"/v1/acl/token/{tok['AccessorID']}",
        method="DELETE",
        token=root_token,
    )
    assert call_err(base, "/v1/jobs", token=secret) == 403


def test_bad_token_rejected(acl_agent, root_token):
    assert call_err(acl_agent.http_addr, "/v1/jobs", token="not-a-real-secret") == 403
