"""Agent config files: HCL/JSON load, merge, precedence, agent boot
(reference command/agent/config.go + config_parse.go + their tests)."""

import json

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.agent.config_file import (
    ConfigError,
    apply_file_config,
    load_agent_config,
    load_config_sources,
    merge_config,
)

HCL = """
region     = "euw"
datacenter = "dc7"
name       = "cfg-agent"
bind_addr  = "127.0.0.1"

ports {
  http = 0
  rpc  = 0
}

server {
  enabled          = true
  bootstrap_expect = 1
  num_schedulers   = 3
  default_scheduler_config {
    scheduler_algorithm = "binpack"
  }
}

client {
  enabled    = true
  node_class = "compute"
  meta {
    team = "infra"
  }
  host_volume "data" {
    path = "/srv/data"
  }
}

telemetry {
  statsd_address = "127.0.0.1:8125"
  prefix         = "np"
}
"""


def test_hcl_file_maps_reference_keys(tmp_path):
    f = tmp_path / "agent.hcl"
    f.write_text(HCL)
    cfg = load_agent_config([str(f)])
    assert cfg.region == "euw"
    assert cfg.datacenter == "dc7"
    assert cfg.name == "cfg-agent"
    assert cfg.server_enabled and cfg.client_enabled
    assert cfg.num_schedulers == 3
    assert cfg.scheduler_algorithm == "binpack"
    assert cfg.node_class == "compute"
    assert cfg.meta == {"team": "infra"}
    assert cfg.host_volumes == {"data": "/srv/data"}
    assert cfg.telemetry_statsd_address == "127.0.0.1:8125"
    assert cfg.telemetry_prefix == "np"


def test_chunked_tier_config_keys(tmp_path):
    f = tmp_path / "agent.hcl"
    f.write_text(
        """
server {
  enabled = true
  default_scheduler_config {
    scheduler_algorithm = "tpu_binpack_chunked"
    chunk_k             = 256
    parity_sample_rate  = 0.25
  }
}
"""
    )
    cfg = load_agent_config([str(f)])
    assert cfg.scheduler_algorithm == "tpu_binpack_chunked"
    assert cfg.chunk_k == 256
    assert cfg.parity_sample_rate == 0.25


def test_chunked_tier_knobs_reach_scheduler_config():
    # ServerConfig -> leader-seeded SchedulerConfiguration plumbing
    from nomad_tpu.server.server import Server, ServerConfig

    srv = Server(ServerConfig(
        scheduler_algorithm="tpu_binpack_chunked",
        chunk_k=64,
        parity_sample_rate=0.5,
        num_schedulers=0,
    ))
    try:
        srv.start()
        _, sc = srv.fsm.state.scheduler_config()
        assert sc.scheduler_algorithm == "tpu_binpack_chunked"
        assert sc.chunk_k == 64
        assert sc.parity_sample_rate == 0.5
    finally:
        srv.stop()


def test_json_file_and_directory_merge_order(tmp_path):
    d = tmp_path / "conf.d"
    d.mkdir()
    (d / "00-base.json").write_text(json.dumps({
        "region": "us", "ports": {"http": 1111, "rpc": 2222},
        "server": {"enabled": True, "num_schedulers": 1},
    }))
    (d / "10-override.hcl").write_text(
        'ports { http = 3333 }\nserver { num_schedulers = 5 }\n'
    )
    data = load_config_sources([str(d)])
    # later files merge over earlier, key-by-key (objects deep-merge)
    assert data["ports"] == {"http": 3333, "rpc": 2222}
    assert data["server"] == {"enabled": True, "num_schedulers": 5}
    assert data["region"] == "us"

    cfg = load_agent_config([str(d)])
    assert cfg.http_port == 3333 and cfg.rpc_port == 2222
    assert cfg.num_schedulers == 5


def test_unknown_keys_fail_loudly(tmp_path):
    f = tmp_path / "bad.hcl"
    f.write_text('regon = "typo"\n')
    with pytest.raises(ConfigError, match="regon"):
        load_agent_config([str(f)])
    f2 = tmp_path / "bad2.hcl"
    f2.write_text('server { bootstrap_expct = 3 }\n')
    with pytest.raises(ConfigError, match="bootstrap_expct"):
        load_agent_config([str(f2)])


def test_missing_path_and_bad_volume(tmp_path):
    with pytest.raises(ConfigError, match="does not exist"):
        load_config_sources([str(tmp_path / "nope.hcl")])
    f = tmp_path / "vol.hcl"
    f.write_text('client { host_volume "x" { } }\n')
    with pytest.raises(ConfigError, match="path"):
        load_agent_config([str(f)])


def test_merge_scalars_replace_objects_merge():
    out = merge_config(
        {"a": 1, "o": {"x": 1, "y": 2}, "l": [1, 2]},
        {"a": 9, "o": {"y": 3}, "l": [7]},
    )
    assert out == {"a": 9, "o": {"x": 1, "y": 3}, "l": [7]}


def test_apply_does_not_mutate_base():
    base = AgentConfig()
    cfg = apply_file_config(base, {"region": "apac"})
    assert cfg.region == "apac" and base.region == "global"


def test_agent_boots_from_config_file(tmp_path):
    """The e2e shape: write a file, boot a real agent from it, observe
    the configured identity through the HTTP API."""
    vol = tmp_path / "data"
    vol.mkdir()
    f = tmp_path / "boot.hcl"
    f.write_text(HCL.replace("/srv/data", str(vol)))
    cfg = load_agent_config([str(f)])
    cfg.dev_mode = True  # in-proc raft; ephemeral ports already set
    a = Agent(cfg)
    a.start()
    try:
        from nomad_tpu.api import Client, Config

        api = Client(Config(address=a.http_addr))
        info = api.agent.self()
        assert info["config"]["Region"] == "euw"
        assert info["config"]["Datacenter"] == "dc7"
        assert info["member"]["Name"].startswith("cfg-agent")
        # client node registered with file-configured class + meta
        nodes, _ = api.nodes.list()
        assert nodes and nodes[0]["NodeClass"] == "compute"
    finally:
        a.shutdown()


def test_cli_flags_override_file(tmp_path):
    """defaults < files < flags, via the real CLI path."""
    from nomad_tpu.cli.main import main as cli_main

    f = tmp_path / "agent.hcl"
    f.write_text('region = "filereg"\ndatacenter = "filedc"\n')
    # exercise only the config-assembly path: patch Agent.start via a
    # sentinel agent that records its config and exits immediately
    captured = {}

    class FakeAgent:
        def __init__(self, cfg):
            captured["cfg"] = cfg
            self.http_addr = "http://x"
            self.client = None
            self.server = None

        def start(self):
            raise KeyboardInterrupt  # unwind out of the serve loop

        def shutdown(self):
            pass

    import nomad_tpu.agent as agent_pkg

    orig = agent_pkg.Agent
    agent_pkg.Agent = FakeAgent
    try:
        try:
            cli_main([
                "agent", "-config", str(f), "-dc", "flagdc", "-dev",
            ], out=lambda s: None)
        except KeyboardInterrupt:
            pass
    finally:
        agent_pkg.Agent = orig
    cfg = captured["cfg"]
    assert cfg.region == "filereg"  # from file
    assert cfg.datacenter == "flagdc"  # flag wins over file
    assert cfg.dev_mode
