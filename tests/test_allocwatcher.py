"""Alloc watcher/migrator tests (reference client/allocwatcher):
await-previous-alloc, local sticky move, remote fetch over the FS API,
and the end-to-end reschedule → data-follows-alloc path.
"""
import os
import time

import pytest

from nomad_tpu.client.allocwatcher import PrevAllocWatcher


class FakeRunner:
    def __init__(self, status="running"):
        self.status = status

    def client_status(self):
        return self.status


class FakeAlloc:
    def __init__(self, alloc_id, job=None, task_group="tg"):
        self.id = alloc_id
        self.job = job
        self.task_group = task_group


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


class TestWaitTerminal:
    def test_waits_for_local_runner_terminal(self):
        runner = FakeRunner("running")
        w = PrevAllocWatcher(
            FakeAlloc("new"), "prev",
            local_runner_lookup=lambda a: runner,
            alloc_dir_base="/nonexistent",
            poll_interval=0.01, timeout=5.0,
        )
        import threading

        done = threading.Event()
        t = threading.Thread(target=lambda: (w._wait_terminal(), done.set()))
        t.start()
        time.sleep(0.15)
        assert not done.is_set(), "must still be waiting on a running prev alloc"
        runner.status = "complete"
        t.join(timeout=2.0)
        assert done.is_set()

    def test_unknown_prev_alloc_does_not_block(self):
        w = PrevAllocWatcher(
            FakeAlloc("new"), "prev",
            local_runner_lookup=lambda a: None,
            alloc_dir_base="/nonexistent",
            remote_alloc_info=lambda a: None,  # GC'd
            poll_interval=0.01, timeout=5.0,
        )
        start = time.monotonic()
        w._wait_terminal()
        assert time.monotonic() - start < 1.0

    def test_remote_status_polled(self):
        statuses = iter(["running", "running", "complete"])
        w = PrevAllocWatcher(
            FakeAlloc("new"), "prev",
            local_runner_lookup=lambda a: None,
            alloc_dir_base="/nonexistent",
            remote_alloc_info=lambda a: {"client_status": next(statuses)},
            poll_interval=0.01, timeout=5.0,
        )
        w._wait_terminal()  # returns once the iterator yields terminal


class TestLocalMigration:
    def test_move_and_copy(self, tmp_path):
        src = tmp_path / "prev" / "alloc" / "data"
        src.mkdir(parents=True)
        (src / "state.db").write_text("precious")
        dest = tmp_path / "new" / "alloc" / "data"

        PrevAllocWatcher._migrate_local(str(src), str(dest), move=True)
        assert (dest / "state.db").read_text() == "precious"
        assert os.path.isdir(src) and not os.listdir(src), "moved, dir recreated"

        # copy mode keeps the source
        (src / "again.txt").write_text("x")
        dest2 = tmp_path / "new2" / "alloc" / "data"
        PrevAllocWatcher._migrate_local(str(src), str(dest2), move=False)
        assert (dest2 / "again.txt").read_text() == "x"
        assert (src / "again.txt").exists()


class TestEndToEndMigration:
    def test_data_follows_rescheduled_alloc(self):
        """Job with sticky+migrate ephemeral disk: alloc writes to
        $NOMAD_ALLOC_DIR/data, gets stopped (migrate transition), and the
        replacement alloc finds the data in ITS alloc dir
        (generic_sched.go:630 findPreferredNode + allocwatcher migrate)."""
        from nomad_tpu import mock
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60))
        server.start()
        client = Client(ServerProxy(server), ClientConfig())
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].ephemeral_disk.sticky = True
            job.task_groups[0].ephemeral_disk.migrate = True
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c",
                         "echo payload-42 > $NOMAD_ALLOC_DIR/data/keep.txt; sleep 300"],
            }
            server.register_job(job)

            def first_running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs if a.client_status == "running"]

            wait_until(lambda: first_running(), msg="first alloc running")
            old = first_running()[0]
            marker = os.path.join(client.alloc_dir_base, old.id,
                                  "alloc", "data", "keep.txt")
            wait_until(lambda: os.path.exists(marker), msg="task wrote data")

            server.stop_alloc(old.id)

            def replacement():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs
                        if a.id != old.id and a.client_status == "running"]

            wait_until(lambda: replacement(), timeout=60, msg="replacement alloc")
            new = replacement()[0]
            assert new.previous_allocation == old.id
            migrated = os.path.join(client.alloc_dir_base, new.id,
                                    "alloc", "data", "keep.txt")
            wait_until(lambda: os.path.exists(migrated), msg="data migrated")
            assert open(migrated).read().strip() == "payload-42"
        finally:
            client.shutdown()
            server.stop()


class TestRemoteMigration:
    def test_fetch_tree_over_fs_api(self, tmp_path):
        """Remote prev alloc: the watcher walks ls/cat on the owning
        node's agent (remotePrevAlloc semantics)."""
        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_schedulers=0))
        client = Client(ServerProxy(server), ClientConfig())
        agent = Agent(AgentConfig(name="remote", gossip_enabled=False),
                      server=server, client=client)
        try:
            agent.start()
            # fabricate a terminal prev alloc's data dir on the remote node
            prev_id = "11111111-2222-3333-4444-555555555555"
            data = os.path.join(client.alloc_dir_base, prev_id, "alloc", "data")
            os.makedirs(os.path.join(data, "sub"))
            open(os.path.join(data, "top.txt"), "w").write("T")
            open(os.path.join(data, "sub", "nested.txt"), "w").write("N")

            http_addr = "{}:{}".format(*agent.http.addr)
            w = PrevAllocWatcher(
                FakeAlloc("new"), prev_id,
                local_runner_lookup=lambda a: None,
                alloc_dir_base=str(tmp_path),
                remote_alloc_info=lambda a: {
                    "client_status": "complete", "node_http_addr": http_addr,
                },
            )
            dest = os.path.join(str(tmp_path), "new", "alloc", "data")
            w._migrate_remote(http_addr, dest)
            assert open(os.path.join(dest, "top.txt")).read() == "T"
            assert open(os.path.join(dest, "sub", "nested.txt")).read() == "N"
        finally:
            agent.shutdown()
