"""API SDK tests against a live dev agent (reference api/*_test.go driven by
testutil.TestServer — here in-process instead of fork-exec)."""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.agent.jsonapi import dumps, loads
from nomad_tpu.api import APIError, Client, Config, QueryOptions
from nomad_tpu.structs.structs import RestartPolicy

import json


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(dev_mode=True, num_schedulers=2, name="sdk-dev"))
    a.start()
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def client(agent):
    return Client(Config(address=agent.http_addr))


def service_job_json(job_id: str, count: int = 1):
    job = mock.job()
    job.id = job_id
    job.name = job_id
    job.task_groups[0].count = count
    task = job.task_groups[0].tasks[0]
    task.driver = "mock"
    task.config = {"run_for": "10s"}
    task.restart_policy = RestartPolicy(attempts=0, mode="fail")
    return json.loads(dumps(job))


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_agent_and_status(client):
    info = client.agent.self()
    assert info["config"]["Server"]["Enabled"] is True
    assert client.agent.health()["server"]["ok"]
    assert ":" in client.status.leader()
    assert client.regions.list() == ["global"]


def test_job_lifecycle(client):
    jobs, meta = client.jobs.list()
    assert jobs == []

    out, wm = client.jobs.register(service_job_json("sdk-job", count=2))
    assert out["EvalID"]
    assert wm.last_index > 0

    info, qm = client.jobs.info("sdk-job")
    assert info["ID"] == "sdk-job"
    assert qm.last_index > 0

    # allocations eventually placed by the scheduler
    wait_for(
        lambda: len(client.jobs.allocations("sdk-job")[0]) == 2,
        msg="allocs placed",
    )
    allocs, _ = client.jobs.allocations("sdk-job")
    assert {a["JobID"] for a in allocs} == {"sdk-job"}

    evals, _ = client.jobs.evaluations("sdk-job")
    assert evals and evals[0]["JobID"] == "sdk-job"

    ev, _ = client.evaluations.info(evals[0]["ID"])
    assert ev["ID"] == evals[0]["ID"]

    alloc, _ = client.allocations.info(allocs[0]["ID"])
    assert alloc["ID"] == allocs[0]["ID"]

    summary, _ = client.jobs.summary("sdk-job")
    assert summary["JobID"] == "sdk-job"

    out, _ = client.jobs.deregister("sdk-job", purge=True)
    assert out["EvalID"]


def test_blocking_query_wakes_on_write(client):
    _, meta = client.jobs.list()
    idx = meta.last_index
    results = {}

    def blocker():
        # standard long-poll loop: any write wakes the query; re-issue with
        # the returned index until the object of interest shows up
        wait_index = idx
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            jobs, m2 = client.jobs.list(
                QueryOptions(wait_index=wait_index, wait_time="10s")
            )
            results["jobs"] = jobs
            results["index"] = m2.last_index
            if any(j["ID"] == "sdk-block" for j in jobs):
                return
            wait_index = max(wait_index + 1, m2.last_index)

    t = threading.Thread(target=blocker)
    t.start()
    time.sleep(0.2)
    client.jobs.register(service_job_json("sdk-block"))
    t.join(timeout=10)
    assert not t.is_alive()
    assert results["index"] > idx
    assert any(j["ID"] == "sdk-block" for j in results["jobs"])
    client.jobs.deregister("sdk-block", purge=True)


def test_nodes_api(client):
    wait_for(lambda: len(client.nodes.list()[0]) == 1, msg="node registered")
    nodes, _ = client.nodes.list()
    node_id = nodes[0]["ID"]
    info, _ = client.nodes.info(node_id)
    assert info["ID"] == node_id
    allocs, _ = client.nodes.allocations(node_id)
    assert isinstance(allocs, list)

    out, _ = client.nodes.toggle_eligibility(node_id, eligible=False)
    info, _ = client.nodes.info(node_id)
    assert info["SchedulingEligibility"] == "ineligible"
    client.nodes.toggle_eligibility(node_id, eligible=True)


def test_parse_and_plan_and_validate(client):
    hcl = 'job "planme" { datacenters=["dc1"] group "g" { task "t" { driver="mock" config { run_for = "5s" } } } }'
    parsed = client.jobs.parse_hcl(hcl)
    assert parsed["ID"] == "planme"

    res = client.jobs.validate(parsed)[0]
    assert res["ValidationErrors"] == []

    plan, _ = client.jobs.plan(parsed, diff=True)
    assert plan["Diff"]["Type"] in ("Added", "added", "Edited", "None")


def test_operator_api(client):
    cfg, _ = client.operator.scheduler_get_configuration()
    assert "SchedulerConfig" in cfg
    raft, _ = client.operator.raft_get_configuration()
    assert raft["Servers"]


def test_search(client):
    client.jobs.register(service_job_json("searchable-job"))
    out = client.search.prefix_search("searchable", context="jobs")
    assert out["Matches"]["jobs"] == ["searchable-job"]
    assert out["Truncations"]["jobs"] is False
    out = client.search.prefix_search("searchable", context="all")
    assert "nodes" in out["Matches"]
    with pytest.raises(APIError):
        client.search.prefix_search("x", context="bogus")
    client.jobs.deregister("searchable-job", purge=True)


def test_api_error_shape(client):
    with pytest.raises(APIError) as ei:
        client.jobs.info("does-not-exist")
    assert ei.value.code == 404
