"""Artifact fetching + template rendering hooks (reference
taskrunner/artifact_hook.go + go-getter; taskrunner/template/template.go
+ consul-template): unit coverage of the fetchers/renderers, and an
end-to-end job whose task downloads an artifact from a local HTTP
server, renders a template from the mock Consul KV, and restarts when
the KV value changes.
"""
import hashlib
import http.server
import os
import socketserver
import tarfile
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.artifacts import ArtifactError, fetch_artifact
from nomad_tpu.client.template import TemplateError, TemplateHook
from nomad_tpu.integrations.consul import ConsulClient, ConsulConfig, MockConsulServer


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def http_files(tmp_path):
    """Local HTTP server serving tmp_path; yields (base_url, dir)."""
    root = tmp_path / "www"
    root.mkdir()

    class Handler(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(root), **kw)

        def log_message(self, fmt, *args):
            pass

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", root
    finally:
        srv.shutdown()
        srv.server_close()


@pytest.fixture
def consul():
    srv = MockConsulServer().start()
    yield srv
    srv.stop()


class TestArtifacts:
    def test_http_download_with_checksum(self, http_files, tmp_path):
        base, root = http_files
        (root / "app.bin").write_bytes(b"the payload")
        digest = hashlib.sha256(b"the payload").hexdigest()
        task_root = tmp_path / "task"
        task_root.mkdir()
        fetch_artifact(
            {"source": f"{base}/app.bin",
             "options": {"checksum": f"sha256:{digest}"}},
            str(task_root),
        )
        assert (task_root / "local" / "app.bin").read_bytes() == b"the payload"

    def test_checksum_mismatch_fails(self, http_files, tmp_path):
        base, root = http_files
        (root / "app.bin").write_bytes(b"the payload")
        task_root = tmp_path / "task"
        task_root.mkdir()
        with pytest.raises(ArtifactError, match="checksum mismatch"):
            fetch_artifact(
                {"source": f"{base}/app.bin",
                 "options": {"checksum": "sha256:" + "0" * 64}},
                str(task_root),
            )

    def test_bare_hex_checksum_length_detected(self, http_files, tmp_path):
        base, root = http_files
        (root / "a.txt").write_bytes(b"x")
        md5 = hashlib.md5(b"x").hexdigest()
        task_root = tmp_path / "task"
        task_root.mkdir()
        fetch_artifact(
            {"source": f"{base}/a.txt", "options": {"checksum": md5}},
            str(task_root),
        )

    def test_archive_unpacks(self, http_files, tmp_path):
        base, root = http_files
        payload = tmp_path / "inner.txt"
        payload.write_text("inside")
        with tarfile.open(root / "bundle.tar.gz", "w:gz") as t:
            t.add(payload, arcname="inner.txt")
        task_root = tmp_path / "task"
        task_root.mkdir()
        fetch_artifact(
            {"source": f"{base}/bundle.tar.gz", "destination": "local/pkg"},
            str(task_root),
        )
        assert (task_root / "local" / "pkg" / "inner.txt").read_text() == "inside"
        assert not (task_root / "local" / "pkg" / "bundle.tar.gz").exists()

    def test_destination_escape_rejected(self, tmp_path):
        task_root = tmp_path / "task"
        task_root.mkdir()
        with pytest.raises(ArtifactError, match="escapes"):
            fetch_artifact(
                {"source": "file:///etc/hostname", "destination": "../../evil"},
                str(task_root),
            )

    def test_missing_source_fails(self, tmp_path):
        with pytest.raises(ArtifactError):
            fetch_artifact({"source": ""}, str(tmp_path))


class TestTemplateHook:
    def _hook(self, templates, tmp_path, consul_srv=None, vault_read=None,
              restart_cb=None, signal_cb=None, poll=0.05, block=2.0):
        consul_client = None
        if consul_srv is not None:
            consul_client = ConsulClient(ConsulConfig(address=consul_srv.address))
        return TemplateHook(
            templates, str(tmp_path),
            consul=consul_client, vault_read=vault_read,
            env_fn=lambda: {"NODE": "n1"},
            restart_cb=restart_cb, signal_cb=signal_cb,
            poll_interval=poll, block_timeout=block,
        )

    def test_render_key_env_secret(self, consul, tmp_path):
        consul.kv["app/db_host"] = "db.internal"
        secrets = {"secret/creds": {"password": "hunter2"}}
        hook = self._hook(
            [{"data": 'host={{ key "app/db_host" }} node={{ env "NODE" }} '
                      'pw={{ secret "secret/creds" "password" }}',
              "destination": "local/app.conf"}],
            tmp_path, consul, vault_read=lambda p: secrets.get(p),
        )
        hook.prestart()
        out = (tmp_path / "local" / "app.conf").read_text()
        assert out == "host=db.internal node=n1 pw=hunter2"

    def test_prestart_blocks_until_key_exists(self, consul, tmp_path):
        hook = self._hook(
            [{"data": 'v={{ key "late/key" }}', "destination": "local/v"}],
            tmp_path, consul, block=5.0,
        )
        t = threading.Thread(target=hook.prestart)
        t.start()
        time.sleep(0.3)
        assert not (tmp_path / "local" / "v").exists()
        consul.kv["late/key"] = "arrived"
        t.join(timeout=5)
        assert not t.is_alive()
        assert (tmp_path / "local" / "v").read_text() == "v=arrived"

    def test_prestart_timeout(self, consul, tmp_path):
        hook = self._hook(
            [{"data": '{{ key "never" }}', "destination": "local/x"}],
            tmp_path, consul, block=0.3,
        )
        with pytest.raises(TemplateError, match="timed out"):
            hook.prestart()

    def test_change_mode_restart_and_signal(self, consul, tmp_path):
        consul.kv["a"] = "1"
        consul.kv["b"] = "1"
        restarts = []
        signals = []
        hook = self._hook(
            [{"data": '{{ key "a" }}', "destination": "local/a",
              "change_mode": "restart"},
             {"data": '{{ key "b" }}', "destination": "local/b",
              "change_mode": "signal", "change_signal": "SIGUSR1"}],
            tmp_path, consul,
            restart_cb=lambda: restarts.append(1),
            signal_cb=lambda s: signals.append(s),
        )
        hook.prestart()
        hook.start_watcher()
        try:
            consul.kv["b"] = "2"
            wait_until(lambda: signals == ["SIGUSR1"], msg="signal applied")
            assert (tmp_path / "local" / "b").read_text() == "2"
            assert not restarts
            consul.kv["a"] = "2"
            wait_until(lambda: restarts, msg="restart applied")
            assert (tmp_path / "local" / "a").read_text() == "2"
        finally:
            hook.stop()

    def test_change_mode_noop(self, consul, tmp_path):
        consul.kv["c"] = "1"
        restarts = []
        hook = self._hook(
            [{"data": '{{ key "c" }}', "destination": "local/c",
              "change_mode": "noop"}],
            tmp_path, consul, restart_cb=lambda: restarts.append(1),
        )
        hook.prestart()
        hook.start_watcher()
        try:
            consul.kv["c"] = "2"
            wait_until(lambda: (tmp_path / "local" / "c").read_text() == "2",
                       msg="re-render")
            assert not restarts
        finally:
            hook.stop()

    def test_destination_escape_rejected(self, consul, tmp_path):
        hook = self._hook(
            [{"data": "x", "destination": "../../evil"}], tmp_path, consul,
        )
        with pytest.raises(TemplateError, match="escapes"):
            hook.prestart()

    def test_perms(self, consul, tmp_path):
        hook = self._hook(
            [{"data": "s3cret", "destination": "secrets/token",
              "perms": "600"}], tmp_path, consul,
        )
        hook.prestart()
        mode = os.stat(tmp_path / "secrets" / "token").st_mode & 0o777
        assert mode == 0o600


class TestVaultTemplateEndToEnd:
    def test_secret_rendered_with_task_token(self, consul):
        """{{ secret }} reads use the TASK's derived Vault token against
        the configured Vault address."""
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.integrations.vault import MockVaultServer, VaultConfig
        from nomad_tpu.server.server import Server, ServerConfig

        vault = MockVaultServer().start()
        vault.secrets["secret/app"] = {"api_key": "k-123"}
        server = Server(ServerConfig(
            num_schedulers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=60,
            vault=VaultConfig(enabled=True, address=vault.address, token="root"),
        ))
        server.start()
        client = Client(ServerProxy(server), ClientConfig(
            vault_addr=vault.address,
        ))
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh", "args": ["-c", "sleep 60"]}
            task.resources.networks = []
            task.vault = {"policies": ["app-read"]}
            task.templates = [{
                "data": 'key={{ secret "secret/app" "api_key" }}',
                "destination": "secrets/app.env",
                "perms": "600",
            }]
            server.register_job(job)

            def running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs if a.client_status == "running"]

            wait_until(lambda: running(), msg="alloc running")
            alloc = running()[0]
            tr = client.allocrunners[alloc.id].task_runners[task.name]
            dest = os.path.join(tr.task_dir.secrets_dir, "app.env")
            assert open(dest).read() == "key=k-123"
            assert os.stat(dest).st_mode & 0o777 == 0o600
        finally:
            client.shutdown()
            server.stop()
            vault.stop()


class TestEndToEnd:
    def test_artifact_template_restart_on_change(self, http_files, consul):
        """The VERDICT's done-condition: a job whose task fetches an
        artifact from a local HTTP server and renders a template from
        the mock Consul, restarting when the KV value changes."""
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        base, root = http_files
        (root / "app.sh").write_bytes(b"#!/bin/sh\nsleep 60\n")
        digest = hashlib.sha256((root / "app.sh").read_bytes()).hexdigest()
        consul.kv["cfg/message"] = "v1"

        server = Server(ServerConfig(
            num_schedulers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=60,
        ))
        server.start()
        client = Client(ServerProxy(server), ClientConfig(
            consul=ConsulConfig(address=consul.address),
        ))
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh",
                           "args": ["local/app.sh"]}
            task.resources.networks = []
            task.artifacts = [{
                "source": f"{base}/app.sh",
                "options": {"checksum": f"sha256:{digest}"},
            }]
            task.templates = [{
                "data": 'message={{ key "cfg/message" }}',
                "destination": "local/app.conf",
                "change_mode": "restart",
            }]
            server.register_job(job)

            def running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs if a.client_status == "running"]

            wait_until(lambda: running(), msg="alloc running")
            alloc = running()[0]
            ar = client.allocrunners[alloc.id]
            tr = ar.task_runners[task.name]
            # artifact downloaded + template rendered
            art = os.path.join(tr.task_dir.local_dir, "app.sh")
            conf = os.path.join(tr.task_dir.local_dir, "app.conf")
            assert os.path.exists(art)
            assert open(conf).read() == "message=v1"

            # KV change -> re-render + restart
            consul.kv["cfg/message"] = "v2"
            wait_until(lambda: open(conf).read() == "message=v2",
                       msg="template re-render")
            wait_until(
                lambda: any(e.type == "Restarting" for e in tr.events),
                msg="restart on template change",
            )
            wait_until(lambda: running(), msg="alloc running again")
        finally:
            client.shutdown()
            server.stop()
