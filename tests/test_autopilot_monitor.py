"""Autopilot + agent monitor + debug endpoint tests (reference
nomad/autopilot.go, command/agent/monitor, http.go pprof gating)."""
import json
import logging
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu.agent.agent import Agent, AgentConfig
from nomad_tpu.server.autopilot import Autopilot, AutopilotConfig


def wait_until(fn, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def http(agent, path, method="GET", body=None, raw=False):
    req = urllib.request.Request(
        agent.http_addr + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    with urllib.request.urlopen(req) as r:
        data = r.read()
    return data if raw else json.loads(data)


@pytest.fixture
def agent():
    a = Agent(AgentConfig(name="ap", gossip_enabled=False, enable_debug=True,
                          num_schedulers=0)).start()
    yield a
    a.shutdown()


class TestAutopilotEndpoints:
    def test_config_get_set(self, agent):
        cfg = http(agent, "/v1/operator/autopilot/configuration")
        assert cfg["CleanupDeadServers"] is True
        http(agent, "/v1/operator/autopilot/configuration", method="PUT",
             body={"CleanupDeadServers": False, "LastContactThresholdS": 5.0})
        cfg = http(agent, "/v1/operator/autopilot/configuration")
        assert cfg["CleanupDeadServers"] is False
        # raft-replicated: visible in state
        _, stored = agent.server.fsm.state.autopilot_config()
        assert stored.cleanup_dead_servers is False

    def test_health_single_server(self, agent):
        out = http(agent, "/v1/operator/autopilot/health")
        assert out["Healthy"] is True
        assert len(out["Servers"]) == 1
        assert out["Servers"][0]["SerfStatus"] == "alive"


class TestDeadServerCleanup:
    def test_prunes_failed_peer_within_quorum(self):
        """Leader removes a gossip-failed raft peer only while quorum
        holds (autopilot.go pruneDeadServers)."""

        class FakeRaft:
            def __init__(self):
                self.peers = {"a.global": 1, "b.global": 2, "c.global": 3,
                              "d.global": 4}
                self.commit_index = 10
                self.match_index = {}
                self.removed = []

            def remove_peer(self, pid):
                self.peers.pop(pid, None)
                self.removed.append(pid)

        class FakeMember:
            def __init__(self, name, status):
                self.name, self.status = name, status

        class FakeMembership:
            class memberlist:
                class config:
                    name = "self.global"

            def members(self):
                return [FakeMember("self.global", "alive"),
                        FakeMember("a.global", "alive"),
                        FakeMember("b.global", "alive"),
                        FakeMember("c.global", "dead"),
                        FakeMember("d.global", "dead")]

            def servers_in_region(self):
                return []

        class FakeServer:
            is_leader = True

            class fsm:
                class state:
                    autopilot_config_entry = None
                    latest_index = 10

            name = "self"

        raft = FakeRaft()
        ap = Autopilot(FakeServer(), membership=FakeMembership(), wire_raft=raft)
        removed = ap.prune_dead_servers()
        # cluster of 5 (4 peers + self), quorum 3 → at most 2 removable
        assert sorted(removed) == ["c.global", "d.global"]
        assert "a.global" in raft.peers and "b.global" in raft.peers

    def test_never_breaks_quorum(self):
        class FakeRaft:
            def __init__(self):
                self.peers = {"a.global": 1, "b.global": 2}
                self.commit_index = 0
                self.match_index = {}

            def remove_peer(self, pid):
                self.peers.pop(pid, None)

        class FakeMember:
            def __init__(self, name, status):
                self.name, self.status = name, status

        class FakeMembership:
            class memberlist:
                class config:
                    name = "self.global"

            def members(self):
                # both peers dead: removing both would leave a 1-node
                # "cluster" — only one removal keeps quorum semantics
                return [FakeMember("self.global", "alive"),
                        FakeMember("a.global", "dead"),
                        FakeMember("b.global", "dead")]

            def servers_in_region(self):
                return []

        class FakeServer:
            is_leader = True

            class fsm:
                class state:
                    autopilot_config_entry = None
                    latest_index = 0

            name = "self"

        raft = FakeRaft()
        ap = Autopilot(FakeServer(), membership=FakeMembership(), wire_raft=raft)
        removed = ap.prune_dead_servers()
        assert len(removed) == 1, "3-node cluster, quorum 2: only 1 removable"


class TestMonitorAndDebug:
    def test_monitor_tails_logs(self, agent):
        out = http(agent, "/v1/agent/monitor?log_level=warn")
        seq = out["Seq"]
        logging.getLogger("nomad_tpu.test").warning("monitor-probe-123")
        wait_until(
            lambda: any("monitor-probe-123" in l for l in http(
                agent, f"/v1/agent/monitor?log_level=warn&seq={seq}")["Lines"]),
            msg="log line visible in monitor",
        )
        # polling from the returned seq doesn't replay old lines
        out2 = http(agent, f"/v1/agent/monitor?log_level=warn&seq={seq}")
        out3 = http(agent, f"/v1/agent/monitor?log_level=warn&seq={out2['Seq']}")
        assert not any("monitor-probe-123" in l for l in out3["Lines"])

    def test_pprof_threads_and_heap(self, agent):
        dump = http(agent, "/v1/agent/pprof?type=threads", raw=True)
        assert b"--- thread" in dump and b"MainThread" in dump
        heap = http(agent, "/v1/agent/pprof?type=heap")
        assert heap["TotalObjects"] > 0 and heap["TopTypes"]

    def test_pprof_gated(self):
        a = Agent(AgentConfig(name="nodebug", gossip_enabled=False,
                              num_schedulers=0)).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                http(a, "/v1/agent/pprof?type=threads", raw=True)
            assert e.value.code == 404
        finally:
            a.shutdown()
