"""Blocked-evals tracker tests, mirroring reference
nomad/blocked_evals_test.go: class-keyed unblocking (captured vs escaped),
per-job dedup (latest wins), missed-unblock protection via snapshot
indexes, system (node-keyed) blocks, the failed (max-plans) queue, and
untracking.
"""
import time

from nomad_tpu import mock
from nomad_tpu.server.blocked_evals import BlockedEvals
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.structs.structs import EVAL_TRIGGER_MAX_PLANS


def make_blocked(job_id=None, classes=None, escaped=False, snapshot=0,
                 node_id="", create_index=1):
    ev = mock.eval()
    if job_id:
        ev.job_id = job_id
    ev.status = "blocked"
    ev.class_eligibility = dict(classes or {})
    ev.escaped_computed_class = escaped
    ev.snapshot_index = snapshot
    ev.node_id = node_id
    ev.create_index = create_index
    return ev


def harness():
    broker = EvalBroker()
    broker.set_enabled(True)
    b = BlockedEvals(broker)
    b.set_enabled(True)
    return broker, b


def drain(broker, timeout=1.0):
    out = []
    while True:
        ev, tok = broker.dequeue(
            ["service", "batch", "system", "_failed"], timeout=timeout
        )
        if ev is None:
            return out
        broker.ack(ev.id, tok)
        out.append(ev)
        timeout = 0.1


class TestClassUnblock:
    def test_unblock_on_eligible_class(self):
        broker, b = harness()
        ev = make_blocked(classes={"web": True, "gpu": False})
        b.block(ev)
        assert b.stats()["total_blocked"] == 1
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]
        assert got[0].status == "pending"

    def test_no_unblock_on_ineligible_class(self):
        broker, b = harness()
        ev = make_blocked(classes={"gpu": False})
        b.block(ev)
        b.unblock("gpu", index=10)
        assert drain(broker, timeout=0.2) == []
        assert b.stats()["total_blocked"] == 1

    def test_unseen_class_unblocks(self):
        """Capacity in a class the eval never evaluated is new capacity
        (blocked_evals_test.go TestBlockedEvals_UnblockUnknown)."""
        broker, b = harness()
        ev = make_blocked(classes={"web": False})
        b.block(ev)
        b.unblock("brand-new-class", index=10)
        assert len(drain(broker)) == 1

    def test_escaped_unblocks_on_any_class(self):
        broker, b = harness()
        ev = make_blocked(escaped=True)
        b.block(ev)
        assert b.stats()["total_escaped"] == 1
        b.unblock("anything", index=10)
        assert len(drain(broker)) == 1


class TestMissedUnblock:
    def test_capacity_after_snapshot_reenqueues_immediately(self):
        """A block whose snapshot predates a seen unblock never parks
        (blocked_evals.go:202 missed-unblock window)."""
        broker, b = harness()
        b.unblock("web", index=50)
        drain(broker, timeout=0.1)
        ev = make_blocked(classes={"web": True}, snapshot=40)
        b.block(ev)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id], "must re-enqueue, not block"
        assert b.stats()["total_blocked"] == 0

    def test_capacity_before_snapshot_blocks(self):
        broker, b = harness()
        b.unblock("web", index=50)
        drain(broker, timeout=0.1)
        ev = make_blocked(classes={"web": True}, snapshot=60)
        b.block(ev)
        assert b.stats()["total_blocked"] == 1


class TestJobDedup:
    def test_latest_eval_per_job_wins(self):
        broker, b = harness()
        old = make_blocked(job_id="dup", classes={"web": True}, create_index=5)
        new = make_blocked(job_id="dup", classes={"web": True}, create_index=9)
        b.block(old)
        b.block(new)
        assert b.stats()["total_blocked"] == 1
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [new.id]

    def test_older_eval_dropped(self):
        broker, b = harness()
        new = make_blocked(job_id="dup2", create_index=9, classes={"web": True})
        old = make_blocked(job_id="dup2", create_index=5, classes={"web": True})
        b.block(new)
        b.block(old)
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [new.id]

    def test_untrack_removes_jobs_blocks(self):
        broker, b = harness()
        ev = make_blocked(job_id="gone", classes={"web": True})
        b.block(ev)
        b.untrack("default", "gone")
        b.unblock("web", index=10)
        assert drain(broker, timeout=0.2) == []


class TestSystemAndFailed:
    def test_node_keyed_system_block(self):
        """System evals block per node and release via unblock_node."""
        broker, b = harness()
        ev = make_blocked(node_id="node-1", classes={})
        ev.type = "system"
        b.block(ev)
        b.unblock_node("node-2", index=5)
        assert drain(broker, timeout=0.2) == []
        b.unblock_node("node-1", index=6)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]

    def test_max_plans_failed_queue(self):
        """Plan-rejection storms park in the failed set until
        unblock_failed sweeps them back (the safety valve)."""
        broker, b = harness()
        ev = make_blocked(classes={"web": True})
        ev.triggered_by = EVAL_TRIGGER_MAX_PLANS
        b.block(ev)
        # class capacity does NOT release failed evals
        b.unblock("web", index=10)
        assert drain(broker, timeout=0.2) == []
        b.unblock_failed()
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]
