"""Blocked-evals tracker tests, mirroring reference
nomad/blocked_evals_test.go: class-keyed unblocking (captured vs escaped),
per-job dedup (latest wins), missed-unblock protection via snapshot
indexes, system (node-keyed) blocks, the failed (max-plans) queue, and
untracking — plus the coalesced unblock-storm path (windowed batching,
the max_batch spike bound, cross-trigger dedup, the unblock_enqueue
fault's defer-and-retry, and flush-on-leadership-loss).
"""
import time

from nomad_tpu import mock
from nomad_tpu.chaos.injector import ChaosInjector
from nomad_tpu.server.blocked_evals import BlockedEvals
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.structs.structs import EVAL_TRIGGER_MAX_PLANS


def make_blocked(job_id=None, classes=None, escaped=False, snapshot=0,
                 node_id="", create_index=1):
    ev = mock.eval()
    if job_id:
        ev.job_id = job_id
    ev.status = "blocked"
    ev.class_eligibility = dict(classes or {})
    ev.escaped_computed_class = escaped
    ev.snapshot_index = snapshot
    ev.node_id = node_id
    ev.create_index = create_index
    return ev


def harness(coalesce_window_s=0.0, max_batch=512):
    broker = EvalBroker()
    broker.set_enabled(True)
    b = BlockedEvals(broker, coalesce_window_s=coalesce_window_s,
                     max_batch=max_batch)
    b.set_enabled(True)
    return broker, b


def wait_ready(broker, n, timeout=2.0):
    """Spin until the broker holds ``n`` ready evals (coalesced flushes
    land on a timer thread)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if broker.stats()["total_ready"] >= n:
            return
        time.sleep(0.005)
    raise AssertionError(
        f"broker never reached {n} ready: {broker.stats()}")


def drain(broker, timeout=1.0):
    out = []
    while True:
        ev, tok = broker.dequeue(
            ["service", "batch", "system", "_failed"], timeout=timeout
        )
        if ev is None:
            return out
        broker.ack(ev.id, tok)
        out.append(ev)
        timeout = 0.1


class TestClassUnblock:
    def test_unblock_on_eligible_class(self):
        broker, b = harness()
        ev = make_blocked(classes={"web": True, "gpu": False})
        b.block(ev)
        assert b.stats()["total_blocked"] == 1
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]
        assert got[0].status == "pending"

    def test_no_unblock_on_ineligible_class(self):
        broker, b = harness()
        ev = make_blocked(classes={"gpu": False})
        b.block(ev)
        b.unblock("gpu", index=10)
        assert drain(broker, timeout=0.2) == []
        assert b.stats()["total_blocked"] == 1

    def test_unseen_class_unblocks(self):
        """Capacity in a class the eval never evaluated is new capacity
        (blocked_evals_test.go TestBlockedEvals_UnblockUnknown)."""
        broker, b = harness()
        ev = make_blocked(classes={"web": False})
        b.block(ev)
        b.unblock("brand-new-class", index=10)
        assert len(drain(broker)) == 1

    def test_escaped_unblocks_on_any_class(self):
        broker, b = harness()
        ev = make_blocked(escaped=True)
        b.block(ev)
        assert b.stats()["total_escaped"] == 1
        b.unblock("anything", index=10)
        assert len(drain(broker)) == 1


class TestMissedUnblock:
    def test_capacity_after_snapshot_reenqueues_immediately(self):
        """A block whose snapshot predates a seen unblock never parks
        (blocked_evals.go:202 missed-unblock window)."""
        broker, b = harness()
        b.unblock("web", index=50)
        drain(broker, timeout=0.1)
        ev = make_blocked(classes={"web": True}, snapshot=40)
        b.block(ev)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id], "must re-enqueue, not block"
        assert b.stats()["total_blocked"] == 0

    def test_capacity_before_snapshot_blocks(self):
        broker, b = harness()
        b.unblock("web", index=50)
        drain(broker, timeout=0.1)
        ev = make_blocked(classes={"web": True}, snapshot=60)
        b.block(ev)
        assert b.stats()["total_blocked"] == 1


class TestJobDedup:
    def test_latest_eval_per_job_wins(self):
        broker, b = harness()
        old = make_blocked(job_id="dup", classes={"web": True}, create_index=5)
        new = make_blocked(job_id="dup", classes={"web": True}, create_index=9)
        b.block(old)
        b.block(new)
        assert b.stats()["total_blocked"] == 1
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [new.id]

    def test_older_eval_dropped(self):
        broker, b = harness()
        new = make_blocked(job_id="dup2", create_index=9, classes={"web": True})
        old = make_blocked(job_id="dup2", create_index=5, classes={"web": True})
        b.block(new)
        b.block(old)
        b.unblock("web", index=10)
        got = drain(broker)
        assert [e.id for e in got] == [new.id]

    def test_untrack_removes_jobs_blocks(self):
        broker, b = harness()
        ev = make_blocked(job_id="gone", classes={"web": True})
        b.block(ev)
        b.untrack("default", "gone")
        b.unblock("web", index=10)
        assert drain(broker, timeout=0.2) == []


class TestSystemAndFailed:
    def test_node_keyed_system_block(self):
        """System evals block per node and release via unblock_node."""
        broker, b = harness()
        ev = make_blocked(node_id="node-1", classes={})
        ev.type = "system"
        b.block(ev)
        b.unblock_node("node-2", index=5)
        assert drain(broker, timeout=0.2) == []
        b.unblock_node("node-1", index=6)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]

    def test_max_plans_failed_queue(self):
        """Plan-rejection storms park in the failed set until
        unblock_failed sweeps them back (the safety valve)."""
        broker, b = harness()
        ev = make_blocked(classes={"web": True})
        ev.triggered_by = EVAL_TRIGGER_MAX_PLANS
        b.block(ev)
        # class capacity does NOT release failed evals
        b.unblock("web", index=10)
        assert drain(broker, timeout=0.2) == []
        b.unblock_failed()
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]


class TestCoalescedStorm:
    def test_window_batches_triggers_into_one_enqueue(self):
        """With a coalesce window, an unblock trigger stages instead of
        enqueueing; the timer flush lands the whole set as ONE batch."""
        broker, b = harness(coalesce_window_s=0.03)
        evs = [make_blocked(job_id=f"j{i}", classes={"web": True})
               for i in range(4)]
        for ev in evs:
            b.block(ev)
        b.block(make_blocked(job_id="esc", escaped=True))
        b.unblock("web", index=10)
        st = b.stats()
        assert st["pending_unblocks"] == 5, "staged, not yet enqueued"
        assert st["unblock_batches"] == 0
        wait_ready(broker, 5)
        got = drain(broker)
        assert len(got) == 5
        st = b.stats()
        assert st["unblock_batches"] == 1
        assert st["unblocks_total"] == 5
        assert st["pending_unblocks"] == 0

    def test_reblock_between_triggers_dedups_keeping_max_index(self):
        """The storm race: an eval unblocked by one trigger re-blocks and
        a second trigger collects it again inside the same window — it
        must re-enqueue ONCE, carrying the highest capacity index it
        witnessed (else its refreshed snapshot misses the later change
        and the next block spuriously parks)."""
        broker, b = harness(coalesce_window_s=0.05)
        ev = make_blocked(job_id="racer", classes={"web": True})
        b.block(ev)
        b.unblock("web", index=5)
        assert b.stats()["pending_unblocks"] == 1
        # re-block at a snapshot covering index 5 (a fresh scheduling
        # attempt that saw the new capacity and still failed) — a stale
        # snapshot would take the missed-unblock fast path instead
        ev.snapshot_index = 5
        b.block(ev)                    # re-blocks while staged
        b.unblock("web", index=7)      # second trigger, same window
        wait_ready(broker, 1)
        got = drain(broker)
        assert [e.id for e in got] == [ev.id]
        assert got[0].snapshot_index == 7, "must keep the max index"
        st = b.stats()
        assert st["unblock_dups_coalesced"] == 1
        assert st["unblocks_total"] == 1

    def test_flushed_snapshot_covers_unblock_index(self):
        """The re-enqueued copy's snapshot_index equals the unblock
        index, so re-blocking at that snapshot parks instead of spinning
        through the missed-unblock fast path forever."""
        broker, b = harness()
        ev = make_blocked(job_id="rt", classes={"web": True}, snapshot=3)
        b.block(ev)
        b.unblock("web", index=10)
        got = drain(broker)
        assert got[0].snapshot_index == 10
        reblocked = make_blocked(job_id="rt", classes={"web": True},
                                 snapshot=got[0].snapshot_index)
        b.block(reblocked)
        assert b.stats()["total_blocked"] == 1, \
            "snapshot at the unblock index must park, not re-enqueue"

    def test_max_batch_bounds_each_windowed_flush(self):
        """A storm bigger than max_batch drains as bounded batches, the
        remainder deferring one window at a time."""
        broker, b = harness(coalesce_window_s=0.02, max_batch=4)
        for i in range(10):
            b.block(make_blocked(job_id=f"s{i}", classes={"web": True}))
        b.unblock("web", index=10)
        wait_ready(broker, 10)
        assert len(drain(broker)) == 10
        st = b.stats()
        assert st["unblock_batches"] == 3          # 4 + 4 + 2
        assert st["unblocks_total"] == 10
        assert st["unblock_deferred"] == 2

    def test_sync_mode_drains_all_batches_at_once(self):
        """coalesce_window_s == 0 keeps unblock-then-ready semantics:
        the flush loops every capped batch synchronously."""
        broker, b = harness(max_batch=4)
        for i in range(10):
            b.block(make_blocked(job_id=f"y{i}", classes={"web": True}))
        b.unblock("web", index=10)
        assert broker.stats()["total_ready"] == 10, "no window, no wait"
        st = b.stats()
        assert st["unblock_batches"] == 3
        assert st["unblock_deferred"] == 0

    def test_unblock_enqueue_fault_defers_then_retries(self):
        """An injected unblock_enqueue fault re-parks the batch and a
        backoff timer retries it — degrade, never drop."""
        broker, b = harness()
        inj = ChaosInjector(seed=0)
        inj.arm("unblock_enqueue", mode="fail", prob=1.0, max_fires=1)
        try:
            for i in range(3):
                b.block(make_blocked(job_id=f"f{i}", classes={"web": True}))
            b.unblock("web", index=10)
            # the one-shot fault consumed the synchronous flush: the
            # batch is parked, nothing reached the broker yet
            assert b.stats()["pending_unblocks"] == 3
            assert b.stats()["unblock_deferred"] == 1
            wait_ready(broker, 3)      # backoff retry lands it
            assert len(drain(broker)) == 3
            assert b.stats()["pending_unblocks"] == 0
        finally:
            inj.disarm_all()

    def test_flush_on_leadership_loss_drops_staged_unblocks(self):
        """Losing leadership mid-window clears tracked AND staged evals
        without enqueueing: the new leader's eval restore owns them."""
        broker, b = harness(coalesce_window_s=0.05)
        for i in range(3):
            b.block(make_blocked(job_id=f"l{i}", classes={"web": True}))
        b.unblock("web", index=10)
        assert b.stats()["pending_unblocks"] == 3
        b.set_enabled(False)           # leadership loss -> flush()
        st = b.stats()
        assert st["pending_unblocks"] == 0
        assert st["total_blocked"] == 0
        time.sleep(0.12)               # past the (cancelled) window
        assert drain(broker, timeout=0.1) == []
        assert b.stats()["unblocks_total"] == 0
