"""Chaos harness tests: trace determinism, disarmed no-ops, leader-kill
replay invariants, and device-fault host-fallback parity (ISSUE 9)."""
import copy
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import (
    POINTS,
    ChaosFault,
    ChaosInjector,
    ChurnReplay,
    SLOGate,
    SLOThresholds,
    fire,
    generate_trace,
    trace_to_jsonable,
)
from nomad_tpu.chaos.injector import active
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import ALLOC_DESIRED_RUN


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# trace determinism
# ---------------------------------------------------------------------------


def test_trace_deterministic_by_seed():
    a = generate_trace(seed=42, duration_s=20.0, n_nodes=50, n_jobs=12)
    b = generate_trace(seed=42, duration_s=20.0, n_nodes=50, n_jobs=12)
    c = generate_trace(seed=43, duration_s=20.0, n_nodes=50, n_jobs=12)
    assert a == b, "same seed must yield the identical event trace"
    assert trace_to_jsonable(a) == trace_to_jsonable(b)
    assert a != c, "different seeds should diverge"
    # sorted by time, disruption paired and cleared before the tail
    assert all(x.t <= y.t for x, y in zip(a, a[1:]))
    kinds = [ev.kind for ev in a]
    assert kinds.count("drain_node") == kinds.count("undrain_node")
    assert kinds.count("mute_node") == kinds.count("unmute_node")
    assert kinds.count("arm_fault") == kinds.count("disarm_fault")
    assert kinds.count("leader_kill") == 1


def test_saturation_kinds_off_is_rng_neutral():
    """New trace kinds default OFF and, when off, consume no rng — every
    existing seed keeps producing a byte-identical trace (replay
    artifacts recorded before the saturation kinds existed stay
    reproducible)."""
    for seed in (0, 7, 42):
        base = generate_trace(seed=seed, duration_s=20.0, n_nodes=50,
                              n_jobs=12)
        explicit_off = generate_trace(seed=seed, duration_s=20.0,
                                      n_nodes=50, n_jobs=12,
                                      n_saturate_waves=0, saturate_jobs=99,
                                      release_nodes=99)
        assert base == explicit_off, \
            "zero saturation waves must not perturb the rng stream"
        assert not any(ev.kind in ("saturate", "capacity_release")
                       for ev in base)


def test_saturation_waves_paired_and_bounded():
    # leader_kill off on both sides: its jitter draws AFTER the
    # saturation block, so the shared-prefix comparison below would
    # otherwise see a shifted kill time
    trace = generate_trace(seed=3, duration_s=20.0, n_nodes=50, n_jobs=12,
                           leader_kill=False,
                           n_saturate_waves=2, saturate_jobs=5,
                           release_nodes=9)
    sats = [ev for ev in trace if ev.kind == "saturate"]
    rels = [ev for ev in trace if ev.kind == "capacity_release"]
    assert len(sats) == len(rels) == 2
    by_wave = {ev.args["wave"]: ev for ev in sats}
    for rel in rels:
        sat = by_wave[rel.args["wave"]]
        assert sat.t < rel.t, "release must follow its wave's saturation"
        assert rel.t <= 20.0 * 0.8 * 0.9, \
            "release lands before the recovery tail"
        assert rel.args["node_count"] == 9
        assert sat.args["job_count"] == 5
    # the prefix shared with a saturation-free trace is unchanged: the
    # new kinds only APPEND rng draws
    base = generate_trace(seed=3, duration_s=20.0, n_nodes=50, n_jobs=12,
                          leader_kill=False)
    residue = [ev for ev in trace
               if ev.kind not in ("saturate", "capacity_release")]
    assert residue == base


# ---------------------------------------------------------------------------
# injector: strict no-op unless armed
# ---------------------------------------------------------------------------


def test_injection_points_noop_when_disarmed():
    # nothing armed: every point is a strict no-op
    assert active() is None
    for point in POINTS:
        fire(point)

    inj = ChaosInjector(seed=1)
    try:
        # armed then disarmed: no-op again
        inj.arm("device_dispatch", prob=1.0)
        inj.disarm("device_dispatch")
        assert active() is None
        for point in POINTS:
            fire(point)

        # armed with prob=1: deterministic fault
        inj.arm("broker_ack", prob=1.0)
        with pytest.raises(ChaosFault):
            fire("broker_ack")
        # a different point stays a no-op even while another is armed
        fire("raft_apply")
        assert inj.fires("broker_ack") == 1
    finally:
        inj.disarm_all()
    assert active() is None
    fire("broker_ack")

    with pytest.raises(ValueError):
        inj.arm("not_a_point")
    with pytest.raises(ValueError):
        inj.arm("heartbeat", mode="explode")


def test_injector_seeded_fire_sequence_is_deterministic():
    def sequence(seed):
        inj = ChaosInjector(seed=seed)
        out = []
        try:
            inj.arm("plan_apply", prob=0.5)
            for _ in range(32):
                try:
                    fire("plan_apply")
                    out.append(0)
                except ChaosFault:
                    out.append(1)
        finally:
            inj.disarm_all()
        return out

    assert sequence(7) == sequence(7)
    assert sequence(7) != sequence(8)


# ---------------------------------------------------------------------------
# leader kill mid-replay: zero lost/duplicated allocations
# ---------------------------------------------------------------------------


def test_leader_kill_mid_replay_zero_lost_allocs():
    trace = generate_trace(
        seed=5, duration_s=6.0, n_nodes=16, n_jobs=5, tg_count=4,
        stop_frac=0.2, rollout_frac=0.2, n_drains=1, n_expiries=1,
        n_hipri=1, n_fault_windows=2, leader_kill=True,
    )
    replay = ChurnReplay(
        seed=5, trace=trace, n_servers=3, n_nodes=16,
        config=ServerConfig(
            num_schedulers=2,
            heartbeat_min_ttl=1.2,
            heartbeat_max_ttl=2.0,
            eval_gc_interval=3600.0,
        ),
        settle_timeout_s=25.0,
    )
    result = replay.run()
    assert active() is None, "replay must disarm its injector"
    assert result["leader_kills"] == 1
    inv = result["invariants"]
    assert inv["lost"] == 0, inv["violations"]
    assert inv["duplicated"] == 0, inv["violations"]
    assert inv["orphaned"] == 0, inv["violations"]
    assert inv["converged"], inv["violations"]
    # the gate consumes exactly this result shape
    verdict = SLOGate(SLOThresholds(
        eval_ms_p99_max=None, slowest_inflight_ms_max=None,
        throughput_min_allocs_per_s=None,
    )).evaluate(result)
    assert verdict["passed"], verdict["checks"]


# ---------------------------------------------------------------------------
# device-dispatch fault -> host fallback, placement parity
# ---------------------------------------------------------------------------


def _placement_map(server, job):
    allocs = [
        a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN
    ]
    return {a.name: a.node_id for a in allocs}


def test_device_fault_forces_host_fallback_with_parity():
    """The same eval placed twice — once through the device batcher, once
    with every device dispatch failing (host-iterator fallback) — must
    land every task on the same node (the bit-parity contract)."""
    cfg = ServerConfig(
        num_schedulers=1,
        deterministic=True,
        ring_decorrelate=False,
        device_min_placements=0,  # always take the device path
        device_batch=8,
        heartbeat_min_ttl=3600.0,
        heartbeat_max_ttl=3601.0,
    )
    nodes = [mock.node() for _ in range(8)]
    job = mock.job()
    job.task_groups[0].count = 16
    job.task_groups[0].tasks[0].resources.networks = []

    def run_once(faulted):
        s = Server(copy.deepcopy(cfg), name="parity")
        s.start()
        inj = ChaosInjector(seed=2)
        try:
            if faulted:
                inj.arm("device_dispatch", mode="fail", prob=1.0)
            for n in nodes:
                s.register_node(copy.deepcopy(n))
            j = copy.deepcopy(job)
            s.register_job(j)
            wait_for(lambda: len(_placement_map(s, j)) == 16,
                     msg="16 allocs placed")
            assert s.drain_evals(timeout=10.0)
            return _placement_map(s, j), s.device_batcher.stats.copy()
        finally:
            inj.disarm_all()
            s.stop()

    device_map, device_stats = run_once(faulted=False)
    host_map, host_stats = run_once(faulted=True)

    assert device_stats["dispatches"] > 0, "control run must use the device"
    assert host_stats["dispatches"] == 0, \
        "faulted run must never complete a device dispatch"
    assert len(host_map) == 16
    assert host_map == device_map, \
        "host fallback must place identically to the device path"


# ---------------------------------------------------------------------------
# nomad-lockdep: witness-armed churn replay
# ---------------------------------------------------------------------------


def test_witness_armed_churn_replay_sound_and_inversion_free():
    """A churn/chaos replay with the runtime lock witness armed: the run
    must finish with zero lock-order violations among the instrumented
    locks, and every witnessed acquisition-order edge must appear in the
    static analyzer's whole-program graph (the dynamic run is the
    soundness check for the static pass)."""
    from nomad_tpu.utils import lock_witness

    trace = generate_trace(
        seed=11, duration_s=3.0, n_nodes=12, n_jobs=3, tg_count=3,
        stop_frac=0.2, rollout_frac=0.2, n_drains=1, n_expiries=1,
        n_hipri=1, n_fault_windows=2,
    )
    replay = ChurnReplay(
        seed=11, trace=trace, n_servers=2, n_nodes=12,
        config=ServerConfig(
            num_schedulers=2,
            heartbeat_min_ttl=1.2,
            heartbeat_max_ttl=2.0,
            eval_gc_interval=3600.0,
        ),
        settle_timeout_s=25.0,
        lock_witness=True,
    )
    result = replay.run()
    assert lock_witness.active() is None, "replay must disarm its witness"
    lw = result["lock_witness"]
    assert lw["armed"] == 1
    assert lw["violations"] == 0
    # churn must actually drive nested acquisition or the check is vacuous
    assert lw["edges"] > 0, lw
    assert lw["missing_from_static"] == [], lw["missing_from_static"]
    inv = result["invariants"]
    assert inv["lost"] == 0, inv["violations"]
    assert inv["converged"], inv["violations"]


# ---------------------------------------------------------------------------
# nomad-race: race-witness-armed churn replay
# ---------------------------------------------------------------------------


def test_race_witness_armed_churn_replay_race_free_and_sound():
    """The same churn replay with the Eraser lockset witness armed: no
    tracked shared field's candidate lockset may empty during the run,
    and every field the runtime witnessed as cross-thread shared must be
    in the static analyzer's inferred-shared set (dynamic soundness
    check for shared-state-discipline's thread-root inventory)."""
    from nomad_tpu.utils import lock_witness, race_witness

    trace = generate_trace(
        seed=13, duration_s=3.0, n_nodes=12, n_jobs=3, tg_count=3,
        stop_frac=0.2, rollout_frac=0.2, n_drains=1, n_expiries=1,
        n_hipri=1, n_fault_windows=2,
    )
    replay = ChurnReplay(
        seed=13, trace=trace, n_servers=2, n_nodes=12,
        config=ServerConfig(
            num_schedulers=2,
            heartbeat_min_ttl=1.2,
            heartbeat_max_ttl=2.0,
            eval_gc_interval=3600.0,
        ),
        settle_timeout_s=25.0,
        race_witness=True,
    )
    result = replay.run()
    assert race_witness.active() is None, "replay must disarm its witness"
    assert lock_witness.active() is None, "auto-armed lock witness too"
    rw = result["race_witness"]
    assert rw["armed"] == 1
    assert rw["violations"] == 0
    # churn must actually drive the tracked hot fields cross-thread or
    # the race check is vacuous
    assert rw["shared_fields"] > 0, rw
    assert rw["missing_from_static"] == [], rw["missing_from_static"]
    inv = result["invariants"]
    assert inv["lost"] == 0, inv["violations"]
    assert inv["converged"], inv["violations"]
