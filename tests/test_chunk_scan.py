"""Chunked throughput scan (engine._build_chunk_scan) unit tests.

Regression suite for the round-2 bench crash: the chunk scan must accept
the EXACT array shapes ``example_scan_inputs`` builds — including the
ZERO-size leading affinity axis that production ``encode_eval`` emits for
affinity-free jobs (the shape specialization the parity step has always
had, engine.py _make_step).
"""
import numpy as np
import pytest

from nomad_tpu.tpu.engine import (
    DIM_CPU,
    DIM_MEM,
    _build_chunk_scan,
    chunk_schedule,
    example_scan_inputs,
)


def _f32(t):
    return tuple(
        np.asarray(a).astype(np.float32)
        if np.asarray(a).dtype.kind == "f" else np.asarray(a)
        for a in t
    )


def _chunk_inputs(n_nodes=64, n_tgs=2, seed=0, open_feas=False):
    """static/carry shaped exactly like bench.c1m_inputs (f32, zero-axis
    affinity arrays from example_scan_inputs — the r2 crash shape)."""
    n_pad, static, carry, _xs = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=n_tgs, n_placements=8, seed=seed
    )
    assert static[4].shape[0] == 0, "fixture must carry the zero-G aff axis"
    static = list(static)
    if open_feas:
        static[3] = np.ones_like(static[3])
    return n_pad, _f32(tuple(static)), _f32(carry)


def test_chunk_scan_zero_affinity_axis_regression():
    # r2 regression: IndexError out of aff_score[g] on axis of size 0
    n_pad, static, carry = _chunk_inputs(open_feas=True)
    scan = _build_chunk_scan(16)
    tg_idx, want = chunk_schedule([(0, 20), (1, 20)], chunk=16)
    _carry, deficit, (top_idx, scores, valid, placed) = scan(
        n_pad, static, carry, (tg_idx, want)
    )
    assert int(np.asarray(placed).sum()) == 40
    assert (np.asarray(deficit) == 0).all()


def test_chunk_scan_respects_capacity_and_counts():
    n_pad, static, carry = _chunk_inputs(n_nodes=32, open_feas=True)
    totals, reserved = np.asarray(static[0]), np.asarray(static[1])
    asks = np.asarray(static[2])
    scan = _build_chunk_scan(8)
    tg_idx, want = chunk_schedule([(0, 30), (1, 30)], chunk=8, retry_rounds=2)
    carry_out, deficit, (top_idx, scores, valid, placed) = scan(
        n_pad, static, carry, (tg_idx, want)
    )
    used, tg_counts, job_counts = carry_out[0], carry_out[1], carry_out[2]
    used = np.asarray(used)
    tg_counts = np.asarray(tg_counts)
    job_counts = np.asarray(job_counts)
    # every placement valid: capacity never exceeded on any dim
    assert (used + reserved <= totals + 1e-5).all()
    # counts reconcile: job_counts == sum over TGs, total == placed
    assert (job_counts == tg_counts.sum(axis=0)).all()
    n_placed = int(np.asarray(placed).sum())
    assert job_counts.sum() == n_placed
    # per-placement replay: each chosen node individually fit at choice time
    top_idx = np.asarray(top_idx)
    valid = np.asarray(valid)
    replay = np.zeros_like(used)
    for si in range(top_idx.shape[0]):
        a = asks[int(tg_idx[si])]
        for k in range(top_idx.shape[1]):
            if valid[si, k]:
                n = int(top_idx[si, k])
                assert (replay[n] + reserved[n] + a <= totals[n] + 1e-5).all()
                replay[n] += a
    assert np.allclose(replay, used, atol=1e-4)


def test_chunk_scan_deficit_rolls_into_retry_rounds():
    # feasibility so tight the first chunks can't fill: deficit must ride
    # the carry and drain through want=0 retry sweeps, never over-placing
    n_pad, static, carry = _chunk_inputs(n_nodes=16)
    static = list(static)
    feas = np.zeros_like(np.asarray(static[3]))
    feas[:, :3] = True  # only 3 feasible nodes per TG
    static[3] = feas
    # tiny nodes: each holds very few allocs
    totals = np.asarray(static[0]).copy()
    totals[:, DIM_CPU] = 300.0
    totals[:, DIM_MEM] = 600.0
    static[0] = totals
    asks = np.asarray(static[2]).copy()
    asks[:, DIM_CPU] = 100.0
    asks[:, DIM_MEM] = 100.0
    static[2] = asks
    reserved = np.zeros_like(np.asarray(static[1]))
    static[1] = reserved
    static = tuple(static)

    scan = _build_chunk_scan(8)
    tg_idx, want = chunk_schedule([(0, 50)], chunk=8, retry_rounds=3)
    _carry, deficit, (_ti, _sc, _valid, placed) = scan(
        n_pad, static, carry, (tg_idx, want)
    )
    n_placed = int(np.asarray(placed).sum())
    # 3 nodes x 2 allocs each (300cpu/100ask = 3 but mem 600/100=6 -> cpu
    # binds at 3) = 9 placements max; never more than capacity allows
    assert n_placed == 9
    # unfilled demand is reported, not silently dropped
    assert int(np.asarray(deficit)[0]) == 50 - n_placed


def test_chunk_scan_distinct_hosts():
    n_pad, static, carry = _chunk_inputs(n_nodes=16, n_tgs=2, open_feas=True)
    static = list(static)
    dh_job = np.zeros(2, bool)
    dh_job[:] = True
    static[6] = dh_job  # job-level distinct_hosts
    static = tuple(static)
    scan = _build_chunk_scan(8)
    tg_idx, want = chunk_schedule([(0, 10), (1, 10)], chunk=8, retry_rounds=1)
    carry_out, deficit, (_ti, _sc, _valid, placed) = scan(
        n_pad, static, carry, (tg_idx, want)
    )
    job_counts = np.asarray(carry_out[2])
    assert job_counts.max() <= 1  # never two allocs of the job on one node
    assert int(np.asarray(placed).sum()) == 16  # bound by 16 distinct nodes


# ---------------------------------------------------------------------------
# Chunked production tier (engine.run_chunked + sampled parity)
# ---------------------------------------------------------------------------


def _chunk_enc(n_nodes=64, n_tgs=2, p=40, seed=3, open_feas=True,
               dtype=np.float32):
    """A chunk-eligible EncodedEval shaped like a fresh C1M-style eval."""
    import time

    from nomad_tpu.tpu.engine import EncodedEval, example_scan_inputs

    n_pad, static, carry, xs = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=n_tgs, n_placements=p, seed=seed
    )
    static = list(static)
    if open_feas:
        static[3] = np.ones_like(static[3])

    def cast(t):
        return tuple(
            np.asarray(a).astype(dtype)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a)
            for a in t
        )

    return EncodedEval(
        n_real=n_nodes, n_pad=n_pad, g=n_tgs, s=static[9].shape[1],
        v=static[10].shape[2], p=p, dtype=dtype,
        static=cast(tuple(static)), carry=cast(carry), xs=xs,
        missing_list=[None] * p, nodes=[], table=None,
        start_ns=time.monotonic_ns(), dense_ok=True,
    )


def test_chunk_eligibility_gates():
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    enc = _chunk_enc()
    assert TpuPlacementEngine._chunk_eligible(enc) is None

    enc.pre_allocs = {0: []}
    assert TpuPlacementEngine._chunk_eligible(enc) == "preemption tables"
    enc.pre_allocs = None

    enc.dense_ok = False
    assert TpuPlacementEngine._chunk_eligible(enc) == "not dense"
    enc.dense_ok = True

    enc.dtype = np.int32
    assert TpuPlacementEngine._chunk_eligible(enc) == "int mode"
    enc.dtype = np.float32

    xs = list(enc.xs)
    evict = np.asarray(xs[2]).copy()
    evict[0] = 5
    xs[2] = evict
    enc.xs = tuple(xs)
    assert TpuPlacementEngine._chunk_eligible(enc) == "eviction axis"


def test_batcher_asserts_chunk_gate_on_preempting_eval():
    from nomad_tpu.tpu.batcher import assert_chunk_gate
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    enc = _chunk_enc()
    assert_chunk_gate(enc)  # clean eval passes

    enc.pre_allocs = {0: []}
    with pytest.raises(AssertionError, match="preempting"):
        assert_chunk_gate(enc)
    enc.pre_allocs = None
    # and the engine refuses to run it through the chunked scan at all
    enc.pre_allocs = {0: []}
    engine = TpuPlacementEngine.shared()
    with pytest.raises(AssertionError):
        engine.run_chunked(enc)


def test_run_chunked_places_all_in_parity_result_shape():
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    enc = _chunk_enc()
    engine = TpuPlacementEngine.shared()
    chosen, scores, pulls, skipped, evict = engine.run_chunked(enc, chunk_k=16)
    assert chosen.shape == (enc.p,) and (chosen >= 0).all()
    assert scores.shape == (enc.p,)
    assert (pulls == enc.n_real).all()
    assert not skipped.any()
    assert evict.shape == (enc.p, 0)
    # per-TG demand exactly met, chosen nodes are real
    tg_idx = np.asarray(enc.xs[0])[: enc.p]
    for gi in np.unique(tg_idx):
        assert (chosen[tg_idx == gi] >= 0).all()
    assert chosen.max() < enc.n_real


def test_sampled_parity_catches_injected_perturbation():
    from nomad_tpu.tpu import engine as eng_mod
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    enc = _chunk_enc()
    engine = TpuPlacementEngine.shared()
    chosen, *_ = engine.run_chunked(enc, chunk_k=16)

    eng_mod._PARITY_SAMPLE_RNG.seed(0)
    engine.reset_parity_samples()
    engine._maybe_sample_parity(enc, chosen, rate=1.0)
    baseline = engine.parity_sample_stats()
    assert baseline["evals_sampled"] == 1
    assert baseline["placements_checked"] == enc.p

    # inject a score-perturbation-style divergence: rebind one placement
    # to a node the bit-parity scan did not pick for its task group
    ref = np.asarray(engine.run_scan_single(enc)[0])[: enc.p]
    tg_idx = np.asarray(enc.xs[0])[: enc.p]
    ref_nodes = set(ref[tg_idx == tg_idx[0]].tolist())
    bad = next(n for n in range(enc.n_real) if n not in ref_nodes)
    perturbed = chosen.copy()
    perturbed[0] = bad

    eng_mod._PARITY_SAMPLE_RNG.seed(0)
    engine.reset_parity_samples()
    engine._maybe_sample_parity(enc, perturbed, rate=1.0)
    stats = engine.parity_sample_stats()
    assert stats["placements_diverged"] > baseline["placements_diverged"]
    assert stats["divergence_rate"] > baseline["divergence_rate"]


def test_sampled_parity_rate_zero_records_nothing():
    from nomad_tpu.tpu.engine import TpuPlacementEngine

    enc = _chunk_enc()
    engine = TpuPlacementEngine.shared()
    chosen, *_ = engine.run_chunked(enc, chunk_k=16)
    engine.reset_parity_samples()
    engine._maybe_sample_parity(enc, chosen, rate=0.0)
    assert engine.parity_sample_stats()["evals_sampled"] == 0


def test_chunk_scan_spread_prefers_undersubscribed_values():
    # one spread axis, all capacity open: chunks should track the desired
    # per-value proportions rather than piling onto one value
    n_pad, static, carry = _chunk_inputs(n_nodes=64, n_tgs=1, open_feas=True)
    scan = _build_chunk_scan(4)
    tg_idx, want = chunk_schedule([(0, 32)], chunk=4)
    carry_out, _deficit, (_ti, _sc, _valid, placed) = scan(
        n_pad, static, carry, (tg_idx, want)
    )
    assert int(np.asarray(placed).sum()) == 32
    spread_counts = np.asarray(carry_out[3])[0, 0]  # [V]
    real = spread_counts[:-1]  # drop the invalid bucket
    assert real.sum() == 32
    # balanced within a chunk width of perfectly even
    assert real.max() - real.min() <= 8
