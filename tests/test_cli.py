"""CLI tests driving a live agent through nomad_tpu.cli.main (reference
command/*_test.go patterns: run command, assert output + exit code)."""

import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.cli.main import main


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(dev_mode=True, num_schedulers=2, name="cli-dev"))
    a.start()
    yield a
    a.shutdown()


def run_cli(agent, *args):
    lines = []
    code = main(["-address", agent.http_addr, *args], out=lines.append)
    return code, "\n".join(lines)


JOBFILE = """
job "cli-job" {
  datacenters = ["dc1"]
  group "g" {
    count = 2
    task "t" {
      driver = "mock"
      config { run_for = "20s" }
      resources { cpu = 100 memory = 64 }
    }
  }
}
"""


def test_version_and_usage(agent):
    code, out = run_cli(agent, "version")
    assert code == 0 and "Nomad-TPU" in out
    code, out = run_cli(agent)
    assert code == 1 and "usage" in out
    code, out = run_cli(agent, "frobnicate")
    assert code == 1 and "unknown command" in out


def test_job_run_and_status(agent, tmp_path):
    jf = tmp_path / "job.hcl"
    jf.write_text(JOBFILE)
    code, out = run_cli(agent, "job", "run", str(jf))
    assert code == 0, out
    assert "Monitoring evaluation" in out
    assert 'finished with status "complete"' in out
    assert out.count("created: node") == 2

    code, out = run_cli(agent, "job", "status")
    assert code == 0 and "cli-job" in out

    code, out = run_cli(agent, "job", "status", "cli-job")
    assert code == 0
    assert "Summary" in out and "Allocations" in out
    assert "cli-job" in out

    code, out = run_cli(agent, "status", "cli-job")  # top-level alias
    assert code == 0 and "cli-job" in out


def test_job_plan_and_validate(agent, tmp_path):
    jf = tmp_path / "job2.hcl"
    jf.write_text(JOBFILE.replace("cli-job", "cli-plan").replace("count = 2", "count = 3"))
    code, out = run_cli(agent, "job", "validate", str(jf))
    assert code == 0 and "validation successful" in out
    code, out = run_cli(agent, "job", "plan", str(jf))
    assert code == 0, out
    assert "Job Modify Index" in out
    # plan must not register
    code, out = run_cli(agent, "job", "status", "cli-plan")
    assert code == 1


def test_node_commands(agent):
    code, out = run_cli(agent, "node", "status")
    assert code == 0 and "ready" in out
    node_id = out.splitlines()[1].split()[0]

    code, out = run_cli(agent, "node", "status", node_id)
    assert code == 0 and "Allocations" in out or code == 0

    code, out = run_cli(agent, "node", "eligibility", "-disable", node_id)
    assert code == 0 and "ineligible" in out
    code, out = run_cli(agent, "node", "eligibility", "-enable", node_id)
    assert code == 0 and "eligible" in out


def test_eval_and_alloc_status(agent):
    code, out = run_cli(agent, "job", "status", "cli-job")
    alloc_line = [l for l in out.splitlines() if l.strip() and "run" in l]
    # find an alloc id from the allocations table
    lines = out.split("Allocations")[-1].splitlines()
    alloc_id = None
    for line in lines[2:]:
        parts = line.split()
        if parts:
            alloc_id = parts[0]
            break
    assert alloc_id
    code, out = run_cli(agent, "alloc", "status", alloc_id)
    assert code == 0, out
    assert "Client Status" in out

    code, out = run_cli(agent, "eval", "status", "zzzz")
    assert code == 1


def test_job_stop(agent):
    code, out = run_cli(agent, "job", "stop", "-purge", "-detach", "cli-job")
    assert code == 0, out
    deadline = time.time() + 5
    while time.time() < deadline:
        code, out = run_cli(agent, "job", "status", "cli-job")
        if code == 1:
            break
        time.sleep(0.2)
    assert code == 1


def test_system_and_operator_and_server(agent):
    code, out = run_cli(agent, "system", "gc")
    assert code == 0
    code, out = run_cli(agent, "operator", "scheduler")
    assert code == 0 and "SchedulerConfig" in out
    code, out = run_cli(agent, "operator", "raft")
    assert code == 0 and "leader" in out
    code, out = run_cli(agent, "server", "members")
    assert code == 0 and "alive" in out
    code, out = run_cli(agent, "ui")
    assert code == 0 and "/ui/" in out


def test_agent_info(agent):
    code, out = run_cli(agent, "agent-info")
    assert code == 0 and "Server" in out


def test_job_init(agent, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    code, out = run_cli(agent, "job", "init")
    assert code == 0 and "example.nomad" in out
    # the generated example must parse through our own HCL front end
    code, out = run_cli(agent, "job", "validate", "example.nomad")
    assert code == 0, out
    # refuses to clobber
    code, out = run_cli(agent, "job", "init")
    assert code == 1 and "already exists" in out


def test_job_eval_and_deployments(agent, tmp_path):
    jf = tmp_path / "evaljob.hcl"
    jf.write_text(JOBFILE.replace("cli-job", "cli-eval"))
    code, out = run_cli(agent, "job", "run", str(jf))
    assert code == 0, out

    code, out = run_cli(agent, "job", "eval", "cli-eval")
    assert code == 0, out
    assert 'finished with status "complete"' in out

    # no update stanza -> no deployments, but the command itself works
    code, out = run_cli(agent, "job", "deployments", "cli-eval")
    assert code == 0 and "No deployments" in out

    run_cli(agent, "job", "stop", "-purge", "-detach", "cli-eval")


def test_alloc_stop_reschedules(agent, tmp_path):
    jf = tmp_path / "stopjob.hcl"
    jf.write_text(JOBFILE.replace("cli-job", "cli-astop").replace("count = 2", "count = 1"))
    code, out = run_cli(agent, "job", "run", str(jf))
    assert code == 0, out

    code, out = run_cli(agent, "job", "status", "cli-astop")
    lines = out.split("Allocations")[-1].splitlines()
    alloc_id = next(p[0] for p in (l.split() for l in lines[2:]) if p)

    code, out = run_cli(agent, "alloc", "stop", alloc_id)
    assert code == 0, out
    assert 'finished with status "complete"' in out

    # the eval replaces the stopped alloc with a fresh one
    deadline = time.time() + 10
    while time.time() < deadline:
        code, out = run_cli(agent, "job", "status", "cli-astop")
        lines = out.split("Allocations")[-1].splitlines()
        ids = [p[0] for p in (l.split() for l in lines[2:]) if p]
        if any(i != alloc_id for i in ids):
            break
        time.sleep(0.2)
    assert any(i != alloc_id for i in ids), out
    run_cli(agent, "job", "stop", "-purge", "-detach", "cli-astop")


def test_deployment_pause_resume_cli(agent, tmp_path):
    jf = tmp_path / "depjob.hcl"
    jf.write_text(JOBFILE.replace("cli-job", "cli-dep").replace(
        'count = 2', 'count = 1\n    update { max_parallel = 1 }'))
    code, out = run_cli(agent, "job", "run", "-detach", str(jf))
    assert code == 0, out
    deadline = time.time() + 10
    dep_id = None
    while time.time() < deadline and not dep_id:
        code, out = run_cli(agent, "job", "deployments", "cli-dep")
        lines = [l for l in out.splitlines()[1:] if l.strip()]
        if code == 0 and lines and "No deployments" not in out:
            dep_id = lines[0].split()[0]
            break
        time.sleep(0.2)
    assert dep_id, out
    code, out = run_cli(agent, "deployment", "pause", dep_id)
    assert code == 0 and "paused" in out
    code, out = run_cli(agent, "deployment", "status", dep_id)
    assert code == 0 and "paused" in out
    code, out = run_cli(agent, "deployment", "resume", dep_id)
    assert code == 0 and "resumed" in out
    run_cli(agent, "job", "stop", "-purge", "-detach", "cli-dep")


def test_monitor_no_follow(agent):
    # the module-scope agent shares this process: emit a log record the
    # monitor's ring buffer is guaranteed to capture
    import logging

    logging.getLogger("nomad_tpu.test").warning("cli-monitor-probe")
    code, out = run_cli(agent, "monitor", "-no-follow", "-log-level", "warn")
    assert code == 0
    assert "cli-monitor-probe" in out


def test_operator_raft_remove_peer_cli(agent):
    # dev agent runs the in-proc raft: removal must refuse cleanly
    code, out = run_cli(agent, "operator", "raft", "remove-peer",
                        "-peer-id", "nonexistent")
    assert code == 1
    code, out = run_cli(agent, "operator", "raft", "list-peers")
    assert code == 0 and "leader" in out


def test_operator_keygen_keyring_autopilot(agent):
    code, out = run_cli(agent, "operator", "keygen")
    assert code == 0
    import base64
    key = out.strip()
    assert len(base64.b64decode(key)) == 32

    # dev agent has no gossip encryption: list shows an empty ring and
    # MUTATIONS refuse cleanly
    code, out = run_cli(agent, "operator", "keyring")
    assert code == 0 and "Primary" in out
    code, out = run_cli(agent, "operator", "keyring", "-install", key)
    assert code == 1 and "error" in out

    code, out = run_cli(agent, "operator", "autopilot")
    assert code == 0 and "CleanupDeadServers" in out
    code, out = run_cli(agent, "operator", "autopilot", "set-config",
                        "-cleanup-dead-servers=false")
    assert code == 0 and "updated" in out
    code, out = run_cli(agent, "operator", "autopilot")
    assert code == 0 and '"CleanupDeadServers": false' in out


def test_top_level_aliases(agent, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    code, out = run_cli(agent, "init")
    assert code == 0 and "example.nomad" in out
    code, out = run_cli(agent, "validate", "example.nomad")
    assert code == 0


def test_json_and_template_output(agent):
    """-json / -t on status commands (reference command/data_format.go,
    wired into node/job/alloc/eval/deployment status)."""
    import json as _json

    # node status -json: full API payloads, 4-space indent
    code, out = run_cli(agent, "node", "status", "-json")
    assert code == 0
    nodes = _json.loads(out)
    assert isinstance(nodes, list) and nodes
    assert "ID" in nodes[0]

    # node status -t: Go-template subset with range/field access
    code, out = run_cli(
        agent, "node", "status", "-t",
        '{{range .}}{{.Name}}:{{.Status}}{{"\\n"}}{{end}}')
    assert code == 0
    assert f"{nodes[0]['Name']}:ready" in out

    # single node via template
    node_id = nodes[0]["ID"]
    code, out = run_cli(agent, "node", "status", "-t", "{{.ID}}", node_id)
    assert code == 0 and out.strip() == node_id

    # job status -json (list + single); cli-job ran earlier in the module
    code, out = run_cli(agent, "job", "status", "-json")
    assert code == 0
    jobs = _json.loads(out)
    assert isinstance(jobs, list)
    if jobs:
        code, out = run_cli(agent, "job", "status", "-json", jobs[0]["ID"])
        assert code == 0
        job = _json.loads(out)
        assert job["ID"] == jobs[0]["ID"]

    # eval status -json + alloc status -t
    code, out = run_cli(agent, "eval", "status", "-json", "x-no-such")
    assert code == 1  # no match is still an error, not empty json

    evals = agent.server.fsm.state.evals()
    if evals:
        ev = evals[0]
        code, out = run_cli(agent, "eval", "status", "-json", ev.id)
        assert code == 0
        assert _json.loads(out)["ID"] == ev.id
        code, out = run_cli(agent, "eval", "status", "-t",
                            "{{.ID}} {{.Status}}", ev.id)
        assert code == 0 and ev.id in out

    allocs = agent.server.fsm.state.allocs()
    if allocs:
        al = allocs[0]
        code, out = run_cli(agent, "alloc", "status", "-t",
                            "{{.ID}}|{{.JobID}}", al.id)
        assert code == 0 and out.strip() == f"{al.id}|{al.job_id}"

    # deployment list -json (empty or not, must be a JSON array)
    code, out = run_cli(agent, "deployment", "list", "-json")
    assert code == 0
    assert isinstance(_json.loads(out), list)

    # server members -t
    code, out = run_cli(agent, "server", "members", "-t",
                        '{{range .}}{{.Name}}{{end}}')
    assert code == 0 and "cli-dev" in out

    # -json and -t together is an error (data_format.go:27)
    code, out = run_cli(agent, "node", "status", "-json", "-t", "{{.}}")
    assert code == 1 and "does not support template" in out

    # template errors surface, not swallowed
    code, out = run_cli(agent, "node", "status", "-t", "{{range .}}no end")
    assert code == 1 and "unclosed" in out


def test_template_subset_semantics():
    """Unit coverage for the Go-template subset evaluator."""
    from nomad_tpu.cli.data_format import (
        FormatError, format_data, render_template,
    )

    data = {"A": {"B": [1, 2, 3]}, "Ok": True, "Null": None}
    assert render_template("{{.A.B}}", data) == "[1, 2, 3]"
    assert render_template("{{len .A.B}}", data) == "3"
    assert render_template("{{range .A.B}}<{{.}}>{{end}}", data) == "<1><2><3>"
    assert render_template("{{if .Ok}}y{{else}}n{{end}}", data) == "y"
    assert render_template("{{if .Null}}y{{else}}n{{end}}", data) == "n"
    assert render_template('{{.Missing}}', data) == "<no value>"
    assert render_template('{{"\\t"}}', data) == "\t"
    # non-ASCII literals pass through verbatim — the old blanket
    # unicode_escape decode turned each UTF-8 byte of é into its own
    # latin-1 codepoint ("cafÃ©" mojibake)
    assert render_template('{{"café"}}', data) == "café"
    assert render_template('{{"café\\n"}}', data) == "café\n"
    assert render_template('{{"\\u00e9"}}', data) == "é"
    assert render_template('{{"a\\\\b"}}', data) == "a\\b"
    # nested range
    assert render_template(
        "{{range .}}{{range .}}{{.}}{{end}};{{end}}", [[1, 2], [3]]
    ) == "12;3;"

    import pytest as _pytest
    with _pytest.raises(FormatError):
        format_data(True, "{{.}}", data)
    with _pytest.raises(FormatError):
        render_template("{{frobnicate .}}", data)
    with _pytest.raises(FormatError):
        render_template("{{end}}", data)
