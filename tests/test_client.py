"""Client agent tests: drivers, task runner restart policy, alloc runner
health, and the full server+client loop — reference client/client_test.go,
allocrunner tests, drivers/mock + drivers/rawexec driver_test.go."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig, ServerProxy
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.drivers.base import TaskConfig, new_driver
from nomad_tpu.client.taskenv import TaskEnvBuilder
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    RestartPolicy,
    UpdateStrategy,
)


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def test_mock_driver_lifecycle():
    d = new_driver("mock")
    h = d.start_task(TaskConfig(id="t1", name="t", config={"run_for": 0.05, "exit_code": 3}))
    assert h.state == "running"
    res = d.wait_task("t1", timeout=5.0)
    assert res.exit_code == 3
    status = d.inspect_task("t1")
    assert status.state == "exited"
    d.destroy_task("t1")


def test_raw_exec_driver_runs_real_process(tmp_path):
    ad = AllocDir(str(tmp_path), "alloc1")
    ad.build()
    td = ad.new_task_dir("t")
    td.build()
    os.makedirs(td.log_dir, exist_ok=True)
    d = new_driver("raw_exec")
    cfg = TaskConfig(
        id="t1",
        name="t",
        config={"command": "/bin/sh", "args": ["-c", "echo hello-$WHO"]},
        env={"WHO": "nomad"},
        task_dir=td,
        stdout_path=os.path.join(td.log_dir, "t.stdout.0"),
        stderr_path=os.path.join(td.log_dir, "t.stderr.0"),
    )
    d.start_task(cfg)
    res = d.wait_task("t1", timeout=10.0)
    assert res.exit_code == 0
    with open(cfg.stdout_path) as f:
        assert f.read().strip() == "hello-nomad"
    d.destroy_task("t1")


def test_raw_exec_stop_escalates_to_kill(tmp_path):
    d = new_driver("raw_exec")
    cfg = TaskConfig(
        id="t1", name="t",
        config={"command": "/bin/sh", "args": ["-c", "trap '' TERM; sleep 60"]},
    )
    d.start_task(cfg)
    time.sleep(0.2)
    start = time.monotonic()
    d.stop_task("t1", timeout_s=0.5)
    res = d.wait_task("t1", timeout=5.0)
    assert time.monotonic() - start < 5.0
    assert res.signal == 9  # escalated


# ---------------------------------------------------------------------------
# task env
# ---------------------------------------------------------------------------


def test_task_env_interpolation():
    node = mock.node()
    node.attributes["kernel.name"] = "linux"
    alloc = mock.alloc()
    job = mock.job()
    alloc.job = job
    alloc.job_id = job.id
    alloc.task_group = job.task_groups[0].name
    alloc.name = f"{job.id}.web[2]"
    task = job.task_groups[0].tasks[0]
    task.env = {"K": "${attr.kernel.name}", "NODE": "${node.datacenter}"}
    env = TaskEnvBuilder(node, alloc, task).build()
    assert env["K"] == "linux"
    assert env["NODE"] == node.datacenter
    assert env["NOMAD_ALLOC_INDEX"] == "2"
    assert env["NOMAD_JOB_ID"] == job.id


# ---------------------------------------------------------------------------
# end-to-end: server + client
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster(tmp_path):
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    c = Client(ServerProxy(s), ClientConfig(state_dir=str(tmp_path / "client")))
    c.start()
    yield s, c
    c.shutdown()
    s.stop()


def batch_echo_job():
    job = mock.job()
    job.type = "batch"
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy.attempts = 0
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "echo done"]}
    task.restart_policy = RestartPolicy(attempts=0, mode="fail")
    return job


def test_client_runs_real_job_end_to_end(cluster):
    server, client = cluster
    job = batch_echo_job()
    server.register_job(job)
    wait_for(
        lambda: any(
            a.client_status == ALLOC_CLIENT_COMPLETE
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="batch job completed via real subprocess",
    )
    allocs = server.fsm.state.allocs_by_job(job.namespace, job.id, True)
    states = allocs[0].task_states
    assert states and all(s.successful() for s in states.values())


def test_failing_task_reports_failed(cluster):
    server, client = cluster
    job = batch_echo_job()
    job.task_groups[0].tasks[0].config = {"command": "/bin/sh", "args": ["-c", "exit 7"]}
    server.register_job(job)
    wait_for(
        lambda: any(
            a.client_status == ALLOC_CLIENT_FAILED
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="failed status synced",
    )


def test_service_job_health_feeds_deployment(cluster):
    """The alloc health watcher reports healthy -> deployment succeeds with
    no test-side simulation."""
    server, client = cluster
    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].update = UpdateStrategy(
        max_parallel=2, min_healthy_time_ns=int(0.2e9)
    )
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "sleep 300"]}
    server.register_job(job)
    wait_for(
        lambda: (
            (d := server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id))
            is not None
            and d.status == "successful"
        ),
        timeout=20.0,
        msg="deployment driven healthy by the client",
    )
    assert server.fsm.state.job_by_id(job.namespace, job.id).stable is True


def test_stop_job_stops_allocs(cluster):
    server, client = cluster
    job = mock.job()
    job.task_groups[0].count = 1
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "sleep 300"]}
    server.register_job(job)
    wait_for(
        lambda: any(
            a.client_status == ALLOC_CLIENT_RUNNING
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="running",
    )
    server.deregister_job(job.namespace, job.id)
    wait_for(lambda: client.num_allocs() == 0 or all(
        not tr.done.is_set() is False
        for ar in client.allocrunners.values() for tr in ar.task_runners.values()
    ), msg="runner stopped")
    wait_for(
        lambda: all(
            a.client_terminal_status() or a.server_terminal_status()
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="allocs terminal after stop",
    )


def test_client_restart_recovers_allocs(tmp_path):
    """Client restart: persisted state restores runners and re-attaches the
    live process (client.go:991 restore + RecoverTask)."""
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    state_dir = str(tmp_path / "client")
    c = Client(ServerProxy(s), ClientConfig(state_dir=state_dir, persist_state=True))
    c.start()
    try:
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh", "args": ["-c", "sleep 300"]}
        server_job_ns, server_job_id = job.namespace, job.id
        s.register_job(job)
        wait_for(
            lambda: any(
                a.client_status == ALLOC_CLIENT_RUNNING
                for a in s.fsm.state.allocs_by_job(server_job_ns, server_job_id, True)
            ),
            msg="running before restart",
        )
        pid = None
        for ar in c.allocrunners.values():
            for tr in ar.task_runners.values():
                pid = tr.handle.driver_state.get("pid")
        assert pid is not None

        # "crash" the client without stopping tasks
        c._shutdown.set()
        c.state_db.close()

        c2 = Client(
            ServerProxy(s),
            ClientConfig(state_dir=state_dir, persist_state=True),
            node=c.node,
        )
        c2.start()
        try:
            assert c2.num_allocs() == 1
            os.kill(pid, 0)  # original process still alive and re-attached
        finally:
            c2.shutdown()
    finally:
        c.shutdown()
        s.stop()
