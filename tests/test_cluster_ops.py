"""Cluster operations: runtime join, force-leave, gossip key rotation,
client GC (VERDICT r3 #6; reference command/agent/http.go:176-185,
serf keyring protocol, client/gc.go)."""

import base64
import os
import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import Client, Config
from nomad_tpu.gossip.memberlist import Memberlist, MemberlistConfig


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def fast_ml(name, key=b"") -> MemberlistConfig:
    return MemberlistConfig(
        name=name, probe_interval=0.05, probe_timeout=0.05,
        suspicion_timeout=0.3, push_pull_interval=0.2, encrypt_key=key,
    )


class TestKeyring:
    def test_rolling_rotation_never_partitions(self):
        """serf keyring protocol: install new everywhere -> use new
        everywhere -> remove old. Gossip flows at every step."""
        key_a = base64.b64encode(os.urandom(32)).decode()
        key_b = base64.b64encode(os.urandom(32)).decode()
        a = Memberlist(fast_ml("ka", key_a.encode())).start()
        b = Memberlist(fast_ml("kb", key_a.encode())).start()
        try:
            assert b.join([a.addr]) == 1
            wait_until(lambda: a.num_alive() == 2, msg="joined under key A")

            for ml in (a, b):
                ml.keyring_install(key_b)
            a.keyring_use(key_b)  # a seals with B; b unseals via ring
            assert b._unseal(a._seal(b"x")) == b"x"
            assert a._unseal(b._seal(b"x")) == b"x"  # b still seals with A
            b.keyring_use(key_b)
            for ml in (a, b):
                ml.keyring_remove(key_a)
            assert a.keyring_list() == [key_b]
            # old-key traffic is now dropped; new-key traffic flows
            old = Memberlist(fast_ml("kold", key_a.encode()))
            try:
                assert a._unseal(old._seal(b"x")) is None
            finally:
                old.shutdown()
            assert b._unseal(a._seal(b"y")) == b"y"
            # liveness survives the rotation
            time.sleep(0.3)
            assert a.num_alive() == 2 and b.num_alive() == 2
        finally:
            a.shutdown()
            b.shutdown()

    def test_keyring_broadcast_propagates(self):
        """Mutations issued on ONE node reach the cluster over sealed
        gossip (serf's keyring queries): install+use+remove via
        keyring_broadcast on `a` converge `b`'s ring too."""
        key_a = base64.b64encode(os.urandom(32)).decode()
        key_b = base64.b64encode(os.urandom(32)).decode()
        a = Memberlist(fast_ml("kba", key_a.encode())).start()
        b = Memberlist(fast_ml("kbb", key_a.encode())).start()
        try:
            assert b.join([a.addr]) == 1
            wait_until(lambda: a.num_alive() == 2, msg="joined")
            a.keyring_broadcast("install", key_b)
            wait_until(lambda: key_b in b.keyring_list(),
                       msg="install propagated")
            a.keyring_broadcast("use", key_b)
            wait_until(lambda: b.keyring_list()[0] == key_b,
                       msg="use propagated")
            a.keyring_broadcast("remove", key_a)
            wait_until(lambda: b.keyring_list() == [key_b],
                       msg="remove propagated")
            assert a.keyring_list() == [key_b]
            time.sleep(0.3)
            assert a.num_alive() == 2 and b.num_alive() == 2
        finally:
            a.shutdown()
            b.shutdown()

    def test_keyring_guards(self):
        key = base64.b64encode(os.urandom(16)).decode()
        ml = Memberlist(fast_ml("kg", key.encode()))
        try:
            with pytest.raises(ValueError, match="primary"):
                ml.keyring_remove(key)
            with pytest.raises(ValueError, match="not installed"):
                ml.keyring_use(base64.b64encode(os.urandom(16)).decode())
            plain = Memberlist(fast_ml("kp"))
            try:
                with pytest.raises(ValueError, match="encryption"):
                    plain.keyring_install(key)
            finally:
                plain.shutdown()
        finally:
            ml.shutdown()


class TestJoinForceLeave:
    def test_runtime_join_then_force_leave(self):
        """Two servers with NO retry_join find each other via
        /v1/agent/join at runtime; force-leave evicts one."""
        a1 = Agent(AgentConfig(name="ops1", bootstrap_expect=1))
        a1.start()
        a2 = Agent(AgentConfig(name="ops2", bootstrap_expect=1))
        a2.start()
        try:
            api1 = Client(Config(address=a1.http_addr))
            assert len(api1.agent.members()["Members"]) == 1

            serf_addr = "{}:{}".format(*a2.membership.memberlist.addr)
            out = api1.agent.join([serf_addr])
            assert out["num_joined"] == 1
            wait_until(
                lambda: len(api1.agent.members()["Members"]) == 2,
                msg="both members visible after runtime join",
            )

            # stop 2's gossip without a graceful leave, then evict it
            a2.membership.memberlist.shutdown()
            api1.agent.force_leave("ops2.global")
            wait_until(
                lambda: any(
                    m["Name"] == "ops2.global" and m["Status"] == "left"
                    for m in api1.agent.members()["Members"]
                ),
                msg="forced member marked left",
            )
        finally:
            a1.shutdown()
            a2.shutdown()

    def test_keyring_http_surface(self):
        key_a = base64.b64encode(os.urandom(32)).decode()
        key_b = base64.b64encode(os.urandom(32)).decode()
        a = Agent(AgentConfig(name="keyr1", encrypt=key_a))
        a.start()
        try:
            api = Client(Config(address=a.http_addr))
            assert list(api.agent.keyring_list()["Keys"]) == [key_a]
            api.agent.keyring_op("install", key_b)
            api.agent.keyring_op("use", key_b)
            api.agent.keyring_op("remove", key_a)
            assert list(api.agent.keyring_list()["Keys"]) == [key_b]
        finally:
            a.shutdown()


class TestClientGC:
    @pytest.fixture
    def dev(self):
        a = Agent(AgentConfig(dev_mode=True, name="gc-dev", num_schedulers=2))
        a.start()
        yield a
        a.shutdown()

    def test_gc_collects_dead_alloc_dir(self, dev):
        api = Client(Config(address=dev.http_addr))
        job = {
            "ID": "gc-job", "Name": "gc-job", "Type": "batch",
            "Datacenters": ["dc1"],
            "TaskGroups": [{
                "Name": "g", "Count": 1,
                "Tasks": [{
                    "Name": "t", "Driver": "mock",
                    "Config": {"run_for": "0s"},
                    "Resources": {"CPU": 50, "MemoryMB": 32},
                }],
            }],
        }
        api.jobs.register(job)

        def terminal_alloc():
            allocs, _ = api.jobs.allocations("gc-job")
            return [a for a in allocs or [] if a["ClientStatus"] == "complete"]

        wait_until(lambda: terminal_alloc(), msg="alloc complete")
        alloc_id = terminal_alloc()[0]["ID"]
        alloc_dir = dev.client.alloc_dir_base
        path = os.path.join(alloc_dir, alloc_id)
        assert os.path.isdir(path), "alloc dir exists before GC"
        assert dev.client.num_allocs() == 1

        out = api.agent.client_gc()
        assert out["Collected"] == 1
        assert not os.path.exists(path), "terminal alloc dir removed"
        assert dev.client.num_allocs() == 0

    def test_gc_loop_respects_max_allocs(self, dev):
        """The background sweep only collects when past thresholds."""
        c = dev.client
        # below thresholds: nothing to collect even with force=False
        assert c.garbage_collect(force=False) == 0
