"""Core-scheduler GC + heartbeat TTL tests (reference nomad/core_sched_test.go
and nomad/heartbeat_test.go): force/threshold GC of terminal evals+allocs,
dead jobs, down nodes and terminal deployments; heartbeat expiry marking
nodes down with node-update evals, TTL re-arm, and clear-on-deregister.
"""
import time

from nomad_tpu import mock
from nomad_tpu.server.core_sched import CoreScheduler
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs.structs import (
    CORE_JOB_FORCE_GC,
    EVAL_TRIGGER_NODE_UPDATE,
    Deployment,
    Evaluation,
)


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def make_server(**kw):
    kw.setdefault("num_schedulers", 0)
    kw.setdefault("heartbeat_min_ttl", 3600)
    kw.setdefault("heartbeat_max_ttl", 7200)
    s = Server(ServerConfig(**kw))
    s.start()
    return s


def force_gc(server):
    ev = Evaluation(job_id=f"{CORE_JOB_FORCE_GC}:all", type="_core")
    CoreScheduler(server, server.fsm.state.snapshot()).process(ev)


class TestCoreGC:
    def test_terminal_eval_and_allocs_gc(self):
        server = make_server()
        try:
            ev = mock.eval()
            ev.status = "complete"
            server.raft_apply("eval-update", [ev])
            alloc = mock.alloc()
            alloc.eval_id = ev.id
            alloc.desired_status = "stop"
            alloc.client_status = "complete"
            server.raft_apply("alloc-update", [alloc])
            force_gc(server)
            assert server.fsm.state.eval_by_id(ev.id) is None
            assert server.fsm.state.alloc_by_id(alloc.id) is None
        finally:
            server.stop()

    def test_running_alloc_blocks_eval_gc(self):
        """An eval with a live alloc survives GC (core_sched_test.go
        TestCoreScheduler_EvalGC_Partial semantics)."""
        server = make_server()
        try:
            ev = mock.eval()
            ev.status = "complete"
            server.raft_apply("eval-update", [ev])
            alloc = mock.alloc()
            alloc.eval_id = ev.id
            alloc.client_status = "running"
            server.raft_apply("alloc-update", [alloc])
            force_gc(server)
            assert server.fsm.state.eval_by_id(ev.id) is not None
            assert server.fsm.state.alloc_by_id(alloc.id) is not None
        finally:
            server.stop()

    def test_dead_job_gc(self):
        server = make_server()
        try:
            job = mock.job()
            job.stop = True
            server.raft_apply("job-register", job)
            # terminal eval so the job has no blocking work
            ev = mock.eval()
            ev.job_id = job.id
            ev.status = "complete"
            server.raft_apply("eval-update", [ev])
            force_gc(server)
            assert server.fsm.state.job_by_id("default", job.id) is None
        finally:
            server.stop()

    def test_running_job_survives_gc(self):
        server = make_server()
        try:
            job = mock.job()
            server.raft_apply("job-register", job)
            force_gc(server)
            assert server.fsm.state.job_by_id("default", job.id) is not None
        finally:
            server.stop()

    def test_down_node_gc(self):
        server = make_server()
        try:
            node = mock.node()
            server.raft_apply("node-register", node)
            server.raft_apply("node-status-update", (node.id, "down"))
            force_gc(server)
            assert server.fsm.state.node_by_id(node.id) is None
        finally:
            server.stop()

    def test_node_with_non_terminal_allocs_survives(self):
        server = make_server()
        try:
            node = mock.node()
            server.raft_apply("node-register", node)
            alloc = mock.alloc()
            alloc.node_id = node.id
            alloc.client_status = "running"
            server.raft_apply("alloc-update", [alloc])
            server.raft_apply("node-status-update", (node.id, "down"))
            force_gc(server)
            assert server.fsm.state.node_by_id(node.id) is not None
        finally:
            server.stop()

    def test_terminal_deployment_gc(self):
        server = make_server()
        try:
            d = Deployment(namespace="default", job_id="gone-job",
                           status="successful")
            server.fsm.state.upsert_deployment(1000, d)
            force_gc(server)
            assert server.fsm.state.deployment_by_id(d.id) is None
        finally:
            server.stop()


class TestHeartbeats:
    def test_ttl_expiry_marks_node_down_and_creates_evals(self):
        server = make_server(heartbeat_min_ttl=0.2, heartbeat_max_ttl=0.3)
        try:
            node = mock.node()
            server.register_node(node)
            job = mock.job()
            alloc = mock.alloc()
            alloc.node_id = node.id
            alloc.job = job
            alloc.job_id = job.id
            alloc.client_status = "running"
            server.raft_apply("job-register", job)
            server.raft_apply("alloc-update", [alloc])
            wait_until(
                lambda: server.fsm.state.node_by_id(node.id).status == "down",
                msg="node marked down on missed heartbeat",
            )
            evs = server.fsm.state.evals_by_job("default", job.id)
            assert any(e.triggered_by == EVAL_TRIGGER_NODE_UPDATE for e in evs)
        finally:
            server.stop()

    def test_heartbeat_rearms_ttl(self):
        server = make_server(heartbeat_min_ttl=0.4, heartbeat_max_ttl=0.5)
        try:
            node = mock.node()
            server.register_node(node)
            for _ in range(4):
                time.sleep(0.2)
                server.heartbeat(node.id)
            assert server.fsm.state.node_by_id(node.id).status == "ready"
        finally:
            server.stop()

    def test_deregister_clears_timer(self):
        server = make_server(heartbeat_min_ttl=0.2, heartbeat_max_ttl=0.3)
        try:
            node = mock.node()
            server.register_node(node)
            assert server.heartbeaters.num_active() == 1
            server.deregister_node(node.id)
            assert server.heartbeaters.num_active() == 0
        finally:
            server.stop()
