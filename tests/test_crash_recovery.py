"""Crash-recovery tests: durable snapshot/restore under load, restart
semantics, leadership-loss nacks, and the real-process SIGKILL E2E.

Three layers, matching the harness's trust chain:

1. raft-level — snapshots taken while apply traffic is live must never
   lose or duplicate entries (both raft impls), and a SIGKILLed server's
   durable meta must prevent double-voting and replay its log tail;
2. engine-level — the async applier nacks (never redispatches) a wave
   whose plan apply lost leadership, and the SLO gate bounds the
   failover MTTR gauges;
3. end-to-end — ``CrashReplay`` SIGKILLs a real leader process mid-wave
   and the surviving cluster elects, recovers, rejoins via
   InstallSnapshot, and passes the invariant sweep (``@pytest.mark.slow``:
   spawns real server processes).
"""
import shutil
import tempfile
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.rpc.transport import RPCServer
from nomad_tpu.server.fsm import NODE_REGISTER, NomadFSM
from nomad_tpu.server.raft import InProcRaft
from nomad_tpu.server.wire_raft import LEADER, WireRaft, WireRaftConfig


def fast_config(node_id: str) -> WireRaftConfig:
    return WireRaftConfig(
        node_id=node_id,
        election_timeout_min=0.15,
        election_timeout_max=0.3,
        heartbeat_interval=0.03,
        rpc_timeout=0.5,
        apply_timeout=5.0,
    )


def wait_until(fn, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class WireNode:
    """One wire-raft participant with its own RPC endpoint and FSM."""

    def __init__(self, node_id: str, data_dir=None):
        self.node_id = node_id
        self.rpc = RPCServer()
        self.fsm = NomadFSM()
        self.data_dir = data_dir
        self.raft = None

    def wire(self, all_nodes, start=True):
        peers = {
            n.node_id: n.rpc.addr for n in all_nodes if n.node_id != self.node_id
        }
        self.raft = WireRaft(
            self.rpc, peers, fast_config(self.node_id), data_dir=self.data_dir
        )
        self.raft.join(self.fsm)
        self.rpc.start()
        if start:
            self.raft.start()
        return self

    def stop(self):
        if self.raft is not None:
            self.raft.close()
        self.rpc.stop()


# ---------------------------------------------------------------------------
# 1a. snapshot under concurrent apply — InProcRaft
# ---------------------------------------------------------------------------


def test_inproc_snapshot_under_concurrent_apply():
    """Hammer apply() from a thread while snapshot() runs in a loop: every
    snapshot must capture a consistent (state, index) pair, the log
    compaction must never eat an entry the snapshot doesn't contain, and
    a fresh join from disk must see every applied entry."""
    tmp = tempfile.mkdtemp(prefix="inproc-snap-")
    n_entries = 150
    try:
        raft = InProcRaft(data_dir=tmp)
        fsm = NomadFSM()
        peer = raft.join(fsm)
        registered = [mock.node() for _ in range(n_entries)]
        errors = []

        def apply_loop():
            try:
                for n in registered:
                    raft.apply(peer, NODE_REGISTER, n)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=apply_loop, daemon=True)
        t.start()
        indexes = []
        while t.is_alive():
            indexes.append(raft.snapshot(peer))
            time.sleep(0.002)
        t.join(timeout=10.0)
        final = raft.snapshot(peer)
        raft.close()

        assert not errors, errors
        assert indexes == sorted(indexes), "snapshot index went backwards"
        assert final == n_entries

        # a rebooted process restores snapshot + tail and sees everything
        raft2 = InProcRaft(data_dir=tmp)
        fsm2 = NomadFSM()
        raft2.join(fsm2)
        for n in registered:
            assert fsm2.state.node_by_id(n.id) is not None, "entry lost"
        assert raft2.last_index == n_entries
        raft2.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def test_inproc_snapshot_stats_surface():
    raft = InProcRaft()
    peer = raft.join(NomadFSM())
    st = raft.stats(peer)
    assert st["state"] == "leader"
    assert st["snapshot_index"] == 0
    assert st["snapshots_installed"] == 0


# ---------------------------------------------------------------------------
# 1b. snapshot under concurrent apply — WireRaft
# ---------------------------------------------------------------------------


def test_wire_raft_snapshot_under_concurrent_apply():
    tmp = tempfile.mkdtemp(prefix="wire-snap-")
    n_entries = 120
    try:
        node = WireNode("solo", data_dir=tmp).wire([])
        try:
            wait_until(lambda: node.raft.state == LEADER, msg="solo leader")
            registered = [mock.node() for _ in range(n_entries)]
            errors = []

            def apply_loop():
                try:
                    for n in registered:
                        node.raft.apply(0, NODE_REGISTER, n)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t = threading.Thread(target=apply_loop, daemon=True)
            t.start()
            indexes = []
            while t.is_alive():
                indexes.append(node.raft.snapshot(0))
                time.sleep(0.002)
            t.join(timeout=15.0)
            assert not errors, errors
            assert indexes == sorted(indexes), "snapshot index went backwards"
            wait_until(lambda: node.raft.last_applied >= node.raft.commit_index,
                       msg="applied caught up")
            final = node.raft.snapshot(0)
            assert final >= max(indexes or [0])
        finally:
            node.stop()

        # restart from disk: snapshot restore + durable tail replay must
        # reconstruct every entry
        node2 = WireNode("solo", data_dir=tmp).wire([])
        try:
            wait_until(lambda: node2.raft.state == LEADER, msg="solo re-leader")
            for n in registered:
                assert node2.fsm.state.node_by_id(n.id) is not None, "entry lost"
        finally:
            node2.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# 1c. durable restart semantics: vote + log tail
# ---------------------------------------------------------------------------


def test_wire_raft_restart_preserves_vote():
    """raft_meta.json survives a crash: a restarted server that granted
    its vote at term T must refuse a DIFFERENT candidate at T — the
    double-vote that durable (term, voted_for) exists to prevent
    (hashicorp/raft persistent state; raft thesis §3.6)."""
    tmp = tempfile.mkdtemp(prefix="wire-vote-")
    try:
        # start=False: no election timer — the node is a pure voter
        node = WireNode("voter", data_dir=tmp)
        node.wire([node], start=False)
        try:
            term, granted = node.raft._handle_request_vote(5, "candA", 10, 5)
            assert granted and term == 5
        finally:
            node.stop()

        node2 = WireNode("voter", data_dir=tmp)
        node2.wire([node2], start=False)
        try:
            assert node2.raft.current_term == 5, "term not persisted"
            assert node2.raft.voted_for == "candA", "vote not persisted"
            # same term, different candidate: must be refused
            term, granted = node2.raft._handle_request_vote(5, "candB", 10, 5)
            assert not granted, "double vote after restart"
            # same candidate retrying is fine (idempotent grant)
            term, granted = node2.raft._handle_request_vote(5, "candA", 10, 5)
            assert granted
        finally:
            node2.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def test_wire_raft_restart_replays_log_tail_to_same_index():
    tmp = tempfile.mkdtemp(prefix="wire-tail-")
    try:
        node = WireNode("solo", data_dir=tmp).wire([])
        try:
            wait_until(lambda: node.raft.state == LEADER, msg="solo leader")
            registered = [mock.node() for _ in range(7)]
            for n in registered:
                node.raft.apply(0, NODE_REGISTER, n)
            last = node.raft._last_index()
            applied = node.raft.last_applied
            assert applied == last
        finally:
            node.stop()

        node2 = WireNode("solo", data_dir=tmp).wire([])
        try:
            # re-election appends its own no-op entry, so the log may
            # GROW past `last` — but nothing before it may be lost
            wait_until(lambda: node2.raft.state == LEADER, msg="solo re-leader")
            assert node2.raft._last_index() >= last, "log tail lost"
            wait_until(lambda: node2.raft.last_applied >= last,
                       msg="tail replayed")
            for n in registered:
                assert node2.fsm.state.node_by_id(n.id) is not None, \
                    "durable entry missing after replay"
        finally:
            node2.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# 2a. applier nacks on leadership loss
# ---------------------------------------------------------------------------


class _RecordingBroker:
    def __init__(self):
        self.acks = []
        self.nacks = []

    def ack(self, eval_id, token):
        self.acks.append((eval_id, token))

    def nack(self, eval_id, token):
        self.nacks.append((eval_id, token))


class _FailedFuture:
    def __init__(self, exc):
        self._exc = exc

    def result(self, timeout=None):
        raise self._exc


def test_applier_nacks_wave_on_leadership_loss():
    """NotLeaderError from the plan apply means this node can commit
    nothing: the wave must be nacked back (for the new leader's eval
    restore to redeliver), never redispatched on the dead pipeline."""
    from types import SimpleNamespace

    from nomad_tpu.pipeline import AsyncApplier
    from nomad_tpu.pipeline.applier import _Wave
    from nomad_tpu.server.raft import NotLeaderError
    from nomad_tpu.structs.structs import Plan

    broker = _RecordingBroker()
    applier = AsyncApplier(server=SimpleNamespace(eval_broker=broker))
    applier._enabled = True
    rec = _Wave(Plan(eval_id="e-lost", async_ok=True), "tok",
                time.monotonic() + 30.0)
    applier._waves[rec.plan.eval_id] = rec
    applier._slots.acquire(blocking=False)

    applier._handle(rec, _FailedFuture(NotLeaderError("leadership lost")))

    assert broker.nacks == [("e-lost", "tok")]
    assert broker.acks == []
    assert rec.done
    assert applier._waves == {}
    # the slot was released exactly once: all inflight_max are available
    assert applier.stats()["slots_free"] == applier.inflight_max


# ---------------------------------------------------------------------------
# 2b. SLO gate failover thresholds
# ---------------------------------------------------------------------------


def test_slo_gate_failover_thresholds():
    from nomad_tpu.chaos import SLOGate, SLOThresholds

    gate = SLOGate(SLOThresholds(
        eval_ms_p99_max=None, slowest_inflight_ms_max=None,
        throughput_min_allocs_per_s=None,
        failover_new_leader_ms_max=5000.0,
        failover_first_commit_ms_max=10000.0,
        require_rejoin=True,
    ))
    base = {
        "trace_summary": {},
        "invariants": {"lost": 0, "duplicated": 0, "converged": True},
    }

    good = dict(base, failover={
        "time_to_new_leader_ms": 900.0, "time_to_first_commit_ms": 950.0,
        "rejoined": True,
    })
    verdict = gate.evaluate(good)
    assert verdict["passed"], verdict["checks"]
    names = {c["name"] for c in verdict["checks"]}
    assert {"failover_time_to_new_leader_ms",
            "failover_time_to_first_commit_ms",
            "killed_server_rejoined"} <= names

    # slow election fails the bound
    slow = dict(base, failover={
        "time_to_new_leader_ms": 9000.0, "time_to_first_commit_ms": 9500.0,
        "rejoined": True,
    })
    assert not gate.evaluate(slow)["passed"]

    # a missing measurement is a failure, not a skip: headless-time
    # that was never measured must not read as "fast"
    unmeasured = dict(base, failover={"rejoined": True})
    assert not gate.evaluate(unmeasured)["passed"]

    # no rejoin fails require_rejoin
    norejoin = dict(base, failover={
        "time_to_new_leader_ms": 900.0, "time_to_first_commit_ms": 950.0,
    })
    assert not gate.evaluate(norejoin)["passed"]


# ---------------------------------------------------------------------------
# 2c. crash-trace validation
# ---------------------------------------------------------------------------


def test_crash_replay_rejects_fault_window_traces():
    from nomad_tpu.chaos import CrashReplay, generate_trace

    tr = generate_trace(1, n_fault_windows=2)
    with pytest.raises(ValueError, match="fault injector is per-process"):
        CrashReplay(seed=1, trace=tr)


def test_crash_replay_rejects_canaried_rollouts():
    from nomad_tpu.chaos import CrashReplay, generate_trace

    tr = generate_trace(1, n_fault_windows=0, canary_frac=1.0)
    with pytest.raises(ValueError, match="deployment nurse"):
        CrashReplay(seed=1, trace=tr)


# ---------------------------------------------------------------------------
# 3a. in-proc replay: canaried rollout + preemption-pressure events
# ---------------------------------------------------------------------------


def test_churn_replay_canaried_rollout_promotes_and_converges():
    from nomad_tpu.chaos import ChurnReplay
    from nomad_tpu.chaos.trace import ChaosEvent

    trace = [
        ChaosEvent(0.1, "register_job",
                   {"job_id": "canary-app", "count": 6, "cpu": 150,
                    "memory_mb": 64, "priority": 50}),
        ChaosEvent(1.5, "rollout",
                   {"job_id": "canary-app", "cpu": 200, "canary": 2}),
    ]
    rep = ChurnReplay(seed=3, trace=trace, n_servers=2, n_nodes=10,
                      settle_timeout_s=60.0)
    res = rep.run()
    assert res["invariants"]["converged"], res["invariants"]["violations"]
    # the rollout really was a canaried deployment, and the nurse
    # promoted it (staged canaries -> healthy -> promote -> full roll)
    deps = rep.servers[0].fsm.state.deployments()
    assert any(
        tg.desired_canaries > 0 and tg.promoted
        for d in deps for tg in d.task_groups.values()
    ), [d.status for d in deps]


def test_churn_replay_preempt_pressure_wave_converges():
    from nomad_tpu.chaos import ChurnReplay
    from nomad_tpu.chaos.trace import ChaosEvent

    trace = [
        ChaosEvent(0.1, "register_job",
                   {"job_id": "steady", "count": 4, "cpu": 150,
                    "memory_mb": 64, "priority": 50}),
        ChaosEvent(1.0, "preempt_pressure",
                   {"wave": 0, "filler_count": 8, "filler_cpu": 600,
                    "memory_mb": 64}),
        ChaosEvent(2.0, "hipri_job",
                   {"job_id": "preempt-hi-0", "count": 2, "cpu": 400,
                    "memory_mb": 64, "priority": 90}),
        ChaosEvent(4.0, "preempt_release", {"wave": 0}),
    ]
    rep = ChurnReplay(seed=4, trace=trace, n_servers=2, n_nodes=8,
                      settle_timeout_s=60.0)
    res = rep.run()
    assert res["invariants"]["converged"], res["invariants"]["violations"]
    # the wave flipped service-scheduler preemption on, through raft
    cfg = rep.servers[0].fsm.state.scheduler_config()[1]
    assert cfg is not None and cfg.preemption_config.service_scheduler_enabled
    # the priority-90 burst placed (into a cluster the fillers saturated)
    run_allocs = [
        a for a in rep.servers[0].fsm.state.allocs_by_job(
            "default", "preempt-hi-0", True)
        if a.desired_status == "run"
    ]
    assert len(run_allocs) == 2


# ---------------------------------------------------------------------------
# 3b. the real thing: SIGKILL a real leader process mid-wave
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_real_process_sigkill_failover_and_snapshot_rejoin():
    """Spawn a real 3-process wire-raft cluster, SIGKILL -9 the leader
    mid-trace, and require the full recovery story: a new leader at a
    higher term, a first post-failover commit, the killed server
    restarted from its data_dir and caught up via InstallSnapshot (the
    new leader snapshots while it is down, compacting the log past its
    durable tail), and an invariant-clean, replica-identical cluster."""
    from nomad_tpu.chaos import CrashReplay, generate_trace

    tr = generate_trace(5, n_jobs=5, n_nodes=15, duration_s=10.0,
                        n_fault_windows=0, n_drains=1, n_expiries=1,
                        leader_kill=True)
    rep = CrashReplay(seed=5, trace=tr, n_servers=3, n_nodes=15,
                      settle_timeout_s=90.0)
    res = rep.run()

    assert res["leader_kills"] == 1
    assert len(res["killed_servers"]) == 1
    fo = res["failover"]
    assert fo["time_to_new_leader_ms"] is not None
    assert fo["time_to_first_commit_ms"] is not None
    assert fo["rejoined"], res["errors"]
    assert fo["snapshot_installs"] >= 1, \
        "rejoin rode AppendEntries — compacted-log path not exercised"
    inv = res["invariants"]
    assert inv["lost"] == 0 and inv["duplicated"] == 0 and inv["orphaned"] == 0
    assert inv["converged"], inv["violations"]
    counts = {k: v for k, v in res["replica_run_counts"].items()
              if v is not None}
    assert len(counts) == 3, "killed server did not come back readable"
    assert len(set(counts.values())) == 1, counts
