"""Dense plan->FSM path: placements stay as arrays (DenseTGPlacements)
from the device scan through plan submit, plan apply and FSM upsert, with
Allocation objects materialized lazily on read.

This is the TPU-native answer to the kernel-vs-system gap: the reference
already normalizes alloc DIFFS on the raft wire (plan_apply.go:324-336);
this design goes further and never materializes per-alloc objects on the
commit path at all.
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import NODE_REGISTER
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    DenseTGPlacements,
    Resources,
)


def dense_job(job_id="dense-job", count=10, cpu=100, mem=128):
    """A service job WITHOUT network/device asks — dense-path eligible."""
    j = mock.job()
    j.id = job_id
    j.task_groups[0].count = count
    j.task_groups[0].tasks[0].resources = Resources(cpu=cpu, memory_mb=mem)
    return j


def wait_for(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def server():
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            device_batch=4, device_batch_window_ms=5.0,
                            device_min_placements=0))  # always device/dense
    s.start()
    yield s
    s.stop()


def _register_nodes(server, n, cpu=4000, mem=8192):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.name = f"dense-{i}"
        node.node_resources.cpu_shares = cpu
        node.node_resources.memory_mb = mem
        node.compute_class()
        server.raft_apply(NODE_REGISTER, node)
        nodes.append(node)
    return nodes


def test_dense_blocks_commit_without_alloc_objects(server):
    _register_nodes(server, 5)
    job = dense_job(count=10)
    server.register_job(job)

    wait_for(
        lambda: server.fsm.state.count_allocs_desired_run() == 10,
        msg="10 dense placements",
    )
    state = server.fsm.state
    # the commit path stored dense blocks, not table allocs
    assert len(state.allocs_table) == 0
    assert sum(len(b.ids) for b in state._dense_blocks) == 10
    # reads materialize on demand and agree across every index
    allocs = state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 10
    a = allocs[0]
    assert a.desired_status == ALLOC_DESIRED_RUN
    assert a.job_id == job.id
    assert a.create_index == a.modify_index > 0
    assert a.allocated_resources.tasks["web"].cpu_shares == 100
    assert a.metrics is not None and a.metrics.score_meta
    assert state.alloc_by_id(a.id) is a  # materialization is cached
    by_node = state.allocs_by_node(a.node_id)
    assert any(x.id == a.id for x in by_node)
    assert len(state.allocs()) == 10
    # names follow the reconciler's name index, one per instance
    assert {x.index() for x in allocs} == set(range(10))


def test_dense_usage_mirror_matches_materialized_usage(server):
    from nomad_tpu.structs.funcs import alloc_usage_vec

    _register_nodes(server, 4)
    job = dense_job(count=8, cpu=250, mem=256)
    server.register_job(job)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 8,
             msg="8 placed")
    state = server.fsm.state
    # mirror rows equal the sum over materialized allocs per node
    per_node = {}
    for a in state.allocs():
        u = alloc_usage_vec(a)
        row = per_node.setdefault(a.node_id, [0.0] * 4)
        for d in range(4):
            row[d] += u[d]
    for node_id, row in per_node.items():
        assert tuple(row) == tuple(state._node_usage[node_id])


def test_client_update_supersedes_dense_slot(server):
    _register_nodes(server, 3)
    job = dense_job(count=3)
    server.register_job(job)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 3,
             msg="3 placed")
    state = server.fsm.state
    target = state.allocs()[0]

    # client sync: the dense slot is superseded by a table alloc
    from nomad_tpu.server.fsm import ALLOC_CLIENT_UPDATE

    update = target.copy_skip_job()
    update.client_status = ALLOC_CLIENT_RUNNING
    server.raft_apply(ALLOC_CLIENT_UPDATE, [update])

    stored = state.alloc_by_id(target.id)
    assert stored.client_status == ALLOC_CLIENT_RUNNING
    assert target.id in state._dense_superseded
    assert target.id in state.allocs_table
    # no duplicates in any read path
    assert len(state.allocs()) == 3
    assert len(state.allocs_by_job(job.namespace, job.id, True)) == 3
    assert (
        sum(1 for a in state.allocs_by_node(target.node_id) if a.id == target.id)
        == 1
    )
    # count helper agrees
    assert state.count_allocs_desired_run() == 3


def test_job_deregister_stops_dense_allocs(server):
    _register_nodes(server, 3)
    job = dense_job(count=6)
    server.register_job(job)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 6,
             msg="6 placed")

    server.deregister_job(job.namespace, job.id, purge=False)
    wait_for(
        lambda: all(
            a.desired_status == ALLOC_DESIRED_STOP
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="all stopped",
    )
    state = server.fsm.state
    # stops superseded every dense slot -> fully-dead blocks compacted,
    # and the usage mirror returned to zero
    assert state._dense_blocks == [] and state._dense_superseded == set()
    assert len(state.allocs_table) == 6
    for node_id, row in state._node_usage.items():
        assert max(row) <= 1e-9, (node_id, row)


def test_fully_superseded_block_compacts_away(server):
    """Once every slot of a block is rewritten as a table alloc (steady-
    state client syncs), the block and all its index entries disappear —
    a long-lived store must not accumulate dead history."""
    from nomad_tpu.server.fsm import ALLOC_CLIENT_UPDATE

    _register_nodes(server, 2)
    job = dense_job(count=4)
    server.register_job(job)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 4,
             msg="4 placed")
    state = server.fsm.state
    assert len(state._dense_blocks) >= 1
    for a in list(state.allocs()):
        upd = a.copy_skip_job()
        upd.client_status = ALLOC_CLIENT_RUNNING
        server.raft_apply(ALLOC_CLIENT_UPDATE, [upd])
    assert state._dense_blocks == []
    assert state._dense_by_id == {}
    assert state._dense_by_job == {}
    assert state._dense_by_node == {}
    assert state._dense_superseded == set()
    assert state._dense_dead == {}
    assert len(state.allocs_table) == 4
    assert state.count_allocs_desired_run() == 4


def test_dense_two_blocks_one_node_all_or_nothing(server):
    """Per-node all-or-nothing must span ALL blocks of a plan (the object
    path's evaluateNodePlan semantics): if the combined asks of two task
    groups exceed a node, NEITHER group's placements commit there."""
    from nomad_tpu.server.plan_apply import PlanQueue, Planner
    from nomad_tpu.structs.structs import Plan

    node = mock.node()
    node.node_resources.cpu_shares = 1000
    node.node_resources.memory_mb = 1024
    node.compute_class()
    server.raft_apply(NODE_REGISTER, node)

    def mk_block(job_id, tg, cpu):
        from nomad_tpu.structs.structs import (
            AllocatedResources,
            AllocatedSharedResources,
        )

        return DenseTGPlacements(
            namespace="default", job_id=job_id, task_group=tg,
            eval_id="e1", ask_vec=(cpu, 100.0, 50.0, 0.0),
            resources_proto=AllocatedResources(
                shared=AllocatedSharedResources(disk_mb=50)
            ),
            ids=[f"{tg}-id"], names=[f"{job_id}.{tg}[0]"],
            node_ids=[node.id], node_names=[node.name],
            scores=[1.0], nodes_evaluated=[1],
        )

    plan = Plan(eval_id="e1", dense_placements=[
        mk_block("j1", "big", 700.0), mk_block("j1", "small", 400.0),
    ])
    snapshot = server.fsm.state.snapshot()
    out, partial = server.planner._evaluate_dense(
        snapshot, plan, __import__(
            "nomad_tpu.structs.structs", fromlist=["PlanResult"]
        ).PlanResult()
    )
    assert partial
    assert out == []  # combined 1100 cpu > 1000: the WHOLE node rejects


def test_dense_partial_commit_on_capacity_conflict(server):
    """Two racing dense plans over one small node: the plan applier's
    vectorized re-check must reject the loser's placements (per-node
    all-or-nothing) and hand back a refresh index."""
    node = mock.node()
    node.node_resources.cpu_shares = 1000
    node.node_resources.memory_mb = 1024
    node.compute_class()
    server.raft_apply(NODE_REGISTER, node)

    # each job fits alone (600 cpu), both together exceed 1000
    j1 = dense_job("dense-a", count=1, cpu=600, mem=300)
    j2 = dense_job("dense-b", count=1, cpu=600, mem=300)
    server.register_job(j1)
    server.register_job(j2)

    # exactly one wins; the other blocks (no capacity) — never both
    def settled():
        placed = server.fsm.state.count_allocs_desired_run()
        blocked = server.blocked_evals.stats()["total_blocked"]
        return placed == 1 and blocked >= 1

    wait_for(settled, msg="one placed, one blocked")
    time.sleep(0.3)  # any double-commit would land by now
    assert server.fsm.state.count_allocs_desired_run() == 1


def test_dense_block_survives_codec_roundtrip():
    from nomad_tpu.rpc.codec import decode, encode

    block = DenseTGPlacements(
        namespace="default", job_id="j1", task_group="web", eval_id="e1",
        ask_vec=(100.0, 128.0, 150.0, 0.0),
        ids=["a1", "a2"], names=["j1.web[0]", "j1.web[1]"],
        node_ids=["n1", "n2"], node_names=["node-1", "node-2"],
        scores=[0.5, 0.25], nodes_evaluated=[3, 3],
        nodes_available={"dc1": 2},
    )
    out = decode(encode(block))
    assert isinstance(out, DenseTGPlacements)
    assert out.ids == block.ids
    assert out.ask_vec == block.ask_vec
    assert out.node_ids == block.node_ids
    a = out.materialize(1)
    assert a.id == "a2" and a.node_id == "n2" and a.name == "j1.web[1]"


def test_dense_store_snapshot_roundtrip(server):
    """Raft-snapshot (codec) roundtrip of a store holding dense blocks:
    derived indexes rebuild, reads agree."""
    from nomad_tpu.server.wire_raft import _decode_fsm_state, _encode_fsm_state

    _register_nodes(server, 3)
    job = dense_job(count=5)
    server.register_job(job)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 5,
             msg="5 placed")

    blob = _encode_fsm_state(server.fsm.state.snapshot())
    restored = _decode_fsm_state(blob)
    assert restored.count_allocs_desired_run() == 5
    allocs = restored.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 5
    a = allocs[0]
    assert restored.alloc_by_id(a.id) is not None
    assert len(restored.allocs_by_node(a.node_id)) >= 1
    # usage mirror survived (it is serialized state, not derived)
    assert restored._node_usage == server.fsm.state._node_usage


def test_encode_cache_shares_arrays_across_identical_jobs(server):
    """Whole-eval encode cache (VERDICT r4 #1/#4): a burst of identical
    fresh jobs encodes ONCE; the cached arrays produce plans identical
    to uncached encoding, and per-eval ring offsets still differ under
    ring decorrelation."""
    _register_nodes(server, 8)

    # widen the gather window so all evals encode BEFORE any commit
    # (one usage epoch -> cache hits); production gets this from the
    # adaptive arrival-gap gather
    server.device_batcher.window_s = 0.5
    jobs = [dense_job(f"cache-{i}", count=6) for i in range(4)]
    for j in jobs:
        server.register_job(j)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 24,
             msg="24 placed")

    # every job fully placed with valid nodes
    for j in jobs:
        allocs = server.fsm.state.allocs_by_job(j.namespace, j.id, True)
        assert len(allocs) == 6
        assert all(a.node_id for a in allocs)

    # all evals gathered into one dispatch encode at ONE usage epoch:
    # at least the later three must have hit the first one's entry
    assert _cache_hits() > 0, "encode cache never hit for identical fresh jobs"


def _cache_hits():
    from nomad_tpu.utils import metrics
    total = 0.0
    sink = metrics.global_sink()
    with sink._lock:
        for iv in sink._intervals:
            agg = iv.counters.get("nomad.tpu_engine.encode_cache_hit")
            if agg is not None:
                total += agg.sum
    return total


def test_encode_cache_invalidated_by_usage_change(server):
    """A committed alloc write bumps usage_epoch: the next eval of an
    identical job must NOT reuse stale usage arrays — its placements
    must account for the capacity the first job consumed."""
    nodes = _register_nodes(server, 2, cpu=1000, mem=2048)
    # job A: 2 allocs of 400 cpu -> one per node under binpack spread?
    # (binpack PACKS; both may land one node). Either way job B's encode
    # must see A's usage: give B asks that only fit the emptier node.
    a = dense_job("use-a", count=2, cpu=400, mem=256)
    server.register_job(a)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 2,
             msg="A placed")
    usage_before = dict(server.fsm.state._node_usage)

    b = dense_job("use-b", count=2, cpu=400, mem=256)
    server.register_job(b)
    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 4,
             msg="B placed")

    # total usage must equal 4 allocs x 400 cpu across the fleet — if B
    # had reused A's pre-commit encoding AND the plan applier somehow
    # accepted it, usage would overcommit a 1000-cpu node
    for node in nodes:
        row = server.fsm.state._node_usage.get(node.id, (0, 0, 0, 0))
        assert row[0] <= 1000, f"node overcommitted: {row}"
    assert usage_before != server.fsm.state._node_usage
