"""Deployment watcher tests: rolling updates, canaries, auto-promote,
auto-revert, progress deadlines — reference nomad/deploymentwatcher/
deployments_watcher_test.go scenarios against the in-process Server."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.deploymentwatcher import (
    DESC_FAILED_ALLOCS,
    DESC_NEWER_JOB,
    DESC_PROGRESS_DEADLINE,
    DESC_SUCCESSFUL,
)
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    AllocDeploymentStatus,
    UpdateStrategy,
)


@pytest.fixture
def server():
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    yield s
    s.stop()


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def deploy_job(server, count=3, canary=0, auto_revert=False, auto_promote=False):
    """Register an updating service job; returns (job, deployment)."""
    for _ in range(count + 2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = count
    job.task_groups[0].update = UpdateStrategy(
        max_parallel=count,
        canary=canary,
        auto_revert=auto_revert,
        auto_promote=auto_promote,
        progress_deadline_ns=10 * 60 * 10**9,
    )
    job.update = job.task_groups[0].update
    server.register_job(job)
    wait_for(
        lambda: server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id)
        is not None,
        msg="deployment created",
    )
    return job, server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id)


def running_allocs(server, job):
    return [
        a
        for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN
    ]


def report_health(server, allocs, healthy=True):
    """Simulate the client's allochealth hook: status sync with health set."""
    updates = []
    for a in allocs:
        u = a.copy_skip_job()
        u.client_status = ALLOC_CLIENT_RUNNING
        u.deployment_status = AllocDeploymentStatus(
            healthy=healthy, timestamp_ns=time.time_ns(),
            canary=(a.deployment_status.canary if a.deployment_status else False),
        )
        updates.append(u)
    server.update_allocs_from_client(updates)


def test_deployment_success_marks_job_stable(server):
    job, d = deploy_job(server, count=3)
    wait_for(lambda: len(running_allocs(server, job)) == 3, msg="3 placed")
    d = server.fsm.state.deployment_by_id(d.id)
    assert d.status == DEPLOYMENT_STATUS_RUNNING
    assert d.task_groups["web"].placed_allocs == 3
    assert d.task_groups["web"].require_progress_by_ns > 0

    report_health(server, running_allocs(server, job))
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="deployment successful",
    )
    assert server.fsm.state.deployment_by_id(d.id).status_description == DESC_SUCCESSFUL
    assert server.fsm.state.job_by_id(job.namespace, job.id).stable is True


def test_unhealthy_alloc_fails_deployment_and_auto_reverts(server):
    # v0: healthy + stable
    job, d0 = deploy_job(server, count=2, auto_revert=True)
    wait_for(lambda: len(running_allocs(server, job)) == 2, msg="v0 placed")
    report_health(server, running_allocs(server, job))
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d0.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="v0 successful",
    )

    # v1: destructive update, goes unhealthy
    job2 = job.copy()
    job2.task_groups[0].tasks[0].env = {"FOO": "v2"}
    server.register_job(job2)
    wait_for(
        lambda: (
            (d := server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id))
            is not None
            and d.id != d0.id
            and d.task_groups["web"].placed_allocs >= 2
        ),
        msg="v1 deployment placing",
    )
    d1 = server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id)
    fresh = [a for a in running_allocs(server, job) if a.deployment_id == d1.id]
    report_health(server, fresh, healthy=False)

    wait_for(
        lambda: server.fsm.state.deployment_by_id(d1.id).status
        == DEPLOYMENT_STATUS_FAILED,
        msg="v1 failed",
    )
    d1 = server.fsm.state.deployment_by_id(d1.id)
    assert DESC_FAILED_ALLOCS in d1.status_description
    assert "rolling back to job version 0" in d1.status_description
    # rollback re-registered v0's content as a fresh version
    wait_for(
        lambda: server.fsm.state.job_by_id(job.namespace, job.id).version > 1,
        msg="rolled back job upserted",
    )
    rolled = server.fsm.state.job_by_id(job.namespace, job.id)
    assert rolled.task_groups[0].tasks[0].env == {"FOO": "bar"}


def test_canary_requires_promotion(server):
    job, d = deploy_job(server, count=3, canary=1)
    wait_for(lambda: len(running_allocs(server, job)) == 3, msg="initial placed")
    report_health(server, running_allocs(server, job))
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="initial deploy done",
    )

    # destructive update → only canaries placed until promotion
    job2 = job.copy()
    job2.task_groups[0].tasks[0].env = {"FOO": "canary"}
    server.register_job(job2)
    wait_for(
        lambda: (
            (nd := server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id))
            is not None
            and nd.id != d.id
            and len(nd.task_groups["web"].placed_canaries) == 1
        ),
        msg="canary placed",
    )
    d2 = server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id)
    assert d2.requires_promotion()

    canary_allocs = [
        server.fsm.state.alloc_by_id(i) for i in d2.task_groups["web"].placed_canaries
    ]
    report_health(server, canary_allocs)
    time.sleep(0.3)
    # healthy canary alone must NOT complete the deployment
    assert (
        server.fsm.state.deployment_by_id(d2.id).status == DEPLOYMENT_STATUS_RUNNING
    )

    server.deployment_watcher.promote(d2.id)
    wait_for(
        lambda: not server.fsm.state.deployment_by_id(d2.id).requires_promotion(),
        msg="promoted",
    )
    # promotion unleashes the rest of the rolling update
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d2.id).task_groups["web"].placed_allocs
        >= 3,
        msg="remaining allocs placed after promote",
    )
    fresh = [a for a in running_allocs(server, job) if a.deployment_id == d2.id]
    report_health(server, fresh)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d2.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="canary deployment successful",
    )


def test_auto_promote(server):
    job, d = deploy_job(server, count=2, canary=1, auto_promote=True)
    wait_for(lambda: len(running_allocs(server, job)) == 2, msg="initial placed")
    report_health(server, running_allocs(server, job))
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="initial done",
    )

    job2 = job.copy()
    job2.task_groups[0].tasks[0].env = {"FOO": "auto"}
    server.register_job(job2)
    wait_for(
        lambda: (
            (nd := server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id))
            is not None
            and nd.id != d.id
            and len(nd.task_groups["web"].placed_canaries) == 1
        ),
        msg="canary placed",
    )
    d2 = server.fsm.state.latest_deployment_by_job_id(job.namespace, job.id)
    canary_allocs = [
        server.fsm.state.alloc_by_id(i) for i in d2.task_groups["web"].placed_canaries
    ]
    report_health(server, canary_allocs)
    # watcher auto-promotes, scheduler finishes the rollout
    wait_for(
        lambda: not server.fsm.state.deployment_by_id(d2.id).requires_promotion(),
        msg="auto-promoted",
    )
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d2.id).task_groups["web"].placed_allocs
        >= 2,
        msg="rollout continues",
    )
    fresh = [a for a in running_allocs(server, job) if a.deployment_id == d2.id]
    report_health(server, fresh)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d2.id).status
        == DEPLOYMENT_STATUS_SUCCESSFUL,
        msg="successful",
    )


def test_progress_deadline_fails_deployment(server):
    job, d = deploy_job(server, count=2)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).task_groups["web"].placed_allocs
        == 2,
        msg="placed",
    )
    # no health reports; force the clock past the deadline
    far_future = time.time_ns() + 11 * 60 * 10**9
    server.deployment_watcher.tick(now_ns=far_future)
    d = server.fsm.state.deployment_by_id(d.id)
    assert d.status == DEPLOYMENT_STATUS_FAILED
    assert DESC_PROGRESS_DEADLINE in d.status_description


def test_pause_blocks_auto_actions_and_fail_endpoint(server):
    job, d = deploy_job(server, count=2)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).task_groups["web"].placed_allocs
        == 2,
        msg="placed",
    )
    server.deployment_watcher.pause(d.id, True)
    assert server.fsm.state.deployment_by_id(d.id).status == DEPLOYMENT_STATUS_PAUSED
    # paused deployments ignore the progress deadline
    server.deployment_watcher.tick(now_ns=time.time_ns() + 11 * 60 * 10**9)
    assert server.fsm.state.deployment_by_id(d.id).status == DEPLOYMENT_STATUS_PAUSED

    server.deployment_watcher.pause(d.id, False)
    assert server.fsm.state.deployment_by_id(d.id).status == DEPLOYMENT_STATUS_RUNNING

    server.deployment_watcher.fail(d.id)
    assert server.fsm.state.deployment_by_id(d.id).status == DEPLOYMENT_STATUS_FAILED


def test_newer_job_version_cancels_deployment(server):
    job, d = deploy_job(server, count=2)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).task_groups["web"].placed_allocs
        == 2,
        msg="placed",
    )
    job2 = job.copy()
    job2.task_groups[0].tasks[0].env = {"FOO": "newer"}
    server.register_job(job2)
    wait_for(
        lambda: server.fsm.state.deployment_by_id(d.id).status
        == DEPLOYMENT_STATUS_CANCELLED,
        msg="old deployment cancelled",
    )
    assert (
        server.fsm.state.deployment_by_id(d.id).status_description == DESC_NEWER_JOB
    )
