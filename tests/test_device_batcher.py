"""Eval-batched device scheduling (SURVEY §2.6 row 1).

Covers the production path the reference realizes as N scheduler workers
per server (nomad/server.go:1307): here, concurrent evals' placement scans
share ONE device dispatch through tpu.batcher.DeviceBatcher. Parity is the
bar: the batched scan must produce bit-identical selections to the
single-eval scan, and batcher-routed scheduling must produce identical
plans to the host pipeline.
"""
import copy
import random
import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Evaluation,
    SchedulerConfiguration,
    Spread,
    SpreadTarget,
)
from nomad_tpu.tpu.batcher import DeviceBatcher, pad_encoded, _pow2ceil
from nomad_tpu.tpu.engine import (
    EncodedEval,
    TpuPlacementEngine,
    example_scan_inputs,
)


def synthetic_enc(n_nodes, n_tgs, n_placements, n_spreads=1, seed=0,
                  dtype=np.float64):
    n_pad, static, carry, xs = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=n_tgs, n_placements=n_placements,
        n_spreads=n_spreads, seed=seed, dtype=dtype,
    )
    return EncodedEval(
        n_real=n_nodes, n_pad=n_pad, g=n_tgs, s=static[9].shape[1],
        v=static[10].shape[2], p=n_placements, dtype=dtype,
        static=static, carry=carry, xs=xs,
        missing_list=[], nodes=[], table=None, start_ns=0,
    )


def run_concurrent(batcher, encs):
    results = [None] * len(encs)
    errors = []

    def submit(i):
        try:
            results[i] = batcher.run(encs[i])
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(len(encs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    return results


class TestBatchedScanParity:
    def test_heterogeneous_batch_matches_single(self):
        """Evals of different node counts, TG counts, placement counts and
        spread shapes padded into one batch must each produce exactly the
        single-eval scan's output (padding is semantically inert)."""
        engine = TpuPlacementEngine.shared()
        encs = [
            synthetic_enc(17, 1, 3, n_spreads=0, seed=1),
            synthetic_enc(64, 3, 16, n_spreads=1, seed=2),
            synthetic_enc(33, 2, 7, n_spreads=2, seed=3),
            synthetic_enc(8, 1, 1, n_spreads=0, seed=4),
            synthetic_enc(50, 4, 11, n_spreads=1, seed=5),
        ]
        singles = [engine.run_scan_single(e) for e in encs]

        batcher = DeviceBatcher(max_batch=len(encs), window_ms=200.0)
        try:
            batched = run_concurrent(batcher, encs)
        finally:
            batcher.stop()

        assert batcher.stats["max_batch_seen"] == len(encs)
        assert batcher.stats["dispatches"] == 1
        for i, (single, batch_r) in enumerate(zip(singles, batched)):
            for k, name in enumerate(("chosen", "scores", "pulls", "skipped")):
                np.testing.assert_array_equal(
                    np.asarray(single[k]), np.asarray(batch_r[k]),
                    err_msg=f"eval {i} {name} diverged under batching",
                )

    def test_uneven_batch_padding(self):
        """3 evals -> batch padded to 4; the inert pad copy must not
        perturb real results."""
        engine = TpuPlacementEngine.shared()
        encs = [synthetic_enc(24, 2, 5, seed=s) for s in (7, 8, 9)]
        singles = [engine.run_scan_single(e) for e in encs]
        batcher = DeviceBatcher(max_batch=8, window_ms=200.0)
        try:
            batched = run_concurrent(batcher, encs)
        finally:
            batcher.stop()
        # multi-eval batches pad to max_batch (two compile buckets total:
        # b=1 and b=max — every intermediate pow2 was its own slow compile)
        assert batcher.stats["padded_evals"] == 5  # 3 -> max_batch 8
        for single, batch_r in zip(singles, batched):
            np.testing.assert_array_equal(single[0], batch_r[0])
            np.testing.assert_array_equal(single[1], batch_r[1])

    def test_pad_encoded_shapes(self):
        enc = synthetic_enc(10, 2, 4, n_spreads=1, seed=0)
        static, carry, xs = pad_encoded(
            enc, n_pad=32, g_pad=4, s_pad=2, v_pad=8, p_pad=8,
            dtype=np.float64,
        )
        d = enc.static[0].shape[1]  # per-job capacity dims (4 + devices)
        assert static[0].shape == (32, d)          # totals
        assert static[3].shape == (4, 32)          # feat_packed (uint8 lanes)
        assert static[9].shape == (4, 2, 32)       # spread_vids
        assert static[10].shape == (4, 2, 8)       # spread_desired
        assert carry[6].shape == (4,)              # failed
        assert carry[6][enc.g:].all()              # padded TGs pre-failed
        assert xs[0].shape == (8,)
        assert (xs[0][enc.p:] == enc.g).all()      # padded steps -> failed TG
        # remapped invalid vocab bucket
        assert (static[9] <= 7).all()
        assert (static[9][:, :, enc.n_pad:] == 7).all()

    def test_mixed_capacity_dims_batch(self):
        """A device job (6 capacity dims) co-batched with deviceless jobs
        (4 dims): D pads across the batch and results stay identical to
        the single-eval scans."""
        import numpy as np

        engine = TpuPlacementEngine.shared()
        lean = synthetic_enc(24, 2, 5, seed=31)
        assert lean.static[0].shape[1] == 4
        # widen one eval to 6 dims manually (as a device job encodes)
        from nomad_tpu.tpu.engine import example_scan_inputs

        n_pad, st, ca, xs = example_scan_inputs(
            n_nodes=24, n_tgs=2, n_placements=5, seed=32,
            dtype=np.float64, num_dims=6,
        )
        st = list(st)
        st[0][:, 4] = 2.0  # 2 free devices per node on dim 4
        st[2][:, 4] = 1.0  # each placement takes one
        wide = EncodedEval(
            n_real=24, n_pad=n_pad, g=2, s=st[9].shape[1],
            v=st[10].shape[2], p=5, dtype=np.float64,
            static=tuple(st), carry=ca, xs=xs,
            missing_list=[], nodes=[], table=None, start_ns=0,
        )
        singles = [engine.run_scan_single(e) for e in (lean, wide)]
        batcher = DeviceBatcher(max_batch=2, window_ms=200.0)
        try:
            batched = run_concurrent(batcher, [lean, wide])
        finally:
            batcher.stop()
        for single, batch_r in zip(singles, batched):
            np.testing.assert_array_equal(single[0], batch_r[0])
            np.testing.assert_array_equal(single[1], batch_r[1])

    def test_mesh_sharded_batch_matches_single(self):
        """The mesh-sharded dispatch (production multi-chip path) is
        bit-identical to the unsharded single scan."""
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs >=4 devices")
        from nomad_tpu.parallel import make_mesh

        engine = TpuPlacementEngine.shared()
        encs = [synthetic_enc(32, 2, 6, seed=s) for s in (11, 12)]
        singles = [engine.run_scan_single(e) for e in encs]
        mesh = make_mesh(4, eval_parallel=2)
        batcher = DeviceBatcher(max_batch=4, window_ms=200.0, mesh=mesh)
        try:
            batched = run_concurrent(batcher, encs)
        finally:
            batcher.stop()
        for single, batch_r in zip(singles, batched):
            np.testing.assert_array_equal(single[0], batch_r[0])
            np.testing.assert_array_equal(single[1], batch_r[1])

    def test_mesh_sharded_c1m_slice_bit_identical(self):
        """VERDICT r3 #5b: a C1M-shaped slice — exact INT spec, DISTINCT
        per-eval inputs, batch sharded over the full ("evals","nodes")
        mesh — must be bitwise identical to the unsharded single-eval
        scans on one device. This is the correctness evidence for the
        production multi-chip dispatch: a shard permutation or wrong-axis
        bug cannot hide behind identical inputs or float tolerance."""
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        from nomad_tpu.parallel import make_mesh

        engine = TpuPlacementEngine.shared()
        # C1M shape, scaled: many nodes relative to devices (node axis
        # shards 512/4 = 128 per device), 2 TGs, spreads active, int32
        encs = [
            synthetic_enc(512, 2, 48, n_spreads=1, seed=100 + s,
                          dtype=np.int32)
            for s in range(4)
        ]
        singles = [engine.run_scan_single(e) for e in encs]
        mesh = make_mesh(8, eval_parallel=2)  # ("evals": 2, "nodes": 4)
        batcher = DeviceBatcher(max_batch=4, window_ms=500.0, mesh=mesh)
        try:
            batched = run_concurrent(batcher, encs)
        finally:
            batcher.stop()
        assert batcher.stats["dispatches"] == 1
        for i, (single, batch_r) in enumerate(zip(singles, batched)):
            for k, name in enumerate(("chosen", "scores", "pulls", "skipped")):
                np.testing.assert_array_equal(
                    np.asarray(single[k]), np.asarray(batch_r[k]),
                    err_msg=(
                        f"eval {i} {name}: sharded dispatch diverged from "
                        "the single-device oracle"
                    ),
                )

    def test_stop_errors_parked_requests(self):
        """stop() must release requests already sitting in the queue (a
        worker parked in run()) with an error, not leave them hanging."""
        from nomad_tpu.tpu.batcher import _Request

        batcher = DeviceBatcher(max_batch=4, window_ms=50.0)
        # park a request WITHOUT a dispatcher thread running
        req = _Request(synthetic_enc(8, 1, 1, seed=0))
        batcher._queue.put(req)
        batcher.stop()
        assert req.event.is_set()
        assert isinstance(req.error, RuntimeError)

    def test_run_after_stop_restarts_lazily(self):
        batcher = DeviceBatcher(max_batch=4, window_ms=50.0)
        batcher._ensure_started()
        batcher.stop()
        # run() restarts the dispatcher lazily; never deadlocks
        out = batcher.run(synthetic_enc(8, 1, 1, seed=0))
        assert out[0].shape == (1,)
        batcher.stop()

    def test_failed_batch_falls_back_per_eval(self):
        """A poisoned co-batched eval must not fail its companions: the
        dispatcher retries each request through the single-eval scan."""
        good = synthetic_enc(16, 1, 2, seed=0)
        bad = synthetic_enc(16, 1, 2, seed=1)
        # corrupt one eval so the stacked dispatch raises (shape mismatch
        # at np.stack time inside _run_batch)
        bad.static = bad.static[:-1]  # drop n_real -> unzips wrong
        batcher = DeviceBatcher(max_batch=2, window_ms=200.0)
        try:
            results = [None, None]
            errors = [None, None]

            def submit(i, enc):
                try:
                    results[i] = batcher.run(enc)
                except BaseException as e:  # noqa: BLE001
                    errors[i] = e

            t0 = threading.Thread(target=submit, args=(0, good))
            t1 = threading.Thread(target=submit, args=(1, bad))
            t0.start(); t1.start(); t0.join(); t1.join()
            assert results[0] is not None, f"good eval failed: {errors[0]}"
            assert errors[1] is not None, "corrupt eval should error"
        finally:
            batcher.stop()


def make_nodes(num, seed):
    rng = random.Random(seed)
    nodes = []
    for i in range(num):
        n = mock.node()
        n.name = f"node-{i}"
        n.node_resources.cpu_shares = rng.choice([2000, 4000, 8000])
        n.node_resources.memory_mb = rng.choice([4096, 8192, 16384])
        n.attributes["rack"] = f"r{rng.randint(0, 3)}"
        n.compute_class()
        nodes.append(n)
    return nodes


def scheduler_plans(nodes, jobs, batcher=None):
    """Run jobs through the harness under tpu_binpack; return
    {(job, alloc name) -> node id} placements."""
    h = Harness()
    if batcher is not None:
        h.device_batcher = batcher
    h.state.scheduler_set_config(
        h.next_index(), SchedulerConfiguration(scheduler_algorithm="tpu_binpack")
    )
    for n in nodes:
        h.state.upsert_node(h.next_index(), copy.deepcopy(n))
    for job in jobs:
        h.state.upsert_job(h.next_index(), copy.deepcopy(job))
    for job in jobs:
        ev = Evaluation(
            priority=job.priority, type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id, namespace=job.namespace,
        )
        h.process("service", ev)
    out = {}
    for plan in h.plans:
        for node_id, allocs in plan.node_allocation.items():
            for a in allocs:
                out[(a.job_id, a.name)] = node_id
    return out


class TestSchedulerThroughBatcher:
    def test_real_scheduler_plans_identical_via_batcher(self):
        """Full scheduler pipeline routed through the DeviceBatcher yields
        the same plans as the direct single-dispatch engine path."""
        nodes = make_nodes(30, seed=42)
        jobs = []
        for i in range(4):
            job = mock.job()
            job.id = f"job-batch-{i}"
            job.task_groups[0].count = 3
            if i % 2:
                job.task_groups[0].spreads = [Spread(
                    attribute="${meta.rack}", weight=50,
                    spread_target=[SpreadTarget(value="r0", percent=50),
                                   SpreadTarget(value="r1", percent=50)],
                )]
            jobs.append(job)

        direct = scheduler_plans(nodes, jobs, batcher=None)
        batcher = DeviceBatcher(max_batch=4, window_ms=5.0)
        try:
            via_batcher = scheduler_plans(nodes, jobs, batcher=batcher)
        finally:
            batcher.stop()
        assert direct == via_batcher
        assert len(via_batcher) == sum(j.task_groups[0].count for j in jobs)
        assert batcher.stats["evals"] == len(jobs)


class TestServerBatchedScheduling:
    def test_concurrent_evals_share_device_dispatch(self):
        """N concurrent evals on a running server are placed via fewer
        device dispatches than evals (the production wiring of SURVEY
        §2.6 row 1), with every allocation placed."""
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_schedulers=0, device_batch=8, device_batch_window_ms=25.0,
            device_min_placements=0,  # this test asserts device dispatch
        ))
        try:
            server.start()
            for i in range(12):
                n = mock.node()
                n.name = f"srv-node-{i}"
                n.compute_class()
                server.register_node(n)

            # enqueue all evals BEFORE workers exist so the flood hits the
            # broker at once (deterministic batching pressure)
            jobs = []
            for i in range(8):
                job = mock.job()
                job.id = f"batched-job-{i}"
                job.task_groups[0].count = 2
                jobs.append(job)
                server.register_job(job)

            from nomad_tpu.server.worker import Worker

            for i in range(4):
                w = Worker(server, i)
                server.workers.append(w)
                w.start()

            deadline = time.monotonic() + 30
            def placed():
                return sum(
                    1 for j in jobs
                    for a in server.fsm.state.allocs_by_job("default", j.id, True)
                )
            while time.monotonic() < deadline and placed() < 16:
                time.sleep(0.05)
            assert placed() == 16, f"only {placed()}/16 allocs placed"

            stats = server.device_batcher.stats
            assert stats["evals"] >= 8
            assert stats["max_batch_seen"] >= 2, (
                f"no eval batching observed: {stats}"
            )
            assert stats["dispatches"] < stats["evals"], stats
        finally:
            server.stop()


class TestAdaptiveGatherLatency:
    def test_trickle_arrivals_latency(self):
        """VERDICT r4 weak #6 / ask #9: when evals arrive at gaps LARGER
        than the idle gap, dispatch latency is bounded by idle_ms — the
        window cap must never hold a lone eval hostage. Each trickled
        eval dispatches alone (stream paused > idle gap), so its gather
        wait stays ~idle_ms even with a 10s window."""
        batcher = DeviceBatcher(max_batch=8, window_ms=10_000.0, idle_ms=30.0)
        try:
            # warm the compile outside the timed phase
            batcher.run(synthetic_enc(32, 1, 4, seed=0))
            waits = []
            for i in range(4):
                enc = synthetic_enc(32, 1, 4, seed=i + 1)
                t0 = time.monotonic()
                batcher.run(enc)
                waits.append(time.monotonic() - t0)
                time.sleep(0.12)  # arrival gap >> idle gap: stream paused
            # each request: one idle-gap wait (~30ms) + dispatch; far
            # below the 10s window. Generous bound for CI jitter, but
            # an order of magnitude under the window cap.
            assert max(waits) < 2.0, waits
            assert batcher.stats["dispatches"] >= 4
            # the latency gauge recorded the gather waits
            assert batcher.stats["gather_wait_ms_max"] >= 0.0
            assert batcher.stats["gather_wait_ms_max"] < 1000.0
        finally:
            batcher.stop()

    def test_burst_gathers_within_idle_gap(self):
        """The complementary direction: requests arriving with gaps
        SMALLER than the idle gap ride one dispatch."""
        batcher = DeviceBatcher(max_batch=8, window_ms=10_000.0, idle_ms=500.0)
        try:
            batcher.run(synthetic_enc(32, 1, 4, seed=0))  # warm
            d0 = batcher.stats["dispatches"]
            encs = [synthetic_enc(32, 1, 4, seed=10 + i) for i in range(4)]
            run_concurrent(batcher, encs)
            assert batcher.stats["dispatches"] == d0 + 1, (
                "a concurrent burst must share one dispatch"
            )
            assert batcher.stats["max_batch_seen"] >= 4
        finally:
            batcher.stop()

    def test_production_defaults_enable_adaptive_gather(self):
        """ServerConfig defaults must exercise the adaptive path
        (idle_ms > 0) with window_ms as a cap, not a tuned constant."""
        from nomad_tpu.server.server import ServerConfig

        cfg = ServerConfig()
        assert cfg.device_batch_idle_ms > 0.0
        assert cfg.device_batch_window_ms >= cfg.device_batch_idle_ms
        # a lone eval's worst-case added latency stays well under one
        # device dispatch (~tens of ms)
        assert cfg.device_batch_idle_ms <= 10.0

    def test_gather_wait_gauge_published(self):
        """The gather-wait latency gauge reaches /v1/metrics via the
        server's stats sweep (nomad.device_batcher.* namespace)."""
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_schedulers=0, device_batch=4,
            heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        ))
        server.start()
        try:
            assert server.device_batcher is not None
            assert "gather_wait_ms_max" in server.device_batcher.stats
            from nomad_tpu.utils import metrics as m

            server.publish_stats_gauges()
            data = m.global_sink().summary()
            gauges = {g["Name"] for g in data.get("Gauges", [])}
            assert any(
                name.startswith("nomad.device_batcher.gather_wait_ms")
                for name in gauges
            ), sorted(n for n in gauges if "batcher" in n)
        finally:
            server.stop()
