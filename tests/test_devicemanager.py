"""Device manager tests: plugin fingerprint → node devices → scheduler
assignment → task reservation env.

Covers reference ``client/devicemanager`` + ``devices/gpu/nvidia`` (here:
the TPU plugin) wired through the whole stack, the way nvidia devices flow
fingerprint → NodeResources.Devices → DeviceChecker/deviceAllocator →
Reserve → NVIDIA_VISIBLE_DEVICES.
"""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
from nomad_tpu.client.devicemanager import (
    DeviceManager,
    DeviceReservationError,
    builtin_device_plugin,
)
from nomad_tpu.plugins.mock_device import MockDevicePlugin
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs.structs import AllocatedDeviceResource, RequestedDevice


class TestDeviceManager:
    def test_fingerprint_merges_into_node(self):
        dm = DeviceManager([MockDevicePlugin(count=3)])
        node = mock.node()
        node.node_resources.devices = []
        dm.fingerprint_node(node)
        devs = node.node_resources.devices
        assert len(devs) == 1
        assert (devs[0].vendor, devs[0].type, devs[0].name) == ("nomad", "gpu", "mock")
        assert len(devs[0].instances) == 3
        assert node.attributes["device.nomad.gpu.mock.count"] == "3"
        assert node.attributes["device.nomad.gpu.mock.memory_mib"] == "4096"

    def test_reserve_routes_to_owning_plugin(self):
        dm = DeviceManager([MockDevicePlugin(count=2)])
        dm.fingerprint()
        res = dm.reserve([
            AllocatedDeviceResource(vendor="nomad", type="gpu", name="mock",
                                    device_ids=["mock-0", "mock-1"])
        ])
        assert res.envs == {"MOCK_VISIBLE_DEVICES": "mock-0,mock-1"}

    def test_reserve_unknown_group_raises(self):
        dm = DeviceManager([MockDevicePlugin()])
        dm.fingerprint()
        with pytest.raises(DeviceReservationError):
            dm.reserve([AllocatedDeviceResource(vendor="x", type="y", name="z",
                                                device_ids=["a"])])

    def test_sick_plugin_does_not_kill_fingerprint(self):
        class Sick(MockDevicePlugin):
            def fingerprint(self):
                raise RuntimeError("nvml exploded")

        dm = DeviceManager([Sick(), MockDevicePlugin(model="ok")])
        groups = dm.fingerprint()
        assert [g.name for g in groups] == ["ok"]

    def test_builtin_factory(self):
        p = builtin_device_plugin("mock-device", {"count": 5})
        assert len(p.fingerprint()[0].devices) == 5
        with pytest.raises(ValueError):
            builtin_device_plugin("nope")


class TestTPUDevicePlugin:
    def test_fingerprint_and_reserve(self):
        """On this host JAX sees at least one device (CPU fallback or real
        TPU); the plugin must expose them and reserve with env vars."""
        p = builtin_device_plugin("tpu")
        groups = p.fingerprint()
        assert groups, "expected at least one jax device group"
        g = groups[0]
        assert g.vendor == "google" and g.devices
        ids = [d.id for d in g.devices]
        res = p.reserve(ids[:1])
        assert res.envs["TPU_VISIBLE_CHIPS"] == ids[0]
        with pytest.raises(ValueError):
            p.reserve(["not-a-chip"])


class TestEndToEndDeviceScheduling:
    def test_task_gets_device_env(self, tmp_path):
        """Job asks for a device → scheduler assigns instances →
        task runner reserves → task process sees the reservation env."""
        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60))
        server.start()
        client = Client(
            ServerProxy(server),
            ClientConfig(device_plugins={"mock-device": {"count": 2}}),
        )
        try:
            client.start()
            # the registered node advertises the mock devices
            stored = server.fsm.state.node_by_id(client.node.id)
            assert stored.node_resources.devices, "devices registered"

            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", "env > $NOMAD_TASK_DIR/envdump; sleep 30"],
            }
            task.resources.devices = [RequestedDevice(name="gpu/mock", count=2)]
            server.register_job(job)

            deadline = time.monotonic() + 30
            alloc = None
            while time.monotonic() < deadline:
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                if allocs and allocs[0].client_status == "running":
                    alloc = allocs[0]
                    break
                time.sleep(0.2)
            assert alloc is not None, "alloc never ran"
            # scheduler recorded the instance assignment
            task_res = alloc.allocated_resources.tasks[task.name]
            assert task_res.devices and sorted(task_res.devices[0].device_ids) == \
                ["mock-0", "mock-1"]
            # the task's environment carries the reservation
            dump = os.path.join(client.alloc_dir_base, alloc.id, task.name,
                                "local", "envdump")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not os.path.exists(dump):
                time.sleep(0.1)
            env_text = open(dump).read()
            assert "MOCK_VISIBLE_DEVICES=mock-0,mock-1" in env_text
        finally:
            client.shutdown()
            server.stop()
