"""Node drainer and periodic dispatcher tests (reference nomad/drainer/
drainer_test.go + watch_jobs_test.go scenarios, nomad/periodic_test.go)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.periodic import CronExpr, next_launch_ns
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    NODE_SCHED_INELIGIBLE,
    DrainStrategy,
    MigrateStrategy,
    PeriodicConfig,
)


@pytest.fixture
def server():
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    yield s
    s.stop()


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def mark_running(server, job):
    """Client sim: report every run-desired alloc as running."""
    ups = []
    for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True):
        if a.desired_status == ALLOC_DESIRED_RUN and a.client_status != ALLOC_CLIENT_RUNNING:
            u = a.copy_skip_job()
            u.client_status = ALLOC_CLIENT_RUNNING
            ups.append(u)
    if ups:
        server.update_allocs_from_client(ups)
    return len(ups)


# ---------------------------------------------------------------------------
# drainer
# ---------------------------------------------------------------------------


def test_drain_migrates_allocs_and_completes(server):
    nodes = [mock.node() for _ in range(3)]
    for n in nodes:
        server.register_node(n)
    job = mock.job()
    job.task_groups[0].count = 3
    job.task_groups[0].migrate = MigrateStrategy(max_parallel=1)
    server.register_job(job)
    wait_for(lambda: len([
        a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN
    ]) == 3, msg="3 placed")
    mark_running(server, job)

    victim = server.fsm.state.allocs_by_job(job.namespace, job.id, True)[0].node_id
    server.update_node_drain(victim, DrainStrategy(deadline_ns=60 * 10**9))

    # keep simulating the client while the drain progresses
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        mark_running(server, job)
        node = server.fsm.state.node_by_id(victim)
        if not node.drain:
            break
        time.sleep(0.05)
    node = server.fsm.state.node_by_id(victim)
    assert not node.drain, "drain did not complete"
    assert node.drain_strategy is None
    # drain completion leaves the node ineligible
    assert node.scheduling_eligibility == NODE_SCHED_INELIGIBLE
    live = [
        a for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN and not a.terminal_status()
    ]
    assert len(live) == 3
    assert all(a.node_id != victim for a in live)


def test_drain_batches_respect_max_parallel():
    """Unit: the first tick marks at most max_parallel per task group and no
    more until replacements are healthy."""
    s = Server(ServerConfig(num_schedulers=0, deterministic=True,
                            scheduler_algorithm="binpack"))
    # no s.start(): drive the drainer by hand
    node_a, node_b = mock.node(), mock.node()
    s.register_node(node_a)
    s.register_node(node_b)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].migrate = MigrateStrategy(max_parallel=2)
    s.fsm.state.upsert_job(10, job)
    allocs = []
    for i in range(4):
        a = mock.alloc()
        a.namespace, a.job_id, a.job = job.namespace, job.id, job
        a.task_group = job.task_groups[0].name
        a.node_id = node_a.id
        a.client_status = ALLOC_CLIENT_RUNNING
        allocs.append(a)
    s.fsm.state.upsert_allocs(11, allocs)
    s.update_node_drain(node_a.id, DrainStrategy(deadline_ns=3600 * 10**9))

    s.node_drainer.tick()
    marked = [
        a for a in s.fsm.state.allocs_by_node(node_a.id)
        if a.desired_transition.should_migrate()
    ]
    assert len(marked) == 2  # first batch == max_parallel

    # replacements not up yet: a second tick must not widen the batch
    s.node_drainer.tick()
    marked = [
        a for a in s.fsm.state.allocs_by_node(node_a.id)
        if a.desired_transition.should_migrate()
    ]
    assert len(marked) == 2

    # two replacements healthy on node B -> next batch of 2 unlocks
    reps = []
    for i in range(2):
        r = mock.alloc()
        r.namespace, r.job_id, r.job = job.namespace, job.id, job
        r.task_group = job.task_groups[0].name
        r.node_id = node_b.id
        r.client_status = ALLOC_CLIENT_RUNNING
        reps.append(r)
    s.fsm.state.upsert_allocs(12, reps)
    # the first batch stopped on the client
    stopped = []
    for a in marked:
        u = a.copy_skip_job()
        u.client_status = "complete"
        stopped.append(u)
    s.fsm.state.update_allocs_from_client(13, stopped)

    s.node_drainer.tick()
    fresh_marks = [
        a for a in s.fsm.state.allocs_by_node(node_a.id)
        if a.desired_transition.should_migrate() and not a.terminal_status()
    ]
    assert len(fresh_marks) == 2  # second batch unlocked
    all_marked = [
        a for a in s.fsm.state.allocs_by_node(node_a.id)
        if a.desired_transition.should_migrate()
    ]
    assert len(all_marked) == 4


def test_system_allocs_drain_last_and_deadline_forces():
    s = Server(ServerConfig(num_schedulers=0, deterministic=True,
                            scheduler_algorithm="binpack"))
    node = mock.node()
    s.register_node(node)
    svc = mock.job()
    svc.task_groups[0].count = 1
    s.fsm.state.upsert_job(10, svc)
    sys_job = mock.system_job()
    s.fsm.state.upsert_job(11, sys_job)

    a_svc = mock.alloc()
    a_svc.namespace, a_svc.job_id, a_svc.job = svc.namespace, svc.id, svc
    a_svc.task_group = svc.task_groups[0].name
    a_svc.node_id = node.id
    a_svc.client_status = ALLOC_CLIENT_RUNNING
    a_sys = mock.alloc()
    a_sys.namespace, a_sys.job_id, a_sys.job = sys_job.namespace, sys_job.id, sys_job
    a_sys.task_group = sys_job.task_groups[0].name
    a_sys.node_id = node.id
    a_sys.client_status = ALLOC_CLIENT_RUNNING
    s.fsm.state.upsert_allocs(12, [a_svc, a_sys])

    s.update_node_drain(node.id, DrainStrategy(deadline_ns=3600 * 10**9))
    s.node_drainer.tick()
    sys_alloc = s.fsm.state.alloc_by_id(a_sys.id)
    assert not sys_alloc.desired_transition.should_migrate(), "system drained too early"
    svc_alloc = s.fsm.state.alloc_by_id(a_svc.id)
    assert svc_alloc.desired_transition.should_migrate()

    # force past the deadline: the system alloc goes too
    s.node_drainer.tick(now_ns=time.time_ns() + 2 * 3600 * 10**9)
    sys_alloc = s.fsm.state.alloc_by_id(a_sys.id)
    assert sys_alloc.desired_transition.should_migrate()


def test_ignore_system_jobs_completes_with_system_left():
    s = Server(ServerConfig(num_schedulers=0, deterministic=True,
                            scheduler_algorithm="binpack"))
    node = mock.node()
    s.register_node(node)
    sys_job = mock.system_job()
    s.fsm.state.upsert_job(10, sys_job)
    a_sys = mock.alloc()
    a_sys.namespace, a_sys.job_id, a_sys.job = sys_job.namespace, sys_job.id, sys_job
    a_sys.task_group = sys_job.task_groups[0].name
    a_sys.node_id = node.id
    a_sys.client_status = ALLOC_CLIENT_RUNNING
    s.fsm.state.upsert_allocs(11, [a_sys])

    s.update_node_drain(
        node.id, DrainStrategy(deadline_ns=3600 * 10**9, ignore_system_jobs=True)
    )
    s.node_drainer.tick()
    node_after = s.fsm.state.node_by_id(node.id)
    assert not node_after.drain, "drain should complete with only ignored system allocs"
    sys_alloc = s.fsm.state.alloc_by_id(a_sys.id)
    assert not sys_alloc.desired_transition.should_migrate()


# ---------------------------------------------------------------------------
# cron / periodic
# ---------------------------------------------------------------------------


def test_cron_expr_basics():
    from datetime import datetime, timezone

    utc = timezone.utc
    e = CronExpr("*/15 * * * *")
    nxt = e.next_after(datetime(2026, 7, 29, 10, 7, tzinfo=utc))
    assert (nxt.hour, nxt.minute) == (10, 15)
    e = CronExpr("0 12 * * *")
    nxt = e.next_after(datetime(2026, 7, 29, 13, 0, tzinfo=utc))
    assert (nxt.day, nxt.hour, nxt.minute) == (30, 12, 0)
    # next-after is strict
    nxt = e.next_after(datetime(2026, 7, 29, 12, 0, tzinfo=utc))
    assert nxt.day == 30
    # dow: 2026-08-03 is a Monday
    e = CronExpr("30 6 * * 1")
    nxt = e.next_after(datetime(2026, 7, 29, 0, 0, tzinfo=utc))
    assert (nxt.month, nxt.day, nxt.hour, nxt.minute) == (8, 3, 6, 30)
    with pytest.raises(ValueError):
        CronExpr("* * * *")
    with pytest.raises(ValueError):
        CronExpr("61 * * * *")


def test_periodic_job_launches_children(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.periodic = PeriodicConfig(enabled=True, spec="* * * * *")
    server.register_job(job)

    # registration returns no eval; the dispatcher tracks it
    assert (job.namespace, job.id) in server.periodic_dispatcher.tracked
    _, nxt = server.periodic_dispatcher.tracked[(job.namespace, job.id)]
    assert nxt is not None and 0 < nxt - time.time_ns() <= 61 * 10**9

    child_id = server.periodic_dispatcher.force_launch(job.namespace, job.id)
    assert child_id is not None and child_id.startswith(f"{job.id}/periodic-")
    wait_for(
        lambda: len(server.fsm.state.allocs_by_job(job.namespace, child_id, True)) == 1,
        msg="child scheduled",
    )
    child = server.fsm.state.job_by_id(job.namespace, child_id)
    assert child.parent_id == job.id
    assert not child.is_periodic()
    assert server.fsm.state.periodic_launch_by_id(job.namespace, job.id) > 0


def test_prohibit_overlap_skips_launch(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.periodic = PeriodicConfig(enabled=True, spec="* * * * *", prohibit_overlap=True)
    server.register_job(job)

    first = server.periodic_dispatcher.force_launch(job.namespace, job.id)
    assert first is not None
    wait_for(
        lambda: len(server.fsm.state.allocs_by_job(job.namespace, first, True)) == 1,
        msg="first child scheduled",
    )
    # child still live (allocs not terminal) -> overlap prohibited
    second = server.periodic_dispatcher.force_launch(
        job.namespace, job.id, launch_ns=time.time_ns() + 10**9
    )
    assert second is None


def test_two_draining_nodes_share_max_parallel_budget():
    """max_parallel is a per-task-group budget across ALL draining nodes,
    not per node."""
    s = Server(ServerConfig(num_schedulers=0, deterministic=True,
                            scheduler_algorithm="binpack"))
    node_a, node_b = mock.node(), mock.node()
    s.register_node(node_a)
    s.register_node(node_b)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].migrate = MigrateStrategy(max_parallel=1)
    s.fsm.state.upsert_job(10, job)
    allocs = []
    for node in (node_a, node_b):
        for _ in range(2):
            a = mock.alloc()
            a.namespace, a.job_id, a.job = job.namespace, job.id, job
            a.task_group = job.task_groups[0].name
            a.node_id = node.id
            a.client_status = ALLOC_CLIENT_RUNNING
            allocs.append(a)
    s.fsm.state.upsert_allocs(11, allocs)
    s.update_node_drain(node_a.id, DrainStrategy(deadline_ns=3600 * 10**9))
    s.update_node_drain(node_b.id, DrainStrategy(deadline_ns=3600 * 10**9))

    s.node_drainer.tick()
    marked = [
        a for a in s.fsm.state.allocs()
        if a.desired_transition.should_migrate()
    ]
    assert len(marked) == 1  # one group budget, not one per node


def test_overlap_releases_when_child_finishes(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.periodic = PeriodicConfig(enabled=True, spec="* * * * *", prohibit_overlap=True)
    server.register_job(job)

    first = server.periodic_dispatcher.force_launch(job.namespace, job.id)
    wait_for(
        lambda: len(server.fsm.state.allocs_by_job(job.namespace, first, True)) == 1,
        msg="first child scheduled",
    )
    server.drain_evals()
    # the batch-style child finishes: allocs terminal
    ups = []
    for a in server.fsm.state.allocs_by_job(job.namespace, first, True):
        u = a.copy_skip_job()
        u.client_status = "complete"
        ups.append(u)
    server.update_allocs_from_client(ups)
    wait_for(
        lambda: server.periodic_dispatcher.force_launch(
            job.namespace, job.id, launch_ns=time.time_ns()
        )
        is not None,
        msg="second launch allowed after child finished",
    )


def test_reregister_without_periodic_untracks(server):
    job = mock.job()
    job.periodic = PeriodicConfig(enabled=True, spec="* * * * *")
    server.register_job(job)
    assert (job.namespace, job.id) in server.periodic_dispatcher.tracked

    job2 = job.copy()
    job2.periodic = None
    server.register_job(job2)
    assert (job.namespace, job.id) not in server.periodic_dispatcher.tracked


def test_cron_respects_job_timezone():
    from nomad_tpu.structs.structs import Job

    job = mock.job()
    job.periodic = PeriodicConfig(
        enabled=True, spec="0 12 * * *", timezone="America/New_York"
    )
    # 2026-07-29 00:00 UTC; noon Eastern (EDT, UTC-4) == 16:00 UTC
    from datetime import datetime, timezone as _tz

    after_ns = int(datetime(2026, 7, 29, 0, 0, tzinfo=_tz.utc).timestamp() * 1e9)
    nxt = next_launch_ns(job, after_ns)
    launched = datetime.fromtimestamp(nxt / 1e9, tz=_tz.utc)
    assert (launched.hour, launched.minute) == (16, 0)


def test_missed_launch_fires_on_restore(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    job.periodic = PeriodicConfig(enabled=True, spec="0 3 * * *")
    server.register_job(job)

    # pretend the last launch was two days ago -> one launch was missed
    two_days_ago = time.time_ns() - 2 * 24 * 3600 * 10**9
    server.fsm.state.upsert_periodic_launch(
        server.fsm.state.latest_index + 1, job.namespace, job.id, two_days_ago
    )
    server.periodic_dispatcher.set_enabled(False)
    server.periodic_dispatcher.set_enabled(True)
    wait_for(
        lambda: len(server.fsm.state.jobs_by_parent(job.namespace, job.id)) >= 1,
        msg="missed launch fired on restore",
    )
    child = server.fsm.state.jobs_by_parent(job.namespace, job.id)[0]
    assert child.id.startswith(f"{job.id}/periodic-")
