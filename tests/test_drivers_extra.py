"""Docker/java/qemu driver tests (reference drivers/docker,
drivers/java, drivers/qemu) — docker against the in-tree fake daemon,
java/qemu as command-construction + gating checks.
"""
import os
import time

import pytest

from nomad_tpu.client.drivers.base import (
    DriverError,
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    TaskConfig,
)
from nomad_tpu.client.drivers.docker import DockerDriver
from nomad_tpu.client.drivers.java_driver import JavaDriver, java_cmd_args
from nomad_tpu.client.drivers.qemu import QemuDriver, qemu_args

from fake_docker import FakeDocker


@pytest.fixture
def dockerd(tmp_path):
    sock = str(tmp_path / "docker.sock")
    fake = FakeDocker(sock).start()
    yield fake
    fake.stop()


@pytest.fixture
def driver(dockerd):
    d = DockerDriver(dockerd.socket_path)
    d.coordinator.image_gc = True
    return d


class TestDockerDriver:
    def test_fingerprint(self, driver, tmp_path):
        fp = driver.fingerprint()
        assert fp.health == HEALTH_HEALTHY
        assert fp.attributes["driver.docker.version"] == "fake-24.0"
        dead = DockerDriver(str(tmp_path / "nope.sock"))
        assert dead.fingerprint().health == HEALTH_UNDETECTED

    def test_full_lifecycle(self, driver, dockerd, tmp_path):
        cfg = TaskConfig(
            id="a1/web", name="web", alloc_id="a1",
            env={"PORT": "80"},
            config={"image": "redis:7", "command": "redis-server",
                    "args": ["--appendonly", "yes"]},
            cpu_limit=500, memory_limit_mb=256,
        )
        handle = driver.start_task(cfg)
        cid = handle.driver_state["container_id"]
        assert "redis:7" in dockerd.images, "image pulled"
        c = dockerd.containers[cid]
        assert c.state == "running"
        assert c.config["Cmd"] == ["redis-server", "--appendonly", "yes"]
        assert "PORT=80" in c.config["Env"]
        assert c.config["HostConfig"]["Memory"] == 256 << 20
        assert driver.inspect_task("a1/web").state == "running"
        assert driver.wait_task("a1/web", timeout=0.2) is None

        stats = driver.task_stats("a1/web")
        assert stats.memory_rss_bytes == 1024 * 1024

        dockerd.finish(cid, 3)
        res = driver.wait_task("a1/web", timeout=5.0)
        assert res is not None and res.exit_code == 3
        driver.destroy_task("a1/web")
        assert cid not in dockerd.containers
        assert "redis:7" in dockerd.removed_images, "image gc on last release"

    def test_stop_uses_graceful_then_kill(self, driver, dockerd):
        cfg = TaskConfig(id="a2/t", name="t", alloc_id="a2",
                         config={"image": "busybox:latest"})
        handle = driver.start_task(cfg)
        cid = handle.driver_state["container_id"]
        driver.stop_task("a2/t", timeout_s=1.0)
        res = driver.wait_task("a2/t", timeout=5.0)
        assert res is not None
        assert dockerd.containers[cid].state == "exited"

    def test_image_refcounting(self, driver, dockerd):
        h1 = driver.start_task(TaskConfig(id="r1/t", name="t", alloc_id="r1",
                                          config={"image": "shared:1"}))
        h2 = driver.start_task(TaskConfig(id="r2/t", name="t", alloc_id="r2",
                                          config={"image": "shared:1"}))
        assert dockerd.images["shared:1"] == 1, "one pull for two tasks"
        dockerd.finish(h1.driver_state["container_id"], 0)
        driver.wait_task("r1/t", timeout=5)
        driver.destroy_task("r1/t")
        assert "shared:1" not in dockerd.removed_images, "still referenced"
        dockerd.finish(h2.driver_state["container_id"], 0)
        driver.wait_task("r2/t", timeout=5)
        driver.destroy_task("r2/t")
        assert "shared:1" in dockerd.removed_images

    def test_log_pump_demuxes_streams(self, driver, dockerd, tmp_path):
        out_path = str(tmp_path / "t.stdout.0")
        err_path = str(tmp_path / "t.stderr.0")
        cfg = TaskConfig(id="l1/t", name="t", alloc_id="l1",
                         config={"image": "busybox:latest"},
                         stdout_path=out_path, stderr_path=err_path)
        handle = driver.start_task(cfg)
        cid = handle.driver_state["container_id"]
        dockerd.add_log(cid, 1, b"to stdout\n")
        dockerd.add_log(cid, 2, b"to stderr\n")
        # the pump reads the (non-follow in fake) stream once available
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if os.path.exists(out_path) and os.path.getsize(out_path) > 0:
                break
            time.sleep(0.05)
        assert open(out_path, "rb").read() == b"to stdout\n"
        assert open(err_path, "rb").read() == b"to stderr\n"
        dockerd.finish(cid, 0)

    def test_reconciler_removes_dangling(self, driver, dockerd):
        handle = driver.start_task(TaskConfig(id="k1/t", name="t", alloc_id="k1",
                                              config={"image": "busybox:latest"}))
        tracked_cid = handle.driver_state["container_id"]
        # a leaked container with the nomad label
        from fake_docker import FakeContainer

        leaked = FakeContainer("nomad-leaked", {
            "Labels": {"com.hashicorp.nomad.alloc_id": "dead"}})
        dockerd.containers[leaked.id] = leaked
        removed = driver.reconcile_dangling()
        assert removed == [leaked.id]
        assert tracked_cid in dockerd.containers, "tracked container kept"

    def test_recover_running_container(self, driver, dockerd):
        cfg = TaskConfig(id="rec/t", name="t", alloc_id="rec",
                         config={"image": "busybox:latest"})
        handle = driver.start_task(cfg)
        fresh = DockerDriver(dockerd.socket_path)
        fresh.recover_task(handle)
        assert fresh.inspect_task("rec/t").state == "running"
        dockerd.finish(handle.driver_state["container_id"], 0)
        assert fresh.wait_task("rec/t", timeout=5.0) is not None

    def test_pull_failure_surfaces(self, driver, dockerd):
        dockerd.fail_pull = True
        with pytest.raises(DriverError, match="pull failed"):
            driver.start_task(TaskConfig(id="p/t", name="t", alloc_id="p",
                                         config={"image": "nope:latest"}))

    def test_exec(self, driver, dockerd):
        handle = driver.start_task(TaskConfig(id="e/t", name="t", alloc_id="e",
                                              config={"image": "busybox:latest"}))
        out, code = driver.exec_task("e/t", ["echo", "hi"], timeout_s=5.0)
        assert code == 7  # fake reports ExitCode 7
        assert out == b"hi\n", "attached exec output demuxed"
        dockerd.finish(handle.driver_state["container_id"], 0)


class TestJavaDriver:
    def test_cmd_args(self):
        assert java_cmd_args({"jar_path": "/x/app.jar", "args": ["serve"],
                              "jvm_options": ["-Xmx256m"]}) == \
            ["-Xmx256m", "-jar", "/x/app.jar", "serve"]
        assert java_cmd_args({"class": "com.App", "class_path": "/lib/*"}) == \
            ["-cp", "/lib/*", "com.App"]
        with pytest.raises(DriverError):
            java_cmd_args({})

    def test_fingerprint_gated(self):
        import shutil

        fp = JavaDriver().fingerprint()
        if shutil.which("java"):
            assert fp.health == HEALTH_HEALTHY
        else:
            assert fp.health == HEALTH_UNDETECTED


class TestQemuDriver:
    def test_args(self):
        cfg = TaskConfig(name="vm", memory_limit_mb=1024,
                         config={"image_path": "/img/linux.qcow2",
                                 "port_map": {"22": 2222}})
        args = qemu_args(cfg)
        assert "-m" in args and "1024M" in args
        assert "file=/img/linux.qcow2" in " ".join(args)
        assert any("hostfwd=tcp::2222-:22" in a for a in args)
        with pytest.raises(DriverError):
            qemu_args(TaskConfig(config={}))

    def test_fingerprint_gated(self):
        import shutil

        fp = QemuDriver().fingerprint()
        if shutil.which("qemu-system-x86_64"):
            assert fp.health == HEALTH_HEALTHY
        else:
            assert fp.health == HEALTH_UNDETECTED
