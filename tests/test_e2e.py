"""E2E suites over real agent processes (reference e2e/: rescheduling/,
spread/, deployment/, clientstate/) — black-box through the SDK only.
"""
import os
import time

import pytest

from e2e_framework import (
    AgentProc,
    allocs_of,
    running_allocs,
    service_job,
    wait_until,
)


@pytest.fixture(scope="module")
def dev():
    agent = AgentProc("-dev", "-no-gossip", name="dev")
    yield agent
    agent.stop()


class TestJobLifecycle:
    def test_run_update_stop(self, dev):
        api = dev.api
        job = service_job("e2e-life", count=2, command="sleep 300")
        api.jobs.register(job)
        wait_until(lambda: len(running_allocs(api, "e2e-life")) == 2,
                   msg="2 allocs running")
        # scale down via re-register
        job["TaskGroups"][0]["Count"] = 1
        api.jobs.register(job)
        wait_until(lambda: len(running_allocs(api, "e2e-life")) == 1,
                   msg="scaled to 1")
        api.jobs.deregister("e2e-life")
        wait_until(lambda: not running_allocs(api, "e2e-life"),
                   msg="all stopped")


class TestRescheduling:
    def test_failed_alloc_rescheduled(self, dev):
        """reference e2e/rescheduling: a dying task is replaced on a new
        alloc rather than restarted forever in place."""
        api = dev.api
        job = service_job("e2e-resched", count=1, command="exit 1")
        job["TaskGroups"][0]["Tasks"][0]["RestartPolicy"] = {
            "Attempts": 0, "Mode": "fail", "IntervalNs": 5_000_000_000,
            "DelayNs": 100_000_000,
        }
        job["TaskGroups"][0]["ReschedulePolicy"] = {
            "Attempts": 2, "IntervalNs": 60_000_000_000,
            "DelayNs": 500_000_000, "DelayFunction": "constant",
            "Unlimited": False,
        }
        api.jobs.register(job)
        wait_until(
            lambda: len([a for a in allocs_of(api, "e2e-resched")
                         if a["ClientStatus"] == "failed"]) >= 1
            and len(allocs_of(api, "e2e-resched")) >= 2,
            msg="failed alloc replaced by reschedule",
        )
        # replacements chain via PreviousAllocation/NextAllocation
        allocs = allocs_of(api, "e2e-resched")
        infos = [api.allocations.info(a["ID"])[0] for a in allocs]
        assert any(i.get("PreviousAllocation") for i in infos), \
            "reschedule links predecessor"


class TestSpreadAcrossNodes:
    def test_allocs_spread_on_two_clients(self):
        """reference e2e/spread: a spread stanza distributes allocs
        across client nodes (real server + 2 real client processes)."""
        server = AgentProc("-server", "-no-gossip", name="spread-srv")
        # discover the server's RPC address through its API
        raft, _ = server.api.get("/v1/operator/raft/configuration")
        rpc_addr = raft["Servers"][0]["Address"]
        clients = [
            AgentProc("-client", "-servers", rpc_addr, "-no-gossip",
                      "-node-class", f"rack{i}", name=f"spread-c{i}")
            for i in range(2)
        ]
        try:
            api = server.api
            wait_until(lambda: len((api.nodes.list()[0]) or []) == 2,
                       timeout=180, msg="2 nodes registered")
            job = service_job("e2e-spread", count=4, command="sleep 300")
            job["TaskGroups"][0]["Spreads"] = [
                {"Attribute": "${node.class}", "Weight": 100}
            ]
            api.jobs.register(job)
            wait_until(lambda: len(running_allocs(api, "e2e-spread")) == 4,
                       timeout=180, msg="4 allocs running")
            nodes_used = {a["NodeID"] for a in running_allocs(api, "e2e-spread")}
            assert len(nodes_used) == 2, "spread across both nodes"
            per_node = [sum(1 for a in running_allocs(api, "e2e-spread")
                            if a["NodeID"] == n) for n in nodes_used]
            assert sorted(per_node) == [2, 2], f"even spread, got {per_node}"
        finally:
            for c in clients:
                c.stop()
            server.stop()


class TestDeployment:
    def test_rolling_update_completes(self, dev):
        """reference e2e/deployment: an update stanza drives a rolling
        deployment to 'successful'."""
        api = dev.api
        job = service_job("e2e-deploy", count=2, command="sleep 300")
        job["TaskGroups"][0]["Update"] = {
            "MaxParallel": 1, "MinHealthyTimeNs": 100_000_000,
            "HealthyDeadlineNs": 30_000_000_000,
        }
        api.jobs.register(job)
        wait_until(lambda: len(running_allocs(api, "e2e-deploy")) == 2,
                   msg="initial rollout")
        # destructive update → new deployment
        job["TaskGroups"][0]["Tasks"][0]["Config"]["args"] = ["-c", "sleep 301"]
        api.jobs.register(job)

        def deployment_successful():
            deps, _ = api.jobs.deployments("e2e-deploy")
            return any(d["Status"] == "successful" and d["JobVersion"] >= 1
                       for d in deps or [])

        wait_until(deployment_successful, timeout=90,
                   msg="rolling deployment successful")


class TestClientState:
    def test_hard_kill_recovery(self, tmp_path_factory):
        """reference e2e/clientstate: kill -9 the agent; a restarted agent
        with the same data dir re-attaches to the live task instead of
        starting a second copy."""
        data_dir = str(tmp_path_factory.mktemp("e2e-state"))
        marker = os.path.join(data_dir, "counter")
        agent = AgentProc("-dev", "-no-gossip", "-data-dir", data_dir,
                          name="state-1")
        try:
            api = agent.api
            # the task appends its pid once at start: a restarted task
            # would append again
            job = service_job(
                "e2e-state", count=1,
                command=f"echo $$ >> {marker}; sleep 600",
            )
            api.jobs.register(job)
            wait_until(lambda: len(running_allocs(api, "e2e-state")) == 1,
                       timeout=150, msg="alloc running")
            wait_until(lambda: os.path.exists(marker), msg="task marker")
            pid_before = open(marker).read().strip()

            agent.kill_hard()
            # the task itself survives the agent's death (detached)
            assert open(marker).read().strip() == pid_before

            agent2 = AgentProc("-dev", "-no-gossip", "-data-dir", data_dir,
                               name="state-2")
            try:
                api2 = agent2.api
                wait_until(lambda: len(running_allocs(api2, "e2e-state")) == 1,
                           timeout=150, msg="alloc recovered after restart")
                time.sleep(1.0)
                assert open(marker).read().strip() == pid_before, \
                    "task re-attached, not restarted"
            finally:
                agent2.stop()
        finally:
            agent.stop()


class TestAffinities:
    """reference e2e/affinities: placements follow affinity weights."""

    def test_affinity_steers_placements(self):
        server = AgentProc("-server", "-no-gossip", name="aff-srv")
        raft, _ = server.api.get("/v1/operator/raft/configuration")
        rpc_addr = raft["Servers"][0]["Address"]
        clients = [
            AgentProc("-client", "-servers", rpc_addr, "-no-gossip",
                      "-node-class", f"aff-r{i}", name=f"aff-c{i}")
            for i in range(2)
        ]
        try:
            api = server.api
            wait_until(lambda: len(api.nodes.list()[0] or []) == 2,
                       timeout=180, msg="both nodes registered")
            # placements 1..count-1 strictly favor the affinity node
            # (anti = -(c+1)/count > -1 while c+1 < count); the FINAL
            # placement's +1 affinity and -1 anti-affinity cancel exactly
            # and the winner is capacity-dependent — assert count-1
            job = service_job("e2e-aff", count=4, command="sleep 300")
            job["Affinities"] = [{
                "LTarget": "${node.class}", "RTarget": "aff-r1",
                "Operand": "=", "Weight": 100,
            }]
            api.jobs.register(job)
            wait_until(lambda: len(running_allocs(api, "e2e-aff")) == 4,
                       timeout=120, msg="4 allocs running")
            nodes, _ = api.nodes.list()
            class_of = {n["ID"]: n.get("NodeClass", "") for n in nodes}
            placements = [class_of[a["NodeID"]]
                          for a in running_allocs(api, "e2e-aff")]
            # strong positive affinity: all but (possibly) the tying
            # final placement land on the affinity node
            assert placements.count("aff-r1") >= 3, placements
        finally:
            for c in clients:
                c.stop()
            server.stop()


class TestNomadExec:
    """reference e2e/nomadexec: command execution inside a live task."""

    def test_exec_and_fs_roundtrip(self, dev):
        api = dev.api
        job = service_job("e2e-exec",
                          command="echo bootmark > $NOMAD_TASK_DIR/mark; sleep 300")
        api.jobs.register(job)
        wait_until(lambda: running_allocs(api, "e2e-exec"), msg="alloc running")
        alloc = running_allocs(api, "e2e-exec")[0]

        # one-shot exec runs INSIDE the task env
        res, _ = api.allocations.exec_task(
            alloc["ID"], "t", ["/bin/sh", "-c", "echo from-exec; exit 7"])
        assert "from-exec" in res["Output"] and res["ExitCode"] == 7

        # fs API sees the file the task wrote
        data = api.alloc_fs.cat(alloc["ID"], "t/local/mark")
        assert data.strip() == b"bootmark"
        entries, _ = api.alloc_fs.ls(alloc["ID"], "t/local")
        assert any(e["Name"] == "mark" for e in entries)

        # task logs captured
        logs = api.alloc_fs.logs(alloc["ID"], "t", "stdout")
        assert isinstance(logs, (bytes, str))
        api.jobs.deregister("e2e-exec")


class TestMetricsE2E:
    """reference e2e/metrics: telemetry visible after scheduling load."""

    def test_scheduler_counters_present(self, dev):
        api = dev.api
        job = service_job("e2e-metrics", count=2, command="sleep 300")
        api.jobs.register(job)
        wait_until(lambda: len(running_allocs(api, "e2e-metrics")) == 2,
                   msg="allocs running")
        # the inmem sink aggregates in 10s intervals: poll until the
        # scheduling counters from this job's eval surface
        def counter_names():
            m = api.agent.metrics()
            names = {c["Name"] for c in m.get("Counters", [])}
            names |= {s["Name"] for s in m.get("Samples", [])}
            return names

        # BOTH names inside ONE polled predicate: asserting "plan" on a
        # separate fresh fetch can land in a new 10s inmem aggregation
        # interval that hasn't seen a plan sample yet (r3 suite-load race)
        def scheduler_and_plan_counters():
            names = counter_names()
            return (
                any("invoke_scheduler" in n for n in names)
                and any("plan" in n for n in names)
            )

        wait_until(scheduler_and_plan_counters, timeout=30,
                   msg="scheduler+plan counters visible in one interval")
        # prometheus format serves too
        import urllib.request

        with urllib.request.urlopen(
            dev.http_addr + "/v1/metrics?format=prometheus", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert "nomad_" in text and "# TYPE" in text
        api.jobs.deregister("e2e-metrics")


class TestParameterizedDispatch:
    """reference e2e (dispatch/periodic slot): parameterized job dispatch
    creates child jobs with payloads."""

    def test_dispatch_with_payload(self, dev):
        api = dev.api
        job = service_job("e2e-batch-param", count=1,
                          command='cat $NOMAD_TASK_DIR/input.txt > $NOMAD_TASK_DIR/out; sleep 300')
        job["Type"] = "batch"
        job["ParameterizedJob"] = {"Payload": "required"}
        job["TaskGroups"][0]["Tasks"][0]["DispatchPayloadFile"] = "input.txt"
        api.jobs.register(job)

        out, _ = api.jobs.dispatch("e2e-batch-param", payload=b"dispatched-data")
        child_id = out["DispatchedJobID"]
        wait_until(lambda: running_allocs(api, child_id), msg="child running")
        alloc = running_allocs(api, child_id)[0]
        wait_until(lambda: api.alloc_fs.cat(alloc["ID"], "t/local/out").strip()
                   == b"dispatched-data", msg="payload delivered")


class TestHostVolumes:
    """reference e2e/hostvolumes: a client-declared host volume is
    scheduled against (HostVolumeChecker) and mounted into the task."""

    def test_volume_scheduling_and_mount(self, tmp_path_factory):
        host_dir = tmp_path_factory.mktemp("hostvol")
        (host_dir / "seed.txt").write_text("from-the-host")
        agent = AgentProc("-dev", "-no-gossip",
                          "-host-volume", f"shared={host_dir}",
                          name="hv-agent")
        try:
            api = agent.api
            # the node advertises the volume
            nodes, _ = api.nodes.list()
            info, _ = api.nodes.info(nodes[0]["ID"])
            assert "shared" in (info.get("HostVolumes") or {})

            job = service_job(
                "e2e-hv", count=1,
                command="cat data/seed.txt > $NOMAD_TASK_DIR/copied; "
                        "echo task-was-here > data/written.txt; sleep 300",
            )
            job["TaskGroups"][0]["Volumes"] = {
                "data": {"Name": "data", "Type": "host", "Source": "shared"},
            }
            job["TaskGroups"][0]["Tasks"][0]["VolumeMounts"] = [
                {"Volume": "data", "Destination": "data"},
            ]
            api.jobs.register(job)
            # generous: suite-context CPU contention (jax compiles on all
            # cores) can starve the agent for a while
            wait_until(lambda: running_allocs(api, "e2e-hv"), timeout=180,
                       msg="alloc running")
            alloc = running_allocs(api, "e2e-hv")[0]
            # the task read host data through the mount...
            wait_until(lambda: api.alloc_fs.cat(
                alloc["ID"], "t/local/copied").strip() == b"from-the-host",
                msg="host file visible through mount")
            # ...and wrote back to the HOST through it
            wait_until(lambda: (host_dir / "written.txt").exists(),
                       msg="task write landed on the host volume")
            assert (host_dir / "written.txt").read_text().strip() == "task-was-here"

            # a job demanding a MISSING volume doesn't place
            bad = service_job("e2e-hv-missing", count=1, command="sleep 30")
            bad["TaskGroups"][0]["Volumes"] = {
                "data": {"Name": "data", "Type": "host", "Source": "no-such"},
            }
            api.jobs.register(bad)
            evals_seen = []
            def blocked():
                evs, _ = api.jobs.evaluations("e2e-hv-missing")
                evals_seen[:] = evs or []
                return any(e.get("Status") == "complete"
                           and e.get("FailedTGAllocs") for e in evals_seen)
            wait_until(blocked, timeout=120, msg="missing volume fails placement")
            assert not running_allocs(api, "e2e-hv-missing")
        finally:
            agent.stop()


class TestClusterOpsE2E:
    """Config-file boot + runtime join + key rotation + force-leave +
    client GC, over REAL forked agent processes (VERDICT r3 #4/#6 e2e
    criteria; reference e2e slots for agent config and cluster ops)."""

    def test_config_boot_join_rotate_forceleave_gc(self, tmp_path):
        import base64
        import secrets as _secrets
        import socket

        def free_port(k):
            # OUTSIDE the kernel's ephemeral range (and pid-scattered), so
            # the agents' own ephemeral http/rpc binds can't steal a
            # reserved port in the boot window (bind TOCTOU)
            for attempt in range(50):
                p = 21000 + (os.getpid() * 13 + k * 7919 + attempt) % 9000
                s = socket.socket()
                try:
                    s.bind(("127.0.0.1", p))
                    return p
                except OSError:
                    continue
                finally:
                    s.close()
            raise RuntimeError("no free fixed port found")

        key_a = base64.b64encode(_secrets.token_bytes(32)).decode()
        key_b = base64.b64encode(_secrets.token_bytes(32)).decode()
        serf1, serf2 = free_port(1), free_port(2)

        def write_cfg(name, serf_port, client=False):
            p = tmp_path / f"{name}.hcl"
            p.write_text(f'''
name       = "{name}"
datacenter = "dc1"
ports {{
  http = 0
  serf = {serf_port}
}}
server {{
  enabled          = true
  bootstrap_expect = 1
  encrypt          = "{key_a}"
}}
client {{
  enabled = {"true" if client else "false"}
}}
''')
            return str(p)

        # both agents boot from CONFIG FILES; no retry_join — they meet
        # via the runtime /v1/agent/join endpoint
        a1 = AgentProc("-config", write_cfg("ops1", serf1, client=True),
                       "-dev", name="ops1")
        a2 = AgentProc("-config", write_cfg("ops2", serf2), name="ops2")
        try:
            api1, api2 = a1.api, a2.api
            # config file took effect (name flows into gossip identity)
            wait_until(lambda: api1.agent.members()["Members"][0]["Name"]
                       .startswith("ops1"), msg="config-file name visible")

            # runtime join
            out = api1.agent.join([f"127.0.0.1:{serf2}"])
            assert out["num_joined"] == 1
            wait_until(lambda: len(api1.agent.members()["Members"]) == 2,
                       msg="runtime join converged on 1")
            wait_until(lambda: len(api2.agent.members()["Members"]) == 2,
                       msg="runtime join converged on 2")

            # cluster-wide key rotation from ONE node's endpoint
            api1.agent.keyring_op("install", key_b)
            wait_until(lambda: key_b in api2.agent.keyring_list()["Keys"],
                       msg="install propagated to 2")
            api1.agent.keyring_op("use", key_b)
            wait_until(lambda: key_b in api2.agent.keyring_list()["PrimaryKeys"],
                       msg="use propagated to 2")
            api1.agent.keyring_op("remove", key_a)
            wait_until(lambda: list(api2.agent.keyring_list()["Keys"])
                       == [key_b], msg="remove propagated to 2")
            # gossip still alive post-rotation
            time.sleep(1.0)
            assert len(api1.agent.members()["Members"]) == 2

            # run a short batch task on the dev agent's client, then GC it
            job = service_job("e2e-gc", count=1, command="true")
            job["Type"] = "batch"
            api1.jobs.register(job)
            wait_until(lambda: any(
                a["ClientStatus"] == "complete"
                for a in allocs_of(api1, "e2e-gc")), timeout=180,
                msg="batch task complete")
            out = api1.agent.client_gc()
            assert out["Collected"] >= 1

            # kill 2's gossip hard, then evict it from 1's view
            a2.kill_hard()
            api1.agent.force_leave("ops2.global")
            wait_until(lambda: any(
                m["Name"] == "ops2.global" and m["Status"] in ("left", "failed")
                for m in api1.agent.members()["Members"]),
                msg="forced member marked left/failed")
        finally:
            a1.stop()
            a2.stop()


class TestServerFailoverE2E:
    """Multi-server black-box failover (VERDICT r4 ask #6; reference
    nomad/testing.go:41 multi-server clusters + testutil/wait.go:85
    WaitForLeader): 3 fork-exec wire-raft server agents + a client
    agent; SIGKILL the leader mid-workload and assert a new leader
    commits the remaining placements with no alloc lost or doubled;
    then `operator raft remove-peer` the corpse and rotate the gossip
    keyring under load."""

    def _free_port(self, k):
        import socket

        for attempt in range(50):
            p = 22000 + (os.getpid() * 17 + k * 6211 + attempt) % 9000
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", p))
                return p
            except OSError:
                continue
            finally:
                s.close()
        raise RuntimeError("no free fixed port found")

    def test_leader_sigkill_failover(self, tmp_path):
        import base64
        import secrets as _secrets

        key_a = base64.b64encode(_secrets.token_bytes(32)).decode()
        key_b = base64.b64encode(_secrets.token_bytes(32)).decode()
        serf = [self._free_port(i) for i in (1, 2, 3)]
        rpc = [self._free_port(i) for i in (4, 5, 6)]

        servers = []
        for i in range(3):
            servers.append(AgentProc(
                "-server", "-wire-raft",
                "-name", f"fo{i}",
                "-bootstrap-expect", "3",
                "-data-dir", str(tmp_path / f"s{i}"),
                "-rpc-port", str(rpc[i]),
                "-serf-port", str(serf[i]),
                "-encrypt", key_a,
                "-retry-join", f"127.0.0.1:{serf[0]}",
                name=f"fo{i}",
            ))
        client = AgentProc(
            "-client", "-no-gossip",
            "-data-dir", str(tmp_path / "c0"),
            "-servers", ",".join(f"127.0.0.1:{p}" for p in rpc),
            name="fo-client",
        )
        try:
            apis = [s.api for s in servers]

            def leader_index():
                for i, api in enumerate(apis):
                    if servers[i].proc.poll() is not None:
                        continue
                    try:
                        if api.status.leader() not in ("", "unknown", None):
                            return i
                    except Exception:  # noqa: BLE001 — mid-election
                        continue
                return None

            wait_until(lambda: leader_index() is not None, timeout=180,
                       msg="initial leader elected")
            li = leader_index()
            follower = apis[(li + 1) % 3]

            # manual-ops mode: autopilot's dead-server cleanup would race
            # the explicit `operator raft remove-peer` exercised below
            apis[li].operator.autopilot_set_configuration(
                {"CleanupDeadServers": False})

            # the client node registers (through any server's HTTP -> RPC
            # forward to the leader)
            wait_until(lambda: any(
                n["Status"] == "ready"
                for n in (follower.nodes.list()[0] or [])),
                timeout=180, msg="client node ready")

            # workload phase 1: committed and placed before the kill
            follower.jobs.register(service_job("fo-pre", count=2,
                                               command="sleep 600"))
            wait_until(lambda: len(running_allocs(follower, "fo-pre")) == 2,
                       timeout=180, msg="pre-failover job running")

            # workload phase 2: registered through the DOOMED leader just
            # before SIGKILL — its evals are committed in raft but may be
            # un-processed; the NEW leader must restore and place them
            leader_api = apis[li]
            for k in range(4):
                leader_api.jobs.register(service_job(
                    f"fo-mid-{k}", count=2, command="sleep 600"))
            servers[li].kill_hard()

            wait_until(lambda: leader_index() is not None and
                       leader_index() != li,
                       timeout=180, msg="new leader elected after SIGKILL")
            survivor = apis[leader_index()]

            try:
                for k in range(4):
                    wait_until(
                        lambda k=k: len(running_allocs(survivor, f"fo-mid-{k}")) == 2,
                        timeout=240, msg=f"fo-mid-{k} placed by the new leader")
            except AssertionError:
                for k in range(4):
                    for a in allocs_of(survivor, f"fo-mid-{k}"):
                        print(f"fo-mid-{k}:", a["Name"], a["DesiredStatus"],
                              a["ClientStatus"])
                        if a["ClientStatus"] == "failed":
                            info, _ = survivor.allocations.info(a["ID"])
                            for task, st in (info.get("TaskStates") or {}).items():
                                for ev in st.get("Events") or []:
                                    print("   event:", task, ev.get("Type"),
                                          ev.get("DisplayMessage"),
                                          ev.get("DriverError", ""))
                evs, _ = survivor.evaluations.list()
                print("evals:", [(e["JobID"], e["Status"]) for e in evs or []])
                nodes, _ = survivor.nodes.list()
                print("nodes:", [(n["Name"], n["Status"]) for n in nodes or []])
                print("client log tail:", "".join(client.lines[-15:]))
                for i, s in enumerate(servers):
                    print(f"server fo{i} log tail:", "".join(s.lines[-10:]))
                raise

            # no alloc lost or doubled: each job holds EXACTLY its count of
            # run-desired allocs, with unique names
            for jid in ["fo-pre"] + [f"fo-mid-{k}" for k in range(4)]:
                allocs = [a for a in allocs_of(survivor, jid)
                          if a["DesiredStatus"] == "run"]
                names = [a["Name"] for a in allocs]
                assert len(names) == 2, (jid, names)
                assert len(set(names)) == 2, f"duplicate alloc names: {names}"

            # pre-failover allocs survived untouched (no reschedule storm)
            assert len(running_allocs(survivor, "fo-pre")) == 2

            # operator raft remove-peer evicts the corpse from the config
            # (autopilot cleanup disabled above, so it's still there)
            cfg, _ = survivor.operator.raft_get_configuration()
            dead = [s for s in cfg["Servers"] if s["ID"].startswith(f"fo{li}")]
            assert dead, cfg
            survivor.operator.raft_remove_peer(dead[0]["ID"])
            def peer_gone():
                c, _ = survivor.operator.raft_get_configuration()
                return all(not s["ID"].startswith(f"fo{li}")
                           for s in c["Servers"])
            wait_until(peer_gone, timeout=60, msg="dead peer removed")

            # keyring rotation UNDER LOAD: rotate while a job registers
            survivor.agent.keyring_op("install", key_b)
            survivor.jobs.register(service_job("fo-rotate", count=2,
                                               command="sleep 600"))
            survivor.agent.keyring_op("use", key_b)
            other = apis[(leader_index() + 1) % 3]
            if servers[(leader_index() + 1) % 3].proc.poll() is not None:
                other = apis[(leader_index() + 2) % 3]
            wait_until(lambda: key_b in other.agent.keyring_list()
                       ["PrimaryKeys"], timeout=60,
                       msg="rotation converged on the other survivor")
            survivor.agent.keyring_op("remove", key_a)
            wait_until(lambda: len(running_allocs(survivor, "fo-rotate")) == 2,
                       timeout=240, msg="job placed during rotation")
            # gossip still healthy across survivors after remove
            wait_until(lambda: sum(
                1 for m in survivor.agent.members()["Members"]
                if m["Status"] == "alive") >= 2, timeout=60,
                msg="survivors alive after rotation")
        finally:
            client.stop()
            for s in servers:
                s.stop()
