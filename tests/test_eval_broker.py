"""Eval broker tests, mirroring reference nomad/eval_broker_test.go:
priority ordering, per-job serialization, nack redelivery with delays,
the delivery limit → _failed queue, wait/wait_until timers, outstanding
token validation, pause/resume of nack timers, and disable-flush.
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import (
    EvalBroker,
    NotOutstandingError,
    TokenMismatchError,
)


def make_eval(priority=50, job_id=None, typ="service", namespace="default"):
    ev = mock.eval()
    ev.priority = priority
    ev.type = typ
    ev.namespace = namespace
    if job_id:
        ev.job_id = job_id
    return ev


def broker(**kw):
    kw.setdefault("nack_timeout", 5.0)
    kw.setdefault("initial_nack_delay", 0.01)
    kw.setdefault("subsequent_nack_delay", 0.02)
    b = EvalBroker(**kw)
    b.set_enabled(True)
    return b


class TestOrdering:
    def test_priority_order(self):
        """Higher priority dequeues first (eval_broker_test.go TestEvalBroker_Enqueue_Dequeue_Priority)."""
        b = broker()
        evs = [make_eval(priority=p) for p in (30, 90, 50)]
        for ev in evs:
            b.enqueue(ev)
        got = [b.dequeue(["service"], timeout=1)[0].priority for _ in range(3)]
        assert got == [90, 50, 30]

    def test_scheduler_type_routing(self):
        b = broker()
        svc = make_eval(typ="service")
        bat = make_eval(typ="batch")
        b.enqueue(svc)
        b.enqueue(bat)
        ev, _ = b.dequeue(["batch"], timeout=1)
        assert ev.id == bat.id
        ev, _ = b.dequeue(["service", "batch"], timeout=1)
        assert ev.id == svc.id

    def test_dequeue_timeout_empty(self):
        b = broker()
        t0 = time.monotonic()
        ev, token = b.dequeue(["service"], timeout=0.2)
        assert ev is None and (token or "") == ""
        assert time.monotonic() - t0 >= 0.15

    def test_disabled_broker_drops(self):
        b = EvalBroker()
        b.enqueue(make_eval())
        assert b.stats()["total_ready"] == 0


class TestJobSerialization:
    def test_one_outstanding_eval_per_job(self):
        """A job's second eval blocks until the first acks
        (TestEvalBroker_Serialize_DuplicateJobID)."""
        b = broker()
        e1 = make_eval(job_id="job-x")
        e2 = make_eval(job_id="job-x")
        other = make_eval(job_id="job-y")
        b.enqueue(e1)
        b.enqueue(e2)
        b.enqueue(other)
        got1, tok1 = b.dequeue(["service"], timeout=1)
        got2, tok2 = b.dequeue(["service"], timeout=1)
        assert {got1.id, got2.id} == {e1.id, other.id}, "e2 must be blocked"
        # acking job-x's first eval releases the second
        tok = tok1 if got1.id == e1.id else tok2
        b.ack(e1.id, tok)
        got3, _ = b.dequeue(["service"], timeout=1)
        assert got3.id == e2.id

    def test_nack_releases_job_for_redelivery(self):
        b = broker()
        e1 = make_eval(job_id="job-n")
        b.enqueue(e1)
        ev, tok = b.dequeue(["service"], timeout=1)
        b.nack(ev.id, tok)
        ev2, tok2 = b.dequeue(["service"], timeout=2)
        assert ev2.id == e1.id and tok2 != tok


class TestNackSemantics:
    def test_delivery_limit_routes_to_failed_queue(self):
        """After delivery_limit nacks the eval lands on the _failed queue
        (TestEvalBroker_DeliveryLimit)."""
        b = broker(delivery_limit=2)
        ev = make_eval()
        b.enqueue(ev)
        for _ in range(2):
            got, tok = b.dequeue(["service"], timeout=2)
            assert got.id == ev.id
            b.nack(got.id, tok)
        got, tok = b.dequeue(["_failed"], timeout=2)
        assert got.id == ev.id
        b.ack(got.id, tok)

    def test_nack_timeout_auto_redelivers(self):
        """An unacked eval returns to ready when its nack timer fires
        (TestEvalBroker_Dequeue_Timeout)."""
        b = broker(nack_timeout=0.15)
        ev = make_eval()
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        # don't ack: the timer must requeue it
        got2, tok2 = b.dequeue(["service"], timeout=3)
        assert got2.id == ev.id and tok2 != tok

    def test_pause_nack_timeout_survives_slow_plan(self):
        """pause_nack_timeout holds the timer while a plan sits in the
        queue (worker.go:277)."""
        b = broker(nack_timeout=0.2)
        ev = make_eval()
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        b.pause_nack_timeout(ev.id, tok)
        time.sleep(0.4)  # would have expired
        b.resume_nack_timeout(ev.id, tok)
        b.ack(ev.id, tok)  # still outstanding: ack succeeds
        assert b.stats()["total_unacked"] == 0

    def test_ack_token_validation(self):
        b = broker()
        ev = make_eval()
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        with pytest.raises(TokenMismatchError):
            b.ack(ev.id, "bogus-token")
        with pytest.raises(NotOutstandingError):
            b.ack("no-such-eval", tok)
        b.ack(ev.id, tok)

    def test_outstanding(self):
        b = broker()
        ev = make_eval()
        b.enqueue(ev)
        assert b.outstanding(ev.id) is None
        _, tok = b.dequeue(["service"], timeout=1)
        assert b.outstanding(ev.id) == tok


class TestWaitTimers:
    def test_wait_ns_delays_readiness(self):
        """An eval with wait_ns only becomes ready after the delay
        (TestEvalBroker_Enqueue_Disable / Wait semantics)."""
        b = broker()
        ev = make_eval()
        ev.wait_ns = int(0.3 * 1e9)
        b.enqueue(ev)
        got, _ = b.dequeue(["service"], timeout=0.1)
        assert got is None, "not ready during the wait"
        got, tok = b.dequeue(["service"], timeout=2)
        assert got.id == ev.id
        b.ack(ev.id, tok)

    def test_wait_until_delays_readiness(self):
        b = broker()
        ev = make_eval()
        ev.wait_until_ns = time.time_ns() + int(0.3 * 1e9)
        b.enqueue(ev)
        got, _ = b.dequeue(["service"], timeout=0.1)
        assert got is None
        got, tok = b.dequeue(["service"], timeout=2)
        assert got.id == ev.id

    def test_disable_flushes_everything(self):
        b = broker()
        b.enqueue(make_eval())
        waiting = make_eval()
        waiting.wait_ns = int(5e9)
        b.enqueue(waiting)
        outst = make_eval()
        b.enqueue(outst)
        b.dequeue(["service"], timeout=1)
        b.set_enabled(False)
        s = b.stats()
        assert s["total_ready"] == 0 and s["total_unacked"] == 0
        assert s.get("total_waiting", 0) == 0


class TestRequeueOnUpdate:
    def test_updating_outstanding_eval_requeues_after_ack(self):
        """Enqueueing a NEWER version of an outstanding eval (token set)
        requeues it when the current delivery acks (enqueue_all with
        token — reference EnqueueAll/requeue semantics)."""
        b = broker()
        ev = make_eval(job_id="job-r")
        b.enqueue(ev)
        got, tok = b.dequeue(["service"], timeout=1)
        newer = got.copy() if hasattr(got, "copy") else got
        import copy as _copy

        newer = _copy.deepcopy(got)
        newer.modify_index = 99
        b.enqueue_all({newer.id: (newer, tok)})
        b.ack(got.id, tok)
        got2, tok2 = b.dequeue(["service"], timeout=2)
        assert got2.id == ev.id and got2.modify_index == 99
        b.ack(got2.id, tok2)
