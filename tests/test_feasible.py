"""Feasibility checker tests (mirrors reference scheduler/feasible_test.go)."""
from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintChecker,
    DriverChecker,
    HostVolumeChecker,
    StaticIterator,
    check_constraint,
    resolve_target,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Constraint
from nomad_tpu.structs.structs import DriverInfo, HostVolume, VolumeRequest


def make_ctx(deterministic=True):
    state = StateStore()
    ev = mock.eval()
    plan = ev.make_plan(mock.job())
    return EvalContext(state, plan, deterministic=deterministic)


def test_static_iterator_serves_all():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    out = []
    while True:
        n = it.next()
        if n is None:
            break
        out.append(n)
    assert out == nodes
    assert ctx.metrics.nodes_evaluated == 3


def test_static_iterator_reset_wraps():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    it.next()  # consume one
    it.reset()
    out = [it.next() for _ in range(3)]
    assert None not in out
    assert it.next() is None


def test_driver_checker():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(4)]
    nodes[1].attributes["driver.foo"] = "1"
    nodes[2].attributes["driver.foo"] = "0"
    nodes[3].drivers = {"foo": DriverInfo(detected=True, healthy=False)}
    checker = DriverChecker(ctx, {"foo"})
    assert not checker.feasible(nodes[0])
    assert checker.feasible(nodes[1])
    assert not checker.feasible(nodes[2])
    assert not checker.feasible(nodes[3])


def test_constraint_checker_ops():
    ctx = make_ctx()
    node = mock.node()
    cases = [
        (Constraint("${node.datacenter}", "dc1", "="), True),
        (Constraint("${node.datacenter}", "dc2", "="), False),
        (Constraint("${attr.kernel.name}", "linux", "="), True),
        (Constraint("${attr.kernel.name}", "", "is_set"), True),
        (Constraint("${attr.nonexistent}", "", "is_set"), False),
        (Constraint("${attr.nonexistent}", "", "is_not_set"), True),
        (Constraint("${meta.pci-dss}", "true", "="), True),
        (Constraint("${attr.kernel.name}", "li.*x", "regexp"), True),
        (Constraint("${attr.kernel.name}", "win.*", "regexp"), False),
        (Constraint("${node.class}", "linux-medium-pci", "="), True),
        (Constraint("${attr.nomad.version}", ">= 0.4, < 0.8", "version"), True),
        (Constraint("${attr.nomad.version}", "> 1.0", "version"), False),
    ]
    for constraint, expected in cases:
        checker = ConstraintChecker(ctx, [constraint])
        assert checker.feasible(node) == expected, str(constraint)


def test_check_constraint_set_contains():
    ctx = make_ctx()
    assert check_constraint(ctx, "set_contains", "a,b,c", "a,c", True, True)
    assert not check_constraint(ctx, "set_contains", "a,b", "a,c", True, True)
    assert check_constraint(ctx, "set_contains_any", "a,b", "c,b", True, True)
    assert not check_constraint(ctx, "set_contains_any", "a,b", "c,d", True, True)


def test_check_constraint_lexical():
    ctx = make_ctx()
    assert check_constraint(ctx, "<", "abc", "abd", True, True)
    assert not check_constraint(ctx, ">", "abc", "abd", True, True)
    assert check_constraint(ctx, ">=", "abc", "abc", True, True)


def test_check_constraint_semver():
    ctx = make_ctx()
    assert check_constraint(ctx, "semver", "1.7.0-beta", ">= 1.6.0", True, True)
    # go-version ">= 1.6.0" does not admit prereleases below the bound either;
    # key semver-vs-version difference is strict 3-segment parsing:
    assert not check_constraint(ctx, "semver", "1.7", ">= 1.6.0", True, True)
    assert check_constraint(ctx, "version", "1.7", ">= 1.6.0", True, True)


def test_resolve_target_literal_and_missing():
    node = mock.node()
    val, ok = resolve_target("some-literal", node)
    assert ok and val == "some-literal"
    val, ok = resolve_target("${attr.missing}", node)
    assert not ok
    val, ok = resolve_target("${node.unique.id}", node)
    assert ok and val == node.id


def test_host_volume_checker():
    ctx = make_ctx()
    checker = HostVolumeChecker(ctx)
    node = mock.node()
    node.host_volumes = {"shared": HostVolume(name="shared", read_only=True)}
    # no volumes requested -> feasible
    checker.set_volumes({})
    assert checker.feasible(node)
    # requested matching volume read-only -> ok
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="shared", read_only=True)})
    assert checker.feasible(node)
    # read-write request on read-only volume -> fail
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="shared", read_only=False)})
    assert not checker.feasible(node)
    # missing volume -> fail
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="zzz")})
    assert not checker.feasible(node)
