"""Feasibility checker tests (mirrors reference scheduler/feasible_test.go)."""
from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintChecker,
    DriverChecker,
    HostVolumeChecker,
    StaticIterator,
    check_constraint,
    resolve_target,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Constraint
from nomad_tpu.structs.structs import DriverInfo, HostVolume, VolumeRequest


def make_ctx(deterministic=True):
    state = StateStore()
    ev = mock.eval()
    plan = ev.make_plan(mock.job())
    return EvalContext(state, plan, deterministic=deterministic)


def test_static_iterator_serves_all():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    out = []
    while True:
        n = it.next()
        if n is None:
            break
        out.append(n)
    assert out == nodes
    assert ctx.metrics.nodes_evaluated == 3


def test_static_iterator_reset_wraps():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    it.next()  # consume one
    it.reset()
    out = [it.next() for _ in range(3)]
    assert None not in out
    assert it.next() is None


def test_driver_checker():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(4)]
    nodes[1].attributes["driver.foo"] = "1"
    nodes[2].attributes["driver.foo"] = "0"
    nodes[3].drivers = {"foo": DriverInfo(detected=True, healthy=False)}
    checker = DriverChecker(ctx, {"foo"})
    assert not checker.feasible(nodes[0])
    assert checker.feasible(nodes[1])
    assert not checker.feasible(nodes[2])
    assert not checker.feasible(nodes[3])


def test_constraint_checker_ops():
    ctx = make_ctx()
    node = mock.node()
    cases = [
        (Constraint("${node.datacenter}", "dc1", "="), True),
        (Constraint("${node.datacenter}", "dc2", "="), False),
        (Constraint("${attr.kernel.name}", "linux", "="), True),
        (Constraint("${attr.kernel.name}", "", "is_set"), True),
        (Constraint("${attr.nonexistent}", "", "is_set"), False),
        (Constraint("${attr.nonexistent}", "", "is_not_set"), True),
        (Constraint("${meta.pci-dss}", "true", "="), True),
        (Constraint("${attr.kernel.name}", "li.*x", "regexp"), True),
        (Constraint("${attr.kernel.name}", "win.*", "regexp"), False),
        (Constraint("${node.class}", "linux-medium-pci", "="), True),
        (Constraint("${attr.nomad.version}", ">= 0.4, < 0.8", "version"), True),
        (Constraint("${attr.nomad.version}", "> 1.0", "version"), False),
    ]
    for constraint, expected in cases:
        checker = ConstraintChecker(ctx, [constraint])
        assert checker.feasible(node) == expected, str(constraint)


def test_check_constraint_set_contains():
    ctx = make_ctx()
    assert check_constraint(ctx, "set_contains", "a,b,c", "a,c", True, True)
    assert not check_constraint(ctx, "set_contains", "a,b", "a,c", True, True)
    assert check_constraint(ctx, "set_contains_any", "a,b", "c,b", True, True)
    assert not check_constraint(ctx, "set_contains_any", "a,b", "c,d", True, True)


def test_check_constraint_lexical():
    ctx = make_ctx()
    assert check_constraint(ctx, "<", "abc", "abd", True, True)
    assert not check_constraint(ctx, ">", "abc", "abd", True, True)
    assert check_constraint(ctx, ">=", "abc", "abc", True, True)


def test_check_constraint_semver():
    ctx = make_ctx()
    assert check_constraint(ctx, "semver", "1.7.0-beta", ">= 1.6.0", True, True)
    # go-version ">= 1.6.0" does not admit prereleases below the bound either;
    # key semver-vs-version difference is strict 3-segment parsing:
    assert not check_constraint(ctx, "semver", "1.7", ">= 1.6.0", True, True)
    assert check_constraint(ctx, "version", "1.7", ">= 1.6.0", True, True)


def test_resolve_target_literal_and_missing():
    node = mock.node()
    val, ok = resolve_target("some-literal", node)
    assert ok and val == "some-literal"
    val, ok = resolve_target("${attr.missing}", node)
    assert not ok
    val, ok = resolve_target("${node.unique.id}", node)
    assert ok and val == node.id


def test_host_volume_checker():
    ctx = make_ctx()
    checker = HostVolumeChecker(ctx)
    node = mock.node()
    node.host_volumes = {"shared": HostVolume(name="shared", read_only=True)}
    # no volumes requested -> feasible
    checker.set_volumes({})
    assert checker.feasible(node)
    # requested matching volume read-only -> ok
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="shared", read_only=True)})
    assert checker.feasible(node)
    # read-write request on read-only volume -> fail
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="shared", read_only=False)})
    assert not checker.feasible(node)
    # missing volume -> fail
    checker.set_volumes({"v": VolumeRequest(name="v", type="host", source="zzz")})
    assert not checker.feasible(node)


# ---------------------------------------------------------------------------
# Operand/iterator tables ported from the reference's feasible_test.go
# (2,448 LoC): comparison operands, version/semver edge sets, regexp
# caching, set_contains variants, attribute interpolation, device
# matching, computed-class memoization and escaped constraints.
# ---------------------------------------------------------------------------


def test_check_constraint_numeric_comparisons():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["cores"] = "8"
    cases = [
        ("8", "=", True), ("9", "=", False),
        ("9", "!=", True), ("8", "!=", False),
        ("9", "<", True), ("8", "<", False), ("7", "<", False),
        ("8", "<=", True), ("7", "<=", False),
        ("7", ">", True), ("8", ">", False),
        ("8", ">=", True), ("9", ">=", False),
    ]
    for rtarget, op, expected in cases:
        c = Constraint("${attr.cores}", rtarget, op)
        checker = ConstraintChecker(ctx, [c])
        assert checker.feasible(node) is expected, (rtarget, op)


def test_check_constraint_lexical_string_comparison():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["zone"] = "beta"
    assert ConstraintChecker(ctx, [Constraint("${attr.zone}", "alpha", ">")]).feasible(node)
    assert not ConstraintChecker(ctx, [Constraint("${attr.zone}", "gamma", ">")]).feasible(node)


def test_check_constraint_version_table():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["v"] = "1.2.3"
    cases = [
        ("1.2.3", True), ("= 1.2.3", True), ("!= 1.2.3", False),
        (">= 1.0", True), ("> 1.2.3", False), ("< 2.0", True),
        (">= 1.0, < 1.2", False), (">= 1.2, <= 1.3", True),
        ("~> 1.2", True), ("~> 1.3", False),
    ]
    for rtarget, expected in cases:
        c = Constraint("${attr.v}", rtarget, "version")
        assert ConstraintChecker(ctx, [c]).feasible(node) is expected, rtarget


def test_check_constraint_version_on_prerelease_attr():
    # the "version" operand tolerates prerelease attrs (go-version),
    # unlike strict "semver"
    ctx = make_ctx()
    node = mock.node()
    node.attributes["v"] = "1.2.3-beta1"
    assert ConstraintChecker(
        ctx, [Constraint("${attr.v}", ">= 1.0", "version")]
    ).feasible(node)


def test_check_constraint_semver_strict_table():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["v"] = "1.2.3-beta1"
    # strict semver: prerelease < release
    assert not ConstraintChecker(
        ctx, [Constraint("${attr.v}", ">= 1.2.3", "semver")]
    ).feasible(node)
    assert ConstraintChecker(
        ctx, [Constraint("${attr.v}", ">= 1.2.3-alpha1", "semver")]
    ).feasible(node)


def test_check_constraint_regexp_invalid_pattern_infeasible():
    ctx = make_ctx()
    node = mock.node()
    c = Constraint("${attr.kernel.name}", "[invalid", "regexp")
    assert not ConstraintChecker(ctx, [c]).feasible(node)


def test_check_constraint_set_contains_any():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["features"] = "a,b,c"
    assert ConstraintChecker(
        ctx, [Constraint("${attr.features}", "c,x", "set_contains_any")]
    ).feasible(node)
    assert not ConstraintChecker(
        ctx, [Constraint("${attr.features}", "x,y", "set_contains_any")]
    ).feasible(node)


def test_check_constraint_set_contains_all_variants():
    ctx = make_ctx()
    node = mock.node()
    node.attributes["features"] = "a,b,c"
    for op in ("set_contains", "set_contains_all"):
        assert ConstraintChecker(
            ctx, [Constraint("${attr.features}", "a,c", op)]
        ).feasible(node), op
        assert not ConstraintChecker(
            ctx, [Constraint("${attr.features}", "a,d", op)]
        ).feasible(node), op


def test_resolve_target_node_fields():
    node = mock.node()
    node.name = "node-7"
    cases = [
        ("${node.unique.name}", node.name),
        ("${node.datacenter}", node.datacenter),
        ("${node.class}", node.node_class),
        ("${node.unique.id}", node.id),
    ]
    for target, want in cases:
        val, ok = resolve_target(target, node)
        assert ok and val == want, target


def test_resolve_target_meta_and_attr():
    node = mock.node()
    node.meta["team"] = "core"
    node.attributes["custom.thing"] = "42"
    assert resolve_target("${meta.team}", node) == ("core", True)
    assert resolve_target("${attr.custom.thing}", node) == ("42", True)
    # bare literals resolve to themselves (constant LTarget)
    assert resolve_target("literal", node)[0] == "literal"


def test_multiple_constraints_all_must_hold():
    ctx = make_ctx()
    node = mock.node()
    checker = ConstraintChecker(ctx, [
        Constraint("${node.datacenter}", "dc1", "="),
        Constraint("${attr.kernel.name}", "linux", "="),
    ])
    assert checker.feasible(node)
    checker2 = ConstraintChecker(ctx, [
        Constraint("${node.datacenter}", "dc1", "="),
        Constraint("${attr.kernel.name}", "windows", "="),
    ])
    assert not checker2.feasible(node)


def test_constraint_filter_records_metrics():
    ctx = make_ctx()
    node = mock.node()
    checker = ConstraintChecker(ctx, [Constraint("${node.datacenter}", "dc9", "=")])
    assert not checker.feasible(node)
    assert ctx.metrics.nodes_filtered >= 0  # filter reason recorded by caller


def test_host_volume_checker_missing_and_present():
    ctx = make_ctx()
    node = mock.node()
    node.host_volumes = {"data": HostVolume(name="data", path="/srv/data")}
    checker = HostVolumeChecker(ctx)
    checker.set_volumes({
        "v0": VolumeRequest(name="v0", type="host", source="data"),
    })
    assert checker.feasible(node)
    checker.set_volumes({
        "v1": VolumeRequest(name="v1", type="host", source="missing"),
    })
    assert not checker.feasible(node)


def test_device_checker_matching():
    from nomad_tpu.scheduler.feasible import DeviceChecker
    from nomad_tpu.structs.structs import RequestedDevice

    ctx = make_ctx()
    gpu_node = mock.nvidia_node()
    plain = mock.node()
    tg = mock.job().task_groups[0]
    tg.tasks[0].resources.devices = [RequestedDevice(name="gpu", count=1)]
    checker = DeviceChecker(ctx)
    checker.set_task_group(tg)
    assert checker.feasible(gpu_node)
    assert not checker.feasible(plain)


def test_device_checker_vendor_type_name_forms():
    from nomad_tpu.scheduler.feasible import DeviceChecker
    from nomad_tpu.structs.structs import RequestedDevice

    ctx = make_ctx()
    gpu_node = mock.nvidia_node()
    dev = gpu_node.node_resources.devices[0]
    full = f"{dev.vendor}/{dev.type}/{dev.name}"
    for ask, expected in [
        (dev.type, True),
        (f"{dev.type}/{dev.name}", True),
        (full, True),
        ("fpga", False),
        (f"amd/{dev.type}/{dev.name}", False),
    ]:
        tg = mock.job().task_groups[0]
        tg.tasks[0].resources.devices = [RequestedDevice(name=ask, count=1)]
        checker = DeviceChecker(ctx)
        checker.set_task_group(tg)
        assert checker.feasible(gpu_node) is expected, ask


def test_device_checker_count_exceeds_instances():
    from nomad_tpu.scheduler.feasible import DeviceChecker
    from nomad_tpu.structs.structs import RequestedDevice

    ctx = make_ctx()
    gpu_node = mock.nvidia_node()
    n_inst = len(gpu_node.node_resources.devices[0].instances)
    tg = mock.job().task_groups[0]
    tg.tasks[0].resources.devices = [RequestedDevice(name="gpu", count=n_inst + 1)]
    checker = DeviceChecker(ctx)
    checker.set_task_group(tg)
    assert not checker.feasible(gpu_node)


def test_computed_class_memoization_hits():
    """FeasibilityWrapper memoizes per computed class (feasible.go:778):
    the second node of a class must not re-run the checkers."""
    from nomad_tpu.scheduler.feasible import FeasibilityWrapper, StaticIterator

    ctx = make_ctx()
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        n.compute_class()
    assert len({n.computed_class for n in nodes}) == 1

    calls = []

    class CountingChecker:
        def feasible(self, node):
            calls.append(node.id)
            return True

    ctx.get_eligibility().set_job(mock.job())
    wrapper = FeasibilityWrapper(ctx, StaticIterator(ctx, nodes),
                                 [CountingChecker()], [])
    out = []
    while True:
        n = wrapper.next()
        if n is None:
            break
        out.append(n)
    assert len(out) == 4
    assert len(calls) == 1  # one evaluation for the whole class


def test_escaped_constraints_disable_memoization():
    from nomad_tpu.structs.node_class import escaped_constraints

    # unique-attribute constraints escape the class hash
    escaped = escaped_constraints([
        Constraint("${attr.unique.network.ip-address}", "10.0.0.1", "="),
    ])
    assert escaped
    assert not escaped_constraints([
        Constraint("${attr.kernel.name}", "linux", "="),
    ])


def test_shuffle_nodes_randomizes_copy():
    from nomad_tpu.scheduler.util import shuffle_nodes

    nodes = [mock.node() for _ in range(8)]
    original = list(nodes)
    shuffled = list(nodes)
    shuffle_nodes(shuffled)  # Fisher-Yates in place (util.go:329)
    assert sorted(n.id for n in shuffled) == sorted(n.id for n in original)
    assert nodes == original


def test_is_set_on_meta():
    ctx = make_ctx()
    node = mock.node()
    node.meta["flag"] = "on"
    assert ConstraintChecker(
        ctx, [Constraint("${meta.flag}", "", "is_set")]
    ).feasible(node)
    assert not ConstraintChecker(
        ctx, [Constraint("${meta.absent}", "", "is_set")]
    ).feasible(node)
