"""Federation: cross-region ACL replication + per-call server failover.

Mirrors reference leader.go:997 replicateACLPolicies / :1138
replicateACLTokens (non-authoritative leaders mirror policies and GLOBAL
tokens from the authoritative region over cross-region RPC) and
client/servers/manager.go (every client RPC fails over across the full
server list).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent.agent import Agent, AgentConfig
from nomad_tpu.structs.acl import ACLPolicy, ACLToken


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


class TestACLReplication:
    def test_policies_and_global_tokens_mirror(self):
        """Policies and GLOBAL tokens written in the authoritative region
        appear in the other region; local tokens stay local; deletes
        propagate."""
        east = Agent(AgentConfig(
            name="east-1", region="east", authoritative_region="east",
            replication_token="repl-secret", num_schedulers=0,
        ))
        west = Agent(AgentConfig(
            name="west-1", region="west", authoritative_region="east",
            replication_token="repl-secret", acl_replication_interval=0.3,
            num_schedulers=0,
        ))
        try:
            east.start()
            west.config.retry_join = [
                "{}:{}".format(*east.membership.gossip_addr)
            ]
            west.start()
            wait_until(
                lambda: set(west.regions()) == {"east", "west"},
                msg="region map",
            )

            # authoritative writes
            east.server.upsert_acl_policies([ACLPolicy(
                name="readonly",
                rules='namespace "default" { policy = "read" }',
            )])
            global_tok = ACLToken(name="global-tok", type="client",
                                  policies=["readonly"], global_=True)
            local_tok = ACLToken(name="local-tok", type="client",
                                 policies=["readonly"], global_=False)
            east.server.upsert_acl_tokens([global_tok, local_tok])

            west_state = west.server.fsm.state
            wait_until(
                lambda: "readonly" in west_state.acl_policies_table,
                msg="policy replicated to west",
            )
            assert west_state.acl_policies_table["readonly"].rules
            wait_until(
                lambda: west_state.acl_token_by_accessor(global_tok.accessor_id)
                is not None,
                msg="global token replicated",
            )
            # the mirrored token keeps its secret (it must authenticate in
            # every region), the local token never crosses
            mirrored = west_state.acl_token_by_accessor(global_tok.accessor_id)
            assert mirrored.secret_id == global_tok.secret_id
            time.sleep(1.0)  # a few replication rounds
            assert west_state.acl_token_by_accessor(local_tok.accessor_id) is None

            # policy update propagates (content diff)
            east.server.upsert_acl_policies([ACLPolicy(
                name="readonly",
                rules='namespace "default" { policy = "write" }',
            )])
            wait_until(
                lambda: "write" in west_state.acl_policies_table["readonly"].rules,
                msg="policy update replicated",
            )

            # deletes propagate
            east.server.delete_acl_policies(["readonly"])
            east.server.delete_acl_tokens([global_tok.accessor_id])
            wait_until(
                lambda: "readonly" not in west_state.acl_policies_table,
                msg="policy delete replicated",
            )
            wait_until(
                lambda: west_state.acl_token_by_accessor(global_tok.accessor_id)
                is None,
                msg="token delete replicated",
            )
        finally:
            west.shutdown()
            east.shutdown()

    def test_replication_endpoint_requires_token(self):
        """Once tokens exist, the replication list RPC refuses callers
        without the replication/management token (token secrets cross this
        endpoint)."""
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_schedulers=0, replication_token="repl-secret",
        ))
        try:
            # before bootstrap: open (nothing secret yet)
            policies, tokens = server.list_acl_for_replication("")
            assert policies == [] and tokens == []
            mgmt = ACLToken(name="mgmt", type="management", global_=True)
            server.upsert_acl_tokens([mgmt])
            with pytest.raises(PermissionError):
                server.list_acl_for_replication("")
            with pytest.raises(PermissionError):
                server.list_acl_for_replication("wrong")
            # the replication token and a management secret both pass
            _, toks = server.list_acl_for_replication("repl-secret")
            assert len(toks) == 1
            _, toks = server.list_acl_for_replication(mgmt.secret_id)
            assert len(toks) == 1
        finally:
            server.stop()


class TestServerFailover:
    def test_client_rpc_fails_over_per_call(self):
        """A client agent keeps working when the server it is using dies:
        the next RPC rotates to a surviving server (client/servers)."""
        from nomad_tpu.server.raft import InProcRaft
        from nomad_tpu.server.server import Server, ServerConfig

        raft = InProcRaft()
        s1 = Server(ServerConfig(num_schedulers=0, heartbeat_min_ttl=3600,
                                 heartbeat_max_ttl=7200), raft=raft, name="s1")
        s2 = Server(ServerConfig(num_schedulers=0, heartbeat_min_ttl=3600,
                                 heartbeat_max_ttl=7200), raft=raft, name="s2")
        a1 = Agent(AgentConfig(name="fo-1", gossip_enabled=False), server=s1)
        a2 = Agent(AgentConfig(name="fo-2", gossip_enabled=False), server=s2)
        client_agent = None
        try:
            a1.start()
            a2.start()
            client_agent = Agent(AgentConfig(
                name="fo-client", server_enabled=False, client_enabled=True,
                gossip_enabled=False,
                servers=[
                    "{}:{}".format(*a1.rpc.addr),
                    "{}:{}".format(*a2.rpc.addr),
                ],
            ))
            client_agent.start()
            # generous: suite-context CPU contention (jax compiles on all
            # cores) can starve the register/retry threads for a while
            wait_until(lambda: len(s1.fsm.state.nodes()) == 1,
                       timeout=90, msg="node registered")
            node_id = client_agent.client.node.id

            # pin the client to the FOLLOWER (a2), then kill it: the next
            # RPC must rotate to the surviving leader (a1) — in-proc raft
            # writes only land on the leader, so survival proves rotation
            manager = client_agent.client.proxy.manager
            manager.set_servers([a2.rpc.addr, a1.rpc.addr])
            assert manager.current() == a2.rpc.addr
            a2.rpc.stop()

            # a write through the proxy fails over and succeeds end-to-end
            client_agent.client.proxy.heartbeat(node_id)
            assert manager.current() == a1.rpc.addr
            assert s1.fsm.state.node_by_id(node_id) is not None
        finally:
            if client_agent is not None:
                client_agent.shutdown()
            a2.shutdown()
            a1.shutdown()
