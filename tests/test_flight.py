"""nomad-flightrec: flight recorder ring/spill/overhead mechanics,
critical-path attribution on synthetic span sets, server/agent wiring
(armed with leadership, /v1/flight route), and the strict disarmed
no-op contract."""
import json
import threading
import time

from nomad_tpu.trace import attribution, lifecycle
from nomad_tpu.trace.flight import FlightRecorder


def spin_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out: {msg}")


# ---------------------------------------------------------------------------
# recorder mechanics
# ---------------------------------------------------------------------------

FRAME_KEYS = {"seq", "t", "wall", "probes", "gauges", "counters", "tick_ms"}


class TestFlightRecorder:
    def test_ring_bounds_and_seq(self):
        rec = FlightRecorder(interval_s=0.25, retain=8)
        for _ in range(20):
            rec.tick()
        frames = rec.frames()
        assert len(frames) == 8  # retain honored, oldest evicted
        assert [f["seq"] for f in frames] == list(range(12, 20))
        assert rec.frames(recent=3) == frames[-3:]
        assert rec.frames(recent=0) == []

    def test_frame_schema_stable(self):
        """The frame key set is the JSONL spill schema — downstream
        consumers (watchdog dump, bench artifacts) parse it."""
        rec = FlightRecorder(interval_s=0.25, retain=4)
        rec.add_probe("const", lambda: {"x": 1})
        frame = rec.tick()
        assert set(frame) == FRAME_KEYS
        assert frame["probes"]["const"] == {"x": 1}
        assert isinstance(frame["gauges"], dict)
        assert isinstance(frame["counters"], dict)

    def test_probe_error_is_contained(self):
        rec = FlightRecorder(interval_s=0.25, retain=4)
        rec.add_probe("bad", lambda: 1 / 0)
        rec.add_probe("good", lambda: {"ok": True})
        frame = rec.tick()
        assert "error" in frame["probes"]["bad"]
        assert frame["probes"]["good"] == {"ok": True}

    def test_disarmed_is_strict_noop(self):
        """interval_s <= 0 disables: arm() starts nothing, no thread, no
        frames, zero overhead."""
        rec = FlightRecorder(interval_s=0.0)
        before = threading.active_count()
        rec.arm()
        assert not rec.armed
        assert threading.active_count() == before
        assert rec.frames() == []
        assert rec.overhead()["ticks"] == 0
        rec.disarm()  # idempotent

    def test_arm_disarm_thread_lifecycle(self):
        rec = FlightRecorder(interval_s=0.01, retain=64)
        rec.arm()
        assert rec.armed
        spin_until(lambda: len(rec.frames()) >= 3, msg="frames sampled")
        rec.arm()  # second arm is a no-op, not a second thread
        rec.disarm()
        assert not rec.armed
        n = len(rec.frames())
        time.sleep(0.05)
        assert len(rec.frames()) == n  # sampling actually stopped
        ov = rec.overhead()
        assert ov["ticks"] >= 3 and ov["tick_ms_max"] >= ov["tick_ms_avg"]
        assert 0.0 <= ov["duty_cycle"] < 1.0

    def test_spill_jsonl(self, tmp_path):
        path = str(tmp_path / "flight.jsonl")
        rec = FlightRecorder(interval_s=0.01, retain=16, spill_path=path)
        rec.add_probe("p", lambda: {"v": 7})
        rec.arm()
        spin_until(lambda: len(rec.frames()) >= 4, msg="spilled frames")
        rec.disarm()
        with open(path) as fh:
            lines = [json.loads(line) for line in fh if line.strip()]
        assert len(lines) >= 4
        assert all(set(f) == FRAME_KEYS for f in lines)
        assert lines[0]["probes"]["p"] == {"v": 7}
        # seq strictly increasing: the spill is an append-only log
        seqs = [f["seq"] for f in lines]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_write_spill_tail_flush(self, tmp_path):
        rec = FlightRecorder(interval_s=0.25, retain=32)
        for _ in range(10):
            rec.tick()
        path = str(tmp_path / "tail.jsonl")
        assert rec.write_spill(path, recent=4) == 4
        with open(path) as fh:
            assert len(fh.readlines()) == 4

    def test_snapshot_payload_shape(self):
        rec = FlightRecorder(interval_s=0.25, retain=16)
        rec.tick()
        snap = rec.snapshot(recent=8)
        assert snap["armed"] is False
        assert snap["interval_s"] == 0.25
        assert snap["retain"] == 16
        assert len(snap["frames"]) == 1
        assert snap["overhead"]["ticks"] == 1


# ---------------------------------------------------------------------------
# critical-path attribution on synthetic span sets
# ---------------------------------------------------------------------------


def _rec(eval_id="e1", **stamps):
    base = {
        "eval_id": eval_id, "type": "service", "attempt": 1,
        "path": "device", "outcome": stamps.pop("outcome", "ack"),
        "enqueue_t": None, "dequeue_t": None, "invoke_start_t": None,
        "invoke_end_t": None, "submit_t": None, "apply_t": None,
        "end_t": None,
    }
    base.update(stamps)
    return base


# one fully-instrumented wave over [0, 10]: queue 1s, encode 1s,
# dispatch 3s (the plant: top-ranked), residual invoke 1s, then
# wait_min_index/commit machinery and a short second eval
SYNTH_RECORDS = [
    _rec("e1", enqueue_t=0.0, dequeue_t=1.0, invoke_start_t=1.0,
         invoke_end_t=6.0, submit_t=6.0, apply_t=8.0, end_t=8.5),
    _rec("e2", enqueue_t=8.5, dequeue_t=9.0, invoke_start_t=9.0,
         invoke_end_t=10.0, submit_t=10.0, apply_t=10.0, end_t=10.0),
    _rec("e3", enqueue_t=9.0, dequeue_t=9.2, invoke_start_t=9.2,
         invoke_end_t=9.4, end_t=9.4, outcome="nack"),
]
SYNTH_SPANS = [
    ("encode", "w1", 1.0, 2.0),
    ("dispatch", "w1", 2.0, 5.0),
    ("wait_min_index", "e1", 6.5, 7.5),
]


class TestAttribution:
    def test_synthetic_coverage_and_ranking(self):
        cp = attribution.critical_path(SYNTH_RECORDS, SYNTH_SPANS, now=10.0)
        assert cp["makespan_s"] == 10.0
        assert cp["waves"] == 1  # e1/e2/e3 windows abut into one wave
        assert cp["occ_retries"] == 1
        comps = cp["components"]
        # the planted decomposition, exclusive (no double counting)
        assert comps["dispatch"] == 3.0
        assert comps["encode"] == 1.0
        assert comps["invoke"] == 2.0  # [5,6] residual + [9,10]; e3 overlap claimed once
        assert comps["wait_min_index"] == 1.0
        assert comps["queue_wait"] == 1.5  # [0,1] + [8.5,9]
        assert comps["commit_wait"] == 1.0  # [6,8] minus wait_min_index
        assert comps["finalize"] == 0.5  # [8,8.5]
        assert "broker_idle" not in comps  # evals in flight wall-to-wall
        # exclusivity: components sum to attributed time, never above
        assert abs(sum(comps.values()) - 10.0) < 1e-9
        assert cp["coverage"] == 1.0
        assert cp["unattributed_s"] == 0.0

    def test_report_ranks_and_names_top(self):
        rep = attribution.bottleneck_report(
            SYNTH_RECORDS, SYNTH_SPANS, now=10.0)
        assert rep["coverage_ok"] is True
        assert rep["coverage"] >= attribution.COVERAGE_FLOOR
        assert rep["top"] == "dispatch: 30% of makespan"
        assert rep["entries"][0] == {
            "component": "dispatch", "seconds": 3.0, "share": 0.3}
        shares = [e["seconds"] for e in rep["entries"]]
        assert shares == sorted(shares, reverse=True)
        assert rep["occ_retries"] == 1

    def test_report_is_deterministic(self):
        a = attribution.bottleneck_report(SYNTH_RECORDS, SYNTH_SPANS, now=10.0)
        b = attribution.bottleneck_report(
            list(reversed(SYNTH_RECORDS)), list(reversed(SYNTH_SPANS)),
            now=10.0)
        assert a == b  # input order never changes the ledger

    def test_tie_break_is_by_name(self):
        recs = [_rec("t", enqueue_t=0.0, dequeue_t=1.0, invoke_start_t=1.0,
                     invoke_end_t=2.0, end_t=2.0)]
        rep = attribution.bottleneck_report(recs, [], now=2.0)
        assert [e["component"] for e in rep["entries"]] == \
            ["invoke", "queue_wait"]  # equal 1s claims: alphabetical

    def test_coverage_failure_refuses_to_rank(self):
        """A span set with a 9s instrumentation hole must say so instead
        of naming a bogus bottleneck."""
        recs = [_rec("gap", enqueue_t=0.0, dequeue_t=0.1,
                     invoke_start_t=0.2, invoke_end_t=0.5,
                     submit_t=9.5, apply_t=9.8, end_t=10.0)]
        rep = attribution.bottleneck_report(recs, [], now=10.0)
        assert rep["coverage"] < attribution.COVERAGE_FLOOR
        assert rep["coverage_ok"] is False
        assert "coverage" in rep["top"] and "incomplete" in rep["top"]

    def test_broker_idle_claims_gaps_between_waves(self):
        recs = [
            _rec("a", enqueue_t=0.0, dequeue_t=0.5, invoke_start_t=0.5,
                 invoke_end_t=1.0, end_t=1.0),
            _rec("b", enqueue_t=9.0, dequeue_t=9.5, invoke_start_t=9.5,
                 invoke_end_t=10.0, end_t=10.0),
        ]
        cp = attribution.critical_path(recs, [], now=10.0)
        assert cp["components"]["broker_idle"] == 8.0  # [1, 9]
        assert cp["coverage"] == 1.0

    def test_instrumented_idle_outranks_broker_idle_synthesis(self):
        """Worker-recorded idle spans (lifecycle.IDLE_STAGE) claim ahead
        of the synthesized broker_idle complement: an inter-wave gap a
        worker measurably sat out decomposes into `idle` for the
        instrumented stretch and broker_idle only for the remainder."""
        recs = [
            _rec("a", enqueue_t=0.0, dequeue_t=0.5, invoke_start_t=0.5,
                 invoke_end_t=1.0, end_t=1.0),
            _rec("b", enqueue_t=9.0, dequeue_t=9.5, invoke_start_t=9.5,
                 invoke_end_t=10.0, end_t=10.0),
        ]
        spans = [(lifecycle.IDLE_STAGE, "worker-0", 1.0, 5.0)]
        cp = attribution.critical_path(recs, spans, now=10.0)
        assert cp["components"]["idle"] == 4.0          # measured [1, 5]
        assert cp["components"]["broker_idle"] == 4.0   # residual [5, 9]
        assert cp["coverage"] == 1.0

    def test_idle_spans_do_not_launder_instrumentation_holes(self):
        """A partial idle span must not rescue a span set with a real
        instrumentation hole: only the measured stretch is claimed, the
        hole still drags coverage under the floor and the report still
        refuses to rank."""
        recs = [_rec("gap", enqueue_t=0.0, dequeue_t=0.1,
                     invoke_start_t=0.2, invoke_end_t=0.5,
                     submit_t=9.5, apply_t=9.8, end_t=10.0)]
        spans = [(lifecycle.IDLE_STAGE, "worker-0", 0.5, 1.5)]
        rep = attribution.bottleneck_report(recs, spans, now=10.0)
        assert rep["coverage"] < attribution.COVERAGE_FLOOR
        assert rep["coverage_ok"] is False
        assert "coverage" in rep["top"] and "incomplete" in rep["top"]

    def test_empty_inputs(self):
        rep = attribution.bottleneck_report([], [], now=0.0)
        assert rep["top"] == "no spans recorded"
        assert rep["entries"] == [] and rep["makespan_s"] == 0.0

    def test_inflight_spans_extend_to_now(self):
        recs = [_rec("open", enqueue_t=0.0, dequeue_t=1.0,
                     invoke_start_t=1.0)]  # still invoking
        cp = attribution.critical_path(recs, [], now=4.0)
        assert cp["components"]["invoke"] == 3.0
        assert cp["coverage"] == 1.0

    def test_format_report_one_liner(self):
        rep = attribution.bottleneck_report(
            SYNTH_RECORDS, SYNTH_SPANS, now=10.0)
        line = attribution.format_report(rep, top_n=2)
        assert line.startswith("dispatch: 30%; ")
        assert line.endswith("(coverage 100%)")

    def test_live_lifecycle_integration(self):
        """Default-argument path reads the live lifecycle tables."""
        from nomad_tpu.structs.structs import EVAL_STATUS_PENDING, Evaluation

        lifecycle.reset()
        ev = Evaluation(job_id="live", type="service",
                        status=EVAL_STATUS_PENDING, priority=50)
        lifecycle.on_enqueue(ev)
        lifecycle.on_dequeue(ev.id, 1)
        lifecycle.on_invoke_start(ev.id)
        time.sleep(0.02)
        lifecycle.on_invoke_end(ev.id)
        lifecycle.on_ack(ev.id)
        t0 = lifecycle.pipeline_now()
        lifecycle.pipeline_record("dispatch", "w-live", t0 - 0.005, t0)
        rep = attribution.bottleneck_report()
        assert rep["makespan_s"] > 0
        assert rep["coverage_ok"], rep
        assert any(e["component"] == "invoke" for e in rep["entries"])
        lifecycle.reset()


# ---------------------------------------------------------------------------
# server + agent wiring
# ---------------------------------------------------------------------------


def test_server_arms_flight_with_leadership(tmp_path):
    from nomad_tpu.server.server import Server, ServerConfig

    server = Server(ServerConfig(
        num_schedulers=0, device_batch=0,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        flight_interval_s=0.02, flight_retain=128,
        flight_spill_dir=str(tmp_path),
    ), name="flight-srv")
    server.start()
    try:
        spin_until(lambda: server.flight.armed, msg="flight armed on leader")
        spin_until(lambda: len(server.flight.frames()) >= 2,
                   msg="flight frames")
        frame = server.flight.frames(recent=1)[0]
        # the standard probe set is wired
        assert {"broker", "plan_queue", "trace", "state", "encode_cache"} \
            <= set(frame["probes"])
        assert "dequeue_waiters" in frame["probes"]["broker"]
        assert "min_index_waiters" in frame["probes"]["state"]
        # publisher satellite: the flight tick keeps gauges fresh with no
        # agent and no 10s leader sweep having run yet
        spin_until(
            lambda: "nomad.broker.total_ready" in (
                server.flight.frames(recent=1) or [{}])[-1].get("gauges", {}),
            msg="gauges published from flight tick")
    finally:
        server.stop()
    assert not server.flight.armed  # disarmed with leadership revocation
    spill = tmp_path / "flight-srv.flight.jsonl"
    assert spill.exists() and spill.read_text().strip()


def test_v1_flight_endpoint_end_to_end():
    import urllib.error
    import urllib.request

    from nomad_tpu.agent import Agent, AgentConfig

    lifecycle.reset()
    agent = Agent(AgentConfig(dev_mode=True, num_schedulers=2,
                              name="flight1", flight_interval_s=0.02))
    agent.start()
    try:
        from nomad_tpu import mock

        agent.server.register_job(mock.job())
        spin_until(lambda: lifecycle.summary()["completed"] >= 1,
                   msg="an eval completing")
        spin_until(lambda: len(agent.server.flight.frames()) >= 2,
                   msg="flight frames")
        with urllib.request.urlopen(
                agent.http_addr + "/v1/flight?recent=4", timeout=30) as resp:
            out = json.loads(resp.read().decode())
        assert out["armed"] is True
        assert 0 < len(out["frames"]) <= 4
        assert set(out["frames"][-1]) == FRAME_KEYS
        assert "broker" in out["frames"][-1]["probes"]
        rep = out["bottleneck_report"]
        assert "top" in rep and "coverage" in rep and "entries" in rep
        # bad recent= is a 400, not a 500
        try:
            urllib.request.urlopen(
                agent.http_addr + "/v1/flight?recent=bogus", timeout=30)
            raise AssertionError("recent=bogus must 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        agent.shutdown()
        lifecycle.reset()
