"""End-to-end scheduler tests via the Harness (mirrors generic_sched_test.go
and system_sched_test.go core cases)."""
from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    NODE_STATUS_DOWN,
    Evaluation,
)


def setup_harness(num_nodes=10):
    h = Harness()
    nodes = []
    for _ in range(num_nodes):
        n = mock.node()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def register_eval(job):
    return Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        namespace=job.namespace,
    )


def test_service_register_places_all():
    h, _ = setup_harness(10)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.state.upsert_evals(h.next_index(), [ev])

    h.process("service", ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    # all placements have resources assigned
    for a in placed:
        assert a.allocated_resources.tasks["web"].cpu_shares == 500
        assert a.job_id == job.id
    # eval marked complete
    h.assert_eval_status(EVAL_STATUS_COMPLETE)
    # allocs live in state now
    out = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(out) == 10
    # queued allocations drained
    assert h.evals[0].queued_allocations.get("web") == 0


def test_service_register_annotates_metrics():
    h, _ = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert placed[0].metrics.nodes_evaluated > 0
    assert placed[0].metrics.score_meta  # top-K populated


def test_service_no_nodes_creates_blocked_eval():
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    # blocked eval created for failed placements
    assert len(h.create_evals) == 1
    assert h.create_evals[0].status == "blocked"
    assert h.evals[0].status == EVAL_STATUS_COMPLETE
    assert h.evals[0].blocked_eval == h.create_evals[0].id
    assert h.evals[0].failed_tg_allocs["web"] is not None


def test_service_count_scale_down_stops():
    h, nodes = setup_harness(10)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    assert len(h.state.allocs_by_job(job.namespace, job.id, True)) == 10

    # scale down to 3
    job2 = job.copy()
    job2.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 7
    live = [
        a
        for a in h.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN
    ]
    assert len(live) == 3
    # the highest-indexed names are the ones stopped
    live_names = sorted(a.name for a in live)
    assert live_names == [f"{job.id}.web[{i}]" for i in range(3)]


def test_service_job_deregister_stops_all():
    h, _ = setup_harness(5)
    job = mock.job()
    job.task_groups[0].count = 5
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    job2 = job.copy()
    job2.stop = True
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 5


def test_service_node_down_replaces_allocs():
    h, nodes = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 2
    # disable rescheduling to exercise the lost-replacement path directly
    job.task_groups[0].reschedule_policy = None
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 2

    # take down the node of the first alloc; mark allocs running first
    for a in allocs:
        ca = a.copy_skip_job()
        ca.client_status = ALLOC_CLIENT_RUNNING
        h.state.update_allocs_from_client(h.next_index(), [ca])
    down_node = allocs[0].node_id
    h.state.update_node_status(h.next_index(), down_node, NODE_STATUS_DOWN)

    ev2 = Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_NODE_UPDATE,
        job_id=job.id,
        node_id=down_node,
        namespace=job.namespace,
    )
    h.process("service", ev2)

    plan = h.plans[-1]
    # lost alloc marked stopped+lost, replacement placed elsewhere
    stopped = [a for allocs_ in plan.node_update.values() for a in allocs_]
    assert any(a.client_status == ALLOC_CLIENT_LOST for a in stopped)
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(placed) == 1
    assert placed[0].node_id != down_node


def test_service_destructive_update():
    h, _ = setup_harness(4)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    # change the task config -> destructive update
    job2 = job.copy()
    job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(stopped) == 4
    assert len(placed) == 4


def test_service_inplace_update():
    h, _ = setup_harness(4)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    # bump job without changing tasks -> in-place update
    job2 = job.copy()
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(stopped) == 0
    assert len(placed) == 4  # in-place updates appended as allocations
    # same alloc ids preserved (in-place)
    prev_ids = {a.id for a in h.state.allocs_by_job(job.namespace, job.id, True)}
    assert {a.id for a in placed} <= prev_ids


def test_batch_ignores_complete_allocs():
    h, _ = setup_harness(2)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("batch", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 1

    # complete successfully on client
    from nomad_tpu.structs.structs import TaskState

    ca = allocs[0].copy_skip_job()
    ca.client_status = "complete"
    ca.task_states = {"worker": TaskState(state="dead", failed=False)}
    h.state.update_allocs_from_client(h.next_index(), [ca])

    ev2 = register_eval(job)
    h.process("batch", ev2)
    # no new placements: batch job already ran successfully
    assert len(h.plans) == 1 or h.plans[-1].is_noop()


def test_system_places_one_per_node():
    h, nodes = setup_harness(5)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        namespace=job.namespace,
    )
    h.process("system", ev)
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 5
    assert {a.node_id for a in placed} == {n.id for n in nodes}
    h.assert_eval_status(EVAL_STATUS_COMPLETE)


def test_system_skips_infeasible_nodes():
    h, nodes = setup_harness(3)
    bad = mock.node()
    bad.attributes["kernel.name"] = "windows"
    bad.compute_class()
    h.state.upsert_node(h.next_index(), bad)
    job = mock.system_job()  # constrained to linux
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id, namespace=job.namespace,
    )
    h.process("system", ev)
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert len(placed) == 3
    assert bad.id not in {a.node_id for a in placed}


def test_failed_alloc_reschedule_now():
    import time

    h, nodes = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 1
    rp = job.task_groups[0].reschedule_policy
    rp.delay_ns = 0
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 1
    failed_node = allocs[0].node_id

    from nomad_tpu.structs.structs import TaskState

    ca = allocs[0].copy_skip_job()
    ca.client_status = ALLOC_CLIENT_FAILED
    ca.task_states = {
        "web": TaskState(state="dead", failed=True, finished_at_ns=time.time_ns())
    }
    ca.modify_time_ns = time.time_ns()
    h.state.update_allocs_from_client(h.next_index(), [ca])

    ev2 = Evaluation(
        priority=job.priority, type=job.type,
        triggered_by="alloc-failure", job_id=job.id, namespace=job.namespace,
    )
    h.process("service", ev2)
    plan = h.plans[-1]
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(placed) == 1
    # rescheduled alloc chains to previous and avoids the failed node
    assert placed[0].previous_allocation == allocs[0].id
    assert placed[0].reschedule_tracker is not None
    assert placed[0].node_id != failed_node
