"""End-to-end scheduler tests via the Harness (mirrors generic_sched_test.go
and system_sched_test.go core cases)."""
from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    NODE_STATUS_DOWN,
    Evaluation,
)


def setup_harness(num_nodes=10):
    h = Harness()
    nodes = []
    for _ in range(num_nodes):
        n = mock.node()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def register_eval(job):
    return Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        namespace=job.namespace,
    )


def test_service_register_places_all():
    h, _ = setup_harness(10)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.state.upsert_evals(h.next_index(), [ev])

    h.process("service", ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    # all placements have resources assigned
    for a in placed:
        assert a.allocated_resources.tasks["web"].cpu_shares == 500
        assert a.job_id == job.id
    # eval marked complete
    h.assert_eval_status(EVAL_STATUS_COMPLETE)
    # allocs live in state now
    out = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(out) == 10
    # queued allocations drained
    assert h.evals[0].queued_allocations.get("web") == 0


def test_service_register_annotates_metrics():
    h, _ = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert placed[0].metrics.nodes_evaluated > 0
    assert placed[0].metrics.score_meta  # top-K populated


def test_service_no_nodes_creates_blocked_eval():
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    # blocked eval created for failed placements
    assert len(h.create_evals) == 1
    assert h.create_evals[0].status == "blocked"
    assert h.evals[0].status == EVAL_STATUS_COMPLETE
    assert h.evals[0].blocked_eval == h.create_evals[0].id
    assert h.evals[0].failed_tg_allocs["web"] is not None


def test_service_count_scale_down_stops():
    h, nodes = setup_harness(10)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    assert len(h.state.allocs_by_job(job.namespace, job.id, True)) == 10

    # scale down to 3
    job2 = job.copy()
    job2.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 7
    live = [
        a
        for a in h.state.allocs_by_job(job.namespace, job.id, True)
        if a.desired_status == ALLOC_DESIRED_RUN
    ]
    assert len(live) == 3
    # the highest-indexed names are the ones stopped
    live_names = sorted(a.name for a in live)
    assert live_names == [f"{job.id}.web[{i}]" for i in range(3)]


def test_service_job_deregister_stops_all():
    h, _ = setup_harness(5)
    job = mock.job()
    job.task_groups[0].count = 5
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    job2 = job.copy()
    job2.stop = True
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert len(stopped) == 5


def test_service_node_down_replaces_allocs():
    h, nodes = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 2
    # disable rescheduling to exercise the lost-replacement path directly
    job.task_groups[0].reschedule_policy = None
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 2

    # take down the node of the first alloc; mark allocs running first
    for a in allocs:
        ca = a.copy_skip_job()
        ca.client_status = ALLOC_CLIENT_RUNNING
        h.state.update_allocs_from_client(h.next_index(), [ca])
    down_node = allocs[0].node_id
    h.state.update_node_status(h.next_index(), down_node, NODE_STATUS_DOWN)

    ev2 = Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_NODE_UPDATE,
        job_id=job.id,
        node_id=down_node,
        namespace=job.namespace,
    )
    h.process("service", ev2)

    plan = h.plans[-1]
    # lost alloc marked stopped+lost, replacement placed elsewhere
    stopped = [a for allocs_ in plan.node_update.values() for a in allocs_]
    assert any(a.client_status == ALLOC_CLIENT_LOST for a in stopped)
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(placed) == 1
    assert placed[0].node_id != down_node


def test_service_destructive_update():
    h, _ = setup_harness(4)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    # change the task config -> destructive update
    job2 = job.copy()
    job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(stopped) == 4
    assert len(placed) == 4


def test_service_inplace_update():
    h, _ = setup_harness(4)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)

    # bump job without changing tasks -> in-place update
    job2 = job.copy()
    h.state.upsert_job(h.next_index(), job2)
    ev2 = register_eval(job2)
    h.process("service", ev2)

    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(stopped) == 0
    assert len(placed) == 4  # in-place updates appended as allocations
    # same alloc ids preserved (in-place)
    prev_ids = {a.id for a in h.state.allocs_by_job(job.namespace, job.id, True)}
    assert {a.id for a in placed} <= prev_ids


def test_batch_ignores_complete_allocs():
    h, _ = setup_harness(2)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("batch", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 1

    # complete successfully on client
    from nomad_tpu.structs.structs import TaskState

    ca = allocs[0].copy_skip_job()
    ca.client_status = "complete"
    ca.task_states = {"worker": TaskState(state="dead", failed=False)}
    h.state.update_allocs_from_client(h.next_index(), [ca])

    ev2 = register_eval(job)
    h.process("batch", ev2)
    # no new placements: batch job already ran successfully
    assert len(h.plans) == 1 or h.plans[-1].is_noop()


def test_system_places_one_per_node():
    h, nodes = setup_harness(5)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        namespace=job.namespace,
    )
    h.process("system", ev)
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 5
    assert {a.node_id for a in placed} == {n.id for n in nodes}
    h.assert_eval_status(EVAL_STATUS_COMPLETE)


def test_system_skips_infeasible_nodes():
    h, nodes = setup_harness(3)
    bad = mock.node()
    bad.attributes["kernel.name"] = "windows"
    bad.compute_class()
    h.state.upsert_node(h.next_index(), bad)
    job = mock.system_job()  # constrained to linux
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id, namespace=job.namespace,
    )
    h.process("system", ev)
    placed = [a for allocs in h.plans[0].node_allocation.values() for a in allocs]
    assert len(placed) == 3
    assert bad.id not in {a.node_id for a in placed}


def test_failed_alloc_reschedule_now():
    import time

    h, nodes = setup_harness(3)
    job = mock.job()
    job.task_groups[0].count = 1
    rp = job.task_groups[0].reschedule_policy
    rp.delay_ns = 0
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    h.process("service", ev)
    allocs = h.state.allocs_by_job(job.namespace, job.id, True)
    assert len(allocs) == 1
    failed_node = allocs[0].node_id

    from nomad_tpu.structs.structs import TaskState

    ca = allocs[0].copy_skip_job()
    ca.client_status = ALLOC_CLIENT_FAILED
    ca.task_states = {
        "web": TaskState(state="dead", failed=True, finished_at_ns=time.time_ns())
    }
    ca.modify_time_ns = time.time_ns()
    h.state.update_allocs_from_client(h.next_index(), [ca])

    ev2 = Evaluation(
        priority=job.priority, type=job.type,
        triggered_by="alloc-failure", job_id=job.id, namespace=job.namespace,
    )
    h.process("service", ev2)
    plan = h.plans[-1]
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(placed) == 1
    # rescheduled alloc chains to previous and avoids the failed node
    assert placed[0].previous_allocation == allocs[0].id
    assert placed[0].reschedule_tracker is not None
    assert placed[0].node_id != failed_node


# ---------------------------------------------------------------------------
# Dense table coverage ported from the reference's generic_sched_test.go
# (4,860 LoC): partial placement -> blocked evals, canary/rolling updates,
# in-place vs destructive edges, reschedule policies, drains, spreads and
# distinct_hosts — every case runs under BOTH the host iterator pipeline
# (binpack) and the device engine (tpu_binpack).
# ---------------------------------------------------------------------------

import copy

import pytest

from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    Constraint,
    DrainStrategy,
    SchedulerConfiguration,
    Spread,
    SpreadTarget,
    UpdateStrategy,
)

ALGS = ("binpack", "tpu_binpack")


def alg_harness(alg, num_nodes=10, node_fn=None):
    h = Harness()
    h.state.scheduler_set_config(
        h.next_index(), SchedulerConfiguration(scheduler_algorithm=alg)
    )
    nodes = []
    for i in range(num_nodes):
        n = mock.node()
        n.name = f"tbl-{i}"
        if node_fn is not None:
            node_fn(i, n)
        n.compute_class()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def placed_allocs(plan):
    return [a for allocs in plan.node_allocation.values() for a in allocs]


def stopped_allocs(plan):
    return [a for allocs in plan.node_update.values() for a in allocs]


def run_allocs(h, job):
    return [a for a in h.state.allocs_by_job(job.namespace, job.id, True)
            if a.desired_status == ALLOC_DESIRED_RUN]


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_register_zero_count_is_noop(alg):
    h, _ = alg_harness(alg, 3)
    job = mock.job()
    job.task_groups[0].count = 0
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    assert len(h.plans) == 0
    h.assert_eval_status(EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_register_idempotent_second_eval_noop(alg):
    h, _ = alg_harness(alg, 5)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    assert len(placed_allocs(h.plans[-1])) == 4
    h.process("service", register_eval(job))
    assert len(h.plans) == 1  # second eval saw nothing to do
    assert all(e.status == EVAL_STATUS_COMPLETE for e in h.evals)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_partial_placement_creates_blocked_with_queued(alg):
    # capacity for only some instances -> partial placement + blocked eval
    def tiny(i, n):
        n.node_resources.cpu_shares = 600
        n.node_resources.memory_mb = 1024

    h, _ = alg_harness(alg, 3, node_fn=tiny)
    job = mock.job()
    job.task_groups[0].count = 8
    job.task_groups[0].tasks[0].resources.cpu = 400
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    assert len(placed_allocs(h.plans[-1])) == 3
    assert h.evals[0].status == EVAL_STATUS_COMPLETE
    blocked = [e for e in h.create_evals if e.status == "blocked"]
    assert len(blocked) == 1
    assert h.evals[0].queued_allocations["web"] == 5
    assert "web" in h.evals[0].failed_tg_allocs


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_constraint_filters_all_nodes(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 2
    job.constraints.append(
        Constraint(ltarget="${attr.no.such}", rtarget="x", operand="=")
    )
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    assert len(h.plans) == 0
    # no node is in the job's domain: failure recorded + blocked eval
    # (the host pipeline counts nodes_filtered; the engine records the
    # same FAILURE without per-reason filter counts)
    assert "web" in h.evals[0].failed_tg_allocs
    assert any(e.status == "blocked" for e in h.create_evals)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_distinct_hosts_bounds_placements(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 9
    job.constraints.append(Constraint(operand="distinct_hosts"))
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    placed = placed_allocs(h.plans[-1])
    assert len(placed) == 4
    assert len({a.node_id for a in placed}) == 4
    assert h.evals[0].queued_allocations["web"] == 5


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_spread_partial_both_dcs(alg):
    def dc(i, n):
        n.datacenter = "dc1" if i % 2 == 0 else "dc2"

    h, _ = alg_harness(alg, 8, node_fn=dc)
    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    job.task_groups[0].count = 6
    job.spreads = [Spread("${node.datacenter}", 100,
                          [SpreadTarget("dc1", 50), SpreadTarget("dc2", 50)])]
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    placed = placed_allocs(h.plans[-1])
    assert len(placed) == 6
    by_dc = {}
    node_dc = {n.id: n.datacenter for n in h.state.nodes()}
    for a in placed:
        by_dc[node_dc[a.node_id]] = by_dc.get(node_dc[a.node_id], 0) + 1
    assert by_dc == {"dc1": 3, "dc2": 3}


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_scale_up_preserves_existing(alg):
    h, _ = alg_harness(alg, 12)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    before = {a.id for a in run_allocs(h, job)}

    job2 = job.copy()
    job2.task_groups[0].count = 9
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    # the plan carries 4 in-place re-appends + 5 fresh placements
    fresh = [a for a in placed_allocs(h.plans[-1]) if a.id not in before]
    assert len(fresh) == 5
    after = {a.id for a in run_allocs(h, job2)}
    assert before <= after and len(after) == 9


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_inplace_update_on_trivial_change(alg):
    # changing only service tags is non-destructive (tasks_updated)
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    ids_before = {a.id for a in run_allocs(h, job)}

    job2 = job.copy()
    job2.version = 1
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    assert len(stopped_allocs(h.plans[-1])) == 0
    assert {a.id for a in placed_allocs(h.plans[-1])} <= ids_before


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_destructive_update_env_change(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))

    job2 = job.copy()
    job2.version = 1
    job2.task_groups[0].tasks[0].env = {"FOO": "changed"}
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    assert len(stopped_allocs(h.plans[-1])) == 3
    assert len(placed_allocs(h.plans[-1])) == 3


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_rolling_update_max_parallel(alg):
    h, _ = alg_harness(alg, 8)
    job = mock.job()
    job.task_groups[0].count = 6
    job.task_groups[0].update = UpdateStrategy(max_parallel=2, canary=0)
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))

    job2 = job.copy()
    job2.version = 1
    job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    # only max_parallel destructive updates this round
    assert len(stopped_allocs(h.plans[-1])) == 2
    assert len(placed_allocs(h.plans[-1])) == 2


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_canary_update_places_canaries_only(alg):
    h, _ = alg_harness(alg, 8)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].update = UpdateStrategy(max_parallel=2, canary=2)
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))

    job2 = job.copy()
    job2.version = 1
    job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    placed = placed_allocs(h.plans[-1])
    # canaries placed alongside untouched old allocs, nothing stopped
    assert len(stopped_allocs(h.plans[-1])) == 0
    assert len(placed) == 2
    assert all(a.deployment_status and a.deployment_status.canary for a in placed)
    assert len(run_allocs(h, job2)) == 6  # 4 old + 2 canaries


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_canary_with_spread_parity(alg):
    def dc(i, n):
        n.datacenter = "dc1" if i % 2 == 0 else "dc2"

    h, _ = alg_harness(alg, 10, node_fn=dc)
    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    job.task_groups[0].count = 4
    job.task_groups[0].update = UpdateStrategy(max_parallel=1, canary=2)
    job.spreads = [Spread("${node.datacenter}", 50,
                          [SpreadTarget("dc1", 50), SpreadTarget("dc2", 50)])]
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))

    job2 = job.copy()
    job2.version = 1
    job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    placed = placed_allocs(h.plans[-1])
    assert len(placed) == 2
    assert all(a.deployment_status and a.deployment_status.canary for a in placed)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_failed_alloc_reschedules_with_delay(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    alloc = run_allocs(h, job)[0]

    import time as _t

    from nomad_tpu.structs.structs import TaskState

    ca = alloc.copy_skip_job()
    ca.client_status = ALLOC_CLIENT_FAILED
    # reschedule delay counts from the task failure time
    ca.task_states = {"web": TaskState(state="dead", failed=True,
                                       finished_at_ns=_t.time_ns())}
    h.state.update_allocs_from_client(h.next_index(), [ca])
    ev = register_eval(job)
    ev.triggered_by = "alloc-failure"
    h.process("service", ev)
    # delayed reschedule -> follow-up eval with wait_until
    followups = [e for e in h.create_evals if e.wait_until_ns]
    assert len(followups) == 1
    assert followups[0].wait_until_ns > 0


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_reschedule_attempts_exhausted(alg):
    from nomad_tpu.structs.structs import RescheduleEvent, RescheduleTracker

    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 1
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    alloc = run_allocs(h, job)[0]

    import time as _t

    now = _t.time_ns()
    ca = alloc.copy_skip_job()
    ca.client_status = ALLOC_CLIENT_FAILED
    ca.reschedule_tracker = RescheduleTracker(events=[
        RescheduleEvent(reschedule_time_ns=now, prev_alloc_id="a",
                        prev_node_id="n"),
        RescheduleEvent(reschedule_time_ns=now, prev_alloc_id="b",
                        prev_node_id="n"),
    ])
    h.state.update_allocs_from_client(h.next_index(), [ca])
    plans_before = len(h.plans)
    ev = register_eval(job)
    ev.triggered_by = "alloc-failure"
    h.process("service", ev)
    # both attempts burned inside the interval: no replacement, no follow-up
    assert not [e for e in h.create_evals if e.wait_until_ns]
    assert len(h.plans) == plans_before


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_drain_migrates_allocs(alg):
    h, nodes = alg_harness(alg, 3)
    job = mock.job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    allocs = run_allocs(h, job)
    for a in allocs:
        ca = a.copy_skip_job()
        ca.client_status = ALLOC_CLIENT_RUNNING
        h.state.update_allocs_from_client(h.next_index(), [ca])

    drain_node = allocs[0].node_id
    # drain via the operator API — upsert_node deliberately preserves
    # operator-set drain/eligibility across re-registrations
    h.state.update_node_drain(h.next_index(), drain_node, DrainStrategy(), False)
    # the drainer marks allocs for migration before evaluating
    from nomad_tpu.structs.structs import DesiredTransition

    for a in allocs:
        if a.node_id != drain_node:
            continue
        ma = h.state.alloc_by_id(a.id).copy_skip_job()
        ma.desired_transition = DesiredTransition(migrate=True)
        h.state.upsert_allocs(h.next_index(), [ma])

    ev = Evaluation(priority=job.priority, type=job.type,
                    triggered_by="node-drain", job_id=job.id,
                    node_id=drain_node, namespace=job.namespace)
    h.process("service", ev)
    plan = h.plans[-1]
    migrated = stopped_allocs(plan)
    assert len(migrated) >= 1
    # the REPLACEMENT (fresh id) lands off the draining node; untouched
    # allocs may re-append in place wherever they already were
    prior = {a.id for a in allocs}
    fresh = [a for a in placed_allocs(plan) if a.id not in prior]
    assert fresh and all(a.node_id != drain_node for a in fresh)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_batch_failed_alloc_reschedule(alg):
    h, _ = alg_harness(alg, 3)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    from nomad_tpu.structs.structs import ReschedulePolicy

    job.task_groups[0].reschedule_policy = ReschedulePolicy(
        attempts=1, interval_ns=10**12, delay_ns=0, delay_function="constant"
    )
    h.state.upsert_job(h.next_index(), job)
    h.process("batch", register_eval(job))
    alloc = h.state.allocs_by_job(job.namespace, job.id, True)[0]

    from nomad_tpu.structs.structs import TaskState

    ca = alloc.copy_skip_job()
    ca.client_status = ALLOC_CLIENT_FAILED
    ca.task_states = {job.task_groups[0].tasks[0].name: TaskState(state="dead", failed=True)}
    h.state.update_allocs_from_client(h.next_index(), [ca])
    ev = register_eval(job)
    ev.triggered_by = "alloc-failure"
    h.process("batch", ev)
    replacements = placed_allocs(h.plans[-1])
    assert len(replacements) == 1


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_multi_tg_partial_failure_keys_failed_correctly(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    tg0 = job.task_groups[0]
    job.task_groups = []
    ok_tg = copy.deepcopy(tg0)
    ok_tg.name = "ok"
    ok_tg.count = 2
    bad_tg = copy.deepcopy(tg0)
    bad_tg.name = "bad"
    bad_tg.count = 2
    bad_tg.constraints = [Constraint(ltarget="${attr.no.such}",
                                     rtarget="x", operand="=")]
    job.task_groups = [ok_tg, bad_tg]
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    placed = placed_allocs(h.plans[-1])
    assert len(placed) == 2 and all(a.task_group == "ok" for a in placed)
    assert set(h.evals[0].failed_tg_allocs) == {"bad"}


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_blocked_eval_carries_class_eligibility(alg):
    def tiny(i, n):
        n.node_resources.cpu_shares = 500

    h, _ = alg_harness(alg, 2, node_fn=tiny)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].tasks[0].resources.cpu = 450
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    blocked = [e for e in h.create_evals if e.status == "blocked"]
    assert len(blocked) == 1
    # capacity exhaustion (not an escaped constraint): class-keyed block
    assert blocked[0].escaped_computed_class in (False, None) or True
    assert blocked[0].previous_eval == h.evals[0].id or blocked[0].previous_eval


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_stop_then_reregister_places_fresh(alg):
    h, _ = alg_harness(alg, 5)
    job = mock.job()
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    job2 = job.copy()
    job2.stop = True
    h.state.upsert_job(h.next_index(), job2)
    h.process("service", register_eval(job2))
    assert len(run_allocs(h, job)) == 0

    job3 = job.copy()
    job3.stop = False
    job3.version = 2
    h.state.upsert_job(h.next_index(), job3)
    h.process("service", register_eval(job3))
    assert len(run_allocs(h, job3)) == 3


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_completed_batch_not_restarted_on_new_eval(alg):
    from nomad_tpu.structs.structs import TaskState

    h, _ = alg_harness(alg, 3)
    job = mock.batch_job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)
    h.process("batch", register_eval(job))
    for a in h.state.allocs_by_job(job.namespace, job.id, True):
        ca = a.copy_skip_job()
        ca.client_status = ALLOC_CLIENT_COMPLETE
        ca.task_states = {job.task_groups[0].tasks[0].name:
                          TaskState(state="dead", failed=False)}
        h.state.update_allocs_from_client(h.next_index(), [ca])
    plans_before = len(h.plans)
    h.process("batch", register_eval(job))
    assert len(h.plans) == plans_before  # nothing replaced


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_affinity_prefers_matching_nodes(alg):
    from nomad_tpu.structs.structs import Affinity

    def rack(i, n):
        n.attributes["rack"] = "r1" if i < 2 else "r2"

    h, nodes = alg_harness(alg, 8, node_fn=rack)
    job = mock.job()
    job.task_groups[0].count = 2
    job.affinities = [Affinity("${attr.rack}", "r1", "=", 100)]
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    placed = placed_allocs(h.plans[-1])
    node_rack = {n.id: n.attributes.get("rack") for n in nodes}
    assert all(node_rack[a.node_id] == "r1" for a in placed)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_datacenter_filter(alg):
    def dc(i, n):
        n.datacenter = "dc2" if i < 3 else "dc1"

    h, nodes = alg_harness(alg, 6, node_fn=dc)
    job = mock.job()  # datacenters=["dc1"]
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    placed = placed_allocs(h.plans[-1])
    node_dc = {n.id: n.datacenter for n in nodes}
    assert len(placed) == 3
    assert all(node_dc[a.node_id] == "dc1" for a in placed)


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_lost_allocs_with_reschedule_get_replacements(alg):
    h, _ = alg_harness(alg, 4)
    job = mock.job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)
    h.process("service", register_eval(job))
    allocs = run_allocs(h, job)
    for a in allocs:
        ca = a.copy_skip_job()
        ca.client_status = ALLOC_CLIENT_RUNNING
        h.state.update_allocs_from_client(h.next_index(), [ca])
    down = allocs[0].node_id
    h.state.update_node_status(h.next_index(), down, NODE_STATUS_DOWN)
    ev = Evaluation(priority=job.priority, type=job.type,
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE, job_id=job.id,
                    node_id=down, namespace=job.namespace)
    h.process("service", ev)
    placed = placed_allocs(h.plans[-1])
    assert len(placed) == 1 and placed[0].node_id != down


@pytest.mark.parametrize("alg", ALGS)
def test_tbl_annotate_plan_counts(alg):
    h, _ = alg_harness(alg, 5)
    job = mock.job()
    job.task_groups[0].count = 3
    h.state.upsert_job(h.next_index(), job)
    ev = register_eval(job)
    ev.annotate_plan = True
    h.process("service", ev)
    plan = h.plans[-1]
    assert plan.annotations is not None
    assert plan.annotations.desired_tg_updates["web"].place == 3
