"""Gossip membership + federation tests.

Covers the serf/memberlist slot (reference nomad/serf.go, server.go:1250):
SWIM convergence, failure detection, refutation, tag dissemination, the
server region map, and cross-region RPC forwarding — all over real UDP/TCP
sockets on loopback, the same single-machine multi-node strategy the
reference uses (SURVEY §4.2).
"""
import time

import pytest

from nomad_tpu.gossip.memberlist import (
    STATUS_ALIVE,
    STATUS_DEAD,
    STATUS_LEFT,
    Memberlist,
    MemberlistConfig,
)


def fast_config(name: str) -> MemberlistConfig:
    return MemberlistConfig(
        name=name,
        probe_interval=0.05,
        probe_timeout=0.05,
        suspicion_timeout=0.3,
        push_pull_interval=0.2,
    )


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def pool():
    lists = []

    def make(name, tags=None):
        ml = Memberlist(fast_config(name), tags=tags)
        lists.append(ml)
        return ml.start()

    yield make
    for ml in lists:
        ml.shutdown()


class TestMemberlist:
    def test_three_way_convergence(self, pool):
        a, b, c = pool("a"), pool("b"), pool("c")
        assert b.join([a.addr]) == 1
        assert c.join([a.addr]) == 1
        for ml in (a, b, c):
            wait_until(lambda ml=ml: ml.num_alive() == 3, msg="3 alive members")
        assert {m.name for m in a.alive_members()} == {"a", "b", "c"}

    def test_join_events_fire(self, pool):
        a = pool("a")
        joined = []
        a.on_join = lambda m: joined.append(m.name)
        b = pool("b")
        b.join([a.addr])
        wait_until(lambda: "b" in joined, msg="join event")

    def test_tag_update_propagates(self, pool):
        a, b = pool("a", tags={"v": "1"}), pool("b")
        b.join([a.addr])
        wait_until(lambda: b.num_alive() == 2)
        updated = []
        b.on_update = lambda m: updated.append((m.name, dict(m.tags)))
        a.set_tags({"v": "2"})
        wait_until(lambda: ("a", {"v": "2"}) in updated, msg="tag update")

    def test_failure_detection(self, pool):
        a, b, c = pool("a"), pool("b"), pool("c")
        b.join([a.addr])
        c.join([a.addr])
        wait_until(lambda: a.num_alive() == 3 and b.num_alive() == 3)
        failed = []
        a.on_fail = lambda m: failed.append(m.name)
        c.shutdown()  # crash, no leave intent
        wait_until(lambda: "c" in failed, msg="failure detection")
        dead = [m for m in a.all_members() if m.name == "c"]
        assert dead and dead[0].status == STATUS_DEAD

    def test_graceful_leave(self, pool):
        a, b = pool("a"), pool("b")
        b.join([a.addr])
        wait_until(lambda: a.num_alive() == 2)
        left = []
        a.on_leave = lambda m: left.append(m.name)
        b.leave()
        wait_until(lambda: "b" in left, msg="leave event")
        gone = [m for m in a.all_members() if m.name == "b"]
        assert gone and gone[0].status == STATUS_LEFT

    def test_restart_with_same_name_rejoins(self, pool):
        """A restarted member (incarnation reset to 1) must outbid the
        cluster's memory of its old, higher incarnation — for both dead
        and gracefully-left predecessors."""
        a = pool("a")
        b = pool("b")
        b.join([a.addr])
        wait_until(lambda: a.num_alive() == 2)
        # age b's incarnation well past a fresh instance's
        for _ in range(5):
            b.set_tags({"gen": "old"})
        wait_until(
            lambda: any(m.name == "b" and m.incarnation >= 5 for m in a.all_members()),
            msg="aged incarnation",
        )
        b.leave()  # predecessor leaves gracefully (status=left, high inc)
        wait_until(
            lambda: any(m.name == "b" and m.status == STATUS_LEFT for m in a.all_members()),
            msg="left recorded",
        )
        b2 = pool("b")  # fresh instance, same name, incarnation 1
        b2.join([a.addr])
        wait_until(
            lambda: any(m.name == "b" and m.status == STATUS_ALIVE for m in a.all_members()),
            msg="restarted member alive again",
        )

    def test_refutes_false_death_rumor(self, pool):
        a, b = pool("a"), pool("b")
        b.join([a.addr])
        wait_until(lambda: a.num_alive() == 2 and b.num_alive() == 2)
        # inject a false dead rumor about b into a
        b_inc = b.local_member().incarnation
        a._on_dead_msg("b", b_inc, STATUS_DEAD)
        # b hears the rumor via gossip, refutes with a higher incarnation,
        # and a resurrects it
        wait_until(
            lambda: any(
                m.name == "b" and m.status == STATUS_ALIVE and m.incarnation > b_inc
                for m in a.all_members()
            ),
            msg="refutation",
        )


class TestServerMembership:
    def test_region_map_and_leader_tag(self, pool):
        from nomad_tpu.server.membership import ServerMembership

        cfgs = {}
        members = []

        def make(name, region, leader=False):
            m = ServerMembership(
                name=name, region=region, datacenter="dc1",
                rpc_addr=("127.0.0.1", 4000 + len(members)),
                config=fast_config(name),
            )
            m.start()
            members.append(m)
            cfgs[name] = m
            return m

        try:
            s1 = make("s1", "east")
            s2 = make("s2", "east")
            s3 = make("s3", "west")
            s2.join([s1.gossip_addr])
            s3.join([s1.gossip_addr])
            for m in members:
                wait_until(lambda m=m: set(m.regions()) == {"east", "west"},
                           msg="region map")
            assert {s.name for s in s3.servers_in_region("east")} == \
                {"s1.east", "s2.east"}
            s1.set_leader(True)
            wait_until(
                lambda: s2.leader_in_region() is not None
                and s2.leader_in_region().name == "s1.east",
                msg="leader tag propagation",
            )
            assert s3.leader_in_region("east").rpc_addr == ("127.0.0.1", 4000)
        finally:
            for m in members:
                m.memberlist.shutdown()


class TestFederatedAgents:
    def test_leader_forwarding_and_regions(self):
        """Two servers sharing a raft: the follower's RPC transparently
        forwards writes to the leader (rpc.go:409)."""
        from nomad_tpu import mock
        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.rpc.transport import RPCClient
        from nomad_tpu.server.raft import InProcRaft
        from nomad_tpu.server.server import Server, ServerConfig

        raft = InProcRaft()
        s1 = Server(ServerConfig(num_schedulers=0), raft=raft, name="s1")
        s2 = Server(ServerConfig(num_schedulers=0), raft=raft, name="s2")
        assert s1.is_leader and not s2.is_leader

        def agent_cfg(name):
            return AgentConfig(
                name=name, server_enabled=True, gossip_enabled=True,
            )

        a1 = Agent(agent_cfg("s1"), server=s1)
        a2 = Agent(agent_cfg("s2"), server=s2)
        try:
            a1.start()
            a2.config.retry_join = [
                "{}:{}".format(*a1.membership.gossip_addr)
            ]
            a2.start()
            wait_until(lambda: a2.membership.num_servers() == 2, msg="peers")
            wait_until(
                lambda: a2.rpc.leader_addr == a1.rpc.addr,
                msg="leader addr learned via gossip",
            )
            # write through the follower: must land in the shared raft
            cli = RPCClient(*a2.rpc.addr)
            cli.call("Node.Register", mock.node())
            assert len(s1.fsm.state.nodes()) == 1
            assert len(s2.fsm.state.nodes()) == 1  # replicated via shared raft

            # leadership transfer: tags flip, follower retargets forwarding
            raft.transfer_leadership(s2.peer)
            wait_until(
                lambda: a1.rpc.leader_addr == a2.rpc.addr,
                msg="new leader learned after transfer",
            )
            cli1 = RPCClient(*a1.rpc.addr)
            cli1.call("Node.Register", mock.node())  # forwarded to new leader
            assert len(s2.fsm.state.nodes()) == 2
            cli1.close()
            cli.close()
        finally:
            a2.shutdown()
            a1.shutdown()

    def test_cross_region_forwarding(self):
        """A request tagged with another region hops there (rpc.go:502)."""
        from nomad_tpu import mock
        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.rpc.transport import RPCClient

        a_east = Agent(AgentConfig(name="e1", region="east"))
        a_west = Agent(AgentConfig(name="w1", region="west"))
        try:
            a_east.start()
            a_west.config.retry_join = [
                "{}:{}".format(*a_east.membership.gossip_addr)
            ]
            a_west.start()
            wait_until(
                lambda: set(a_east.regions()) == {"east", "west"}
                and set(a_west.regions()) == {"east", "west"},
                msg="federated regions",
            )
            # register a job in east by calling west with region=east
            cli = RPCClient(*a_west.rpc.addr)
            job = mock.job()
            cli.call("Job.Register", job, region="east")
            assert a_east.server.fsm.state.job_by_id("default", job.id) is not None
            assert a_west.server.fsm.state.job_by_id("default", job.id) is None
            # reads hop too
            got = cli.call("Job.GetJob", "default", job.id, region="east")
            assert got is not None and got.id == job.id
            cli.close()
        finally:
            a_west.shutdown()
            a_east.shutdown()


class TestGossipEncryption:
    def test_encrypted_cluster_converges(self):
        """Members sharing an encrypt key form a cluster; their datagrams
        on the wire are AES-GCM sealed (serf keyring slot)."""
        import base64
        import os

        key = base64.b64encode(os.urandom(32)).decode().encode()
        lists = []
        try:
            for name in ("enc-a", "enc-b"):
                cfg = fast_config(name)
                cfg.encrypt_key = key
                lists.append(Memberlist(cfg).start())
            lists[1].join([lists[0].addr])
            for m in lists:
                wait_until(lambda m=m: m.num_alive() == 2,
                           msg="encrypted cluster convergence")
            # wire format check: sealed frames carry the version byte and
            # never the msgpack map marker a plaintext message starts with
            sealed = lists[0]._seal(b"probe")
            assert sealed[0:1] == b"\x01" and sealed != b"probe"
            assert lists[1]._unseal(sealed) == b"probe"
        finally:
            for m in lists:
                m.shutdown()

    def test_plaintext_and_wrong_key_dropped(self):
        """A member without the key (or with a different key) cannot join
        or poison an encrypted cluster."""
        import base64
        import os

        key = base64.b64encode(os.urandom(32)).decode().encode()
        cfg = fast_config("enc-secure")
        cfg.encrypt_key = key
        secure = Memberlist(cfg).start()

        plain = Memberlist(fast_config("enc-plain")).start()
        wrong_cfg = fast_config("enc-wrong")
        wrong_cfg.encrypt_key = base64.b64encode(os.urandom(32)).decode().encode()
        wrong = Memberlist(wrong_cfg).start()
        try:
            plain.join([secure.addr])
            wrong.join([secure.addr])
            time.sleep(1.0)
            assert secure.num_alive() == 1, "unauthenticated members must not join"
            # and the secure node's unseal drops both foreign wire formats
            assert secure._unseal(b"\x81\xa1t\xa4ping") is None  # plaintext msgpack
            assert secure._unseal(wrong._seal(b"x")) is None     # wrong key
        finally:
            secure.shutdown()
            plain.shutdown()
            wrong.shutdown()
