"""HTTP agent tests: /v1 surface over a dev-mode agent — reference
command/agent/http_test.go, job_endpoint_test.go, node_endpoint_test.go."""
import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.agent.jsonapi import camel, dumps, from_json_obj, to_json_obj
from nomad_tpu.structs.structs import Job, RestartPolicy


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def call(base, path, method="GET", body=None, headers=None):
    data = None
    if body is not None:
        data = json.dumps(body).encode() if not isinstance(body, bytes) else body
    req = urllib.request.Request(base + path, data=data, method=method,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as resp:
        payload = resp.read().decode()
        return json.loads(payload) if payload else None, dict(resp.headers)


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(dev_mode=True, num_schedulers=2,
                          scheduler_algorithm="binpack", name="dev1"))
    a.start()
    yield a
    a.shutdown()


def batch_echo_job_json():
    job = mock.job()
    job.type = "batch"
    job.task_groups[0].count = 1
    job.task_groups[0].reschedule_policy.attempts = 0
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "echo done"]}
    task.restart_policy = RestartPolicy(attempts=0, mode="fail")
    return job, json.loads(dumps(job))


# ---------------------------------------------------------------------------
# jsonapi codec
# ---------------------------------------------------------------------------


def test_camel_casing():
    assert camel("id") == "ID"
    assert camel("job_id") == "JobID"
    assert camel("memory_mb") == "MemoryMB"
    assert camel("task_groups") == "TaskGroups"
    assert camel("create_index") == "CreateIndex"
    assert camel("eval_ids") == "EvalIDs"
    assert camel("modify_time_ns") == "ModifyTimeNs"


def test_json_roundtrip_job():
    job = mock.job()
    data = to_json_obj(job)
    assert data["ID"] == job.id
    assert data["TaskGroups"][0]["Tasks"][0]["Driver"]
    back = from_json_obj(Job, data)
    assert back.id == job.id
    assert back.task_groups[0].tasks[0].driver == job.task_groups[0].tasks[0].driver
    assert back.task_groups[0].count == job.task_groups[0].count


def test_json_decode_tolerates_snake_and_unknown_keys():
    back = from_json_obj(Job, {"id": "j1", "TotallyUnknown": 5, "Priority": 70})
    assert back.id == "j1" and back.priority == 70


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


def test_register_job_and_run_to_completion(agent):
    base = agent.http_addr
    job, job_json = batch_echo_job_json()
    out, headers = call(base, "/v1/jobs", "PUT", {"Job": job_json})
    assert out["EvalID"]
    assert "X-Nomad-Index" in headers

    def done():
        allocs, _ = call(base, f"/v1/job/{job.id}/allocations?all=true")
        return any(a["ClientStatus"] == "complete" for a in allocs)

    wait_for(done, msg="alloc complete over HTTP")
    got, _ = call(base, f"/v1/job/{job.id}")
    assert got["ID"] == job.id
    summary, _ = call(base, f"/v1/job/{job.id}/summary")
    assert summary["JobID"] == job.id

    evals, _ = call(base, f"/v1/job/{job.id}/evaluations")
    assert evals and evals[0]["JobID"] == job.id
    alloc_id = call(base, f"/v1/job/{job.id}/allocations")[0][0]["ID"]
    alloc, _ = call(base, f"/v1/allocation/{alloc_id}")
    assert alloc["ID"] == alloc_id and alloc["Job"]["ID"] == job.id


def test_jobs_list_and_prefix(agent):
    base = agent.http_addr
    jobs, headers = call(base, "/v1/jobs")
    assert isinstance(jobs, list) and jobs
    assert jobs[0]["JobSummary"]["JobID"]
    none, _ = call(base, "/v1/jobs?prefix=definitely-not-a-job")
    assert none == []


def test_nodes_endpoints(agent):
    base = agent.http_addr
    nodes, _ = call(base, "/v1/nodes")
    assert len(nodes) == 1
    node_id = nodes[0]["ID"]
    node, _ = call(base, f"/v1/node/{node_id}")
    assert node["ID"] == node_id
    allocs, _ = call(base, f"/v1/node/{node_id}/allocations")
    assert isinstance(allocs, list)
    out, _ = call(base, f"/v1/node/{node_id}/eligibility", "PUT",
                  {"Eligibility": "ineligible"})
    assert out["Index"] > 0
    node, _ = call(base, f"/v1/node/{node_id}")
    assert node["SchedulingEligibility"] == "ineligible"
    call(base, f"/v1/node/{node_id}/eligibility", "PUT", {"Eligibility": "eligible"})


def test_blocking_query_unblocks_on_write(agent):
    base = agent.http_addr
    _, headers = call(base, "/v1/jobs")
    index = int(headers["X-Nomad-Index"])

    import threading

    results = {}

    def blocked():
        t0 = time.monotonic()
        results["out"], results["headers"] = call(
            base, f"/v1/jobs?index={index}&wait=30s")
        results["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.3)
    job, job_json = batch_echo_job_json()
    call(base, "/v1/jobs", "PUT", {"Job": job_json})
    t.join(timeout=20)
    assert not t.is_alive()
    assert int(results["headers"]["X-Nomad-Index"]) > index


def test_evaluations_and_deployments_listing(agent):
    base = agent.http_addr
    evals, _ = call(base, "/v1/evaluations")
    assert evals
    ev_id = evals[0]["ID"]
    ev, _ = call(base, f"/v1/evaluation/{ev_id}")
    assert ev["ID"] == ev_id
    deps, _ = call(base, "/v1/deployments")
    assert isinstance(deps, list)


def test_status_and_agent_endpoints(agent):
    base = agent.http_addr
    leader, _ = call(base, "/v1/status/leader")
    host, port = agent.http.addr
    assert leader == f"{host}:{port}"
    self_info, _ = call(base, "/v1/agent/self")
    assert self_info["config"]["Server"]["Enabled"] is True
    health, _ = call(base, "/v1/agent/health")
    assert health["server"]["ok"] and health["client"]["ok"]
    members, _ = call(base, "/v1/agent/members")
    assert members["Members"][0]["Status"] == "alive"
    regions, _ = call(base, "/v1/regions")
    assert regions == ["global"]


def test_operator_scheduler_configuration(agent):
    base = agent.http_addr
    out, _ = call(base, "/v1/operator/scheduler/configuration")
    assert "SchedulerConfig" in out
    call(base, "/v1/operator/scheduler/configuration", "PUT",
         {"SchedulerAlgorithm": "binpack",
          "PreemptionConfig": {"SystemSchedulerEnabled": True}})
    out, _ = call(base, "/v1/operator/scheduler/configuration")
    assert out["SchedulerConfig"]["SchedulerAlgorithm"] == "binpack"


def test_job_stop_and_purge(agent):
    base = agent.http_addr
    job, job_json = batch_echo_job_json()
    call(base, "/v1/jobs", "PUT", {"Job": job_json})
    out, _ = call(base, f"/v1/job/{job.id}?purge=true", "DELETE")
    assert out["EvalID"]
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, f"/v1/job/{job.id}")
    assert e.value.code == 404


def test_404_and_405(agent):
    base = agent.http_addr
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, "/v1/nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, "/v1/jobs", "DELETE")
    assert e.value.code == 405


def test_validate_job(agent):
    base = agent.http_addr
    _, job_json = batch_echo_job_json()
    out, _ = call(base, "/v1/validate/job", "PUT", {"Job": job_json})
    assert out["ValidationErrors"] == []
    bad = dict(job_json)
    bad["TaskGroups"] = []
    out, _ = call(base, "/v1/validate/job", "PUT", {"Job": bad})
    assert out["ValidationErrors"]


def test_system_gc(agent):
    base = agent.http_addr
    out, _ = call(base, "/v1/system/gc", "PUT")
    assert out == {}


def test_job_plan_with_diff(agent):
    base = agent.http_addr
    job, job_json = batch_echo_job_json()
    out, _ = call(base, f"/v1/job/{job.id}/plan", "PUT",
                  {"Job": job_json, "Diff": True})
    assert out["Diff"]["Type"] == "Added"
    assert out["Diff"]["ID"] == job.id
    assert out["JobModifyIndex"] > 0
    # nothing was actually registered by a plan
    with pytest.raises(urllib.error.HTTPError):
        call(base, f"/v1/job/{job.id}")
    # now register, modify, and plan the modification -> Edited
    call(base, "/v1/jobs", "PUT", {"Job": job_json})
    job_json["Priority"] = 90
    out, _ = call(base, f"/v1/job/{job.id}/plan", "PUT",
                  {"Job": job_json, "Diff": True})
    assert out["Diff"]["Type"] == "Edited"
    fields = {f["Name"]: f for f in out["Diff"]["Fields"]}
    assert fields["Priority"]["New"] == "90"


def test_dispatch_parameterized_job(agent):
    base = agent.http_addr
    job, job_json = batch_echo_job_json()
    job_json["Parameterized"] = {"Payload": "optional", "MetaRequired": ["who"]}
    call(base, "/v1/jobs", "PUT", {"Job": job_json})
    # missing required meta -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, f"/v1/job/{job.id}/dispatch", "PUT", {"Meta": {}})
    assert e.value.code == 400
    out, _ = call(base, f"/v1/job/{job.id}/dispatch", "PUT",
                  {"Meta": {"who": "world"}})
    assert out["DispatchedJobID"].startswith(job.id + "/dispatch-")
    child, _ = call(base, f"/v1/job/{out['DispatchedJobID']}")
    assert child["ParentID"] == job.id
    assert child["Meta"]["who"] == "world"
    assert child["Stable"] is False and child["Stop"] is False
    # stopped parent refuses dispatch
    call(base, f"/v1/job/{job.id}", "DELETE")
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, f"/v1/job/{job.id}/dispatch", "PUT", {"Meta": {"who": "x"}})
    assert e.value.code == 400


def test_job_stability_validates_version(agent):
    base = agent.http_addr
    job, job_json = batch_echo_job_json()
    call(base, "/v1/jobs", "PUT", {"Job": job_json})
    with pytest.raises(urllib.error.HTTPError) as e:
        call(base, f"/v1/job/{job.id}/stable", "PUT",
             {"JobVersion": 99, "Stable": True})
    assert e.value.code == 400
    out, _ = call(base, f"/v1/job/{job.id}/stable", "PUT",
                  {"JobVersion": 0, "Stable": True})
    assert out["Index"] > 0
    got, _ = call(base, f"/v1/job/{job.id}")
    assert got["Stable"] is True
