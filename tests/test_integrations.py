"""Vault + Consul integration tests (reference nomad/vault.go,
command/agent/consul/): token derivation/revocation tracked through raft,
the client task vault hook, and task service registration lifecycle —
against in-tree mock Vault/Consul HTTP servers.
"""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.integrations.consul import ConsulClient, ConsulConfig, MockConsulServer
from nomad_tpu.integrations.vault import (
    MockVaultServer,
    VaultClient,
    VaultConfig,
    VaultError,
)


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def vault():
    srv = MockVaultServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def consul():
    srv = MockConsulServer().start()
    yield srv
    srv.stop()


class TestVaultClient:
    def test_derive_renew_revoke(self, vault):
        client = VaultClient(VaultConfig(enabled=True, address=vault.address,
                                         token="root"))
        derived = client.derive_token(["db-read", "kv-write"])
        assert derived["token"].startswith("s.") and derived["accessor"]
        tok = vault.by_accessor[derived["accessor"]]
        assert tok.policies == ["db-read", "kv-write"]
        client.renew(derived["token"])
        assert tok.renewals == 1
        client.revoke_accessor(derived["accessor"])
        assert tok.revoked

    def test_bad_server_token_rejected(self, vault):
        client = VaultClient(VaultConfig(enabled=True, address=vault.address,
                                         token="wrong"))
        with pytest.raises(VaultError):
            client.derive_token(["p"])

    def test_revoke_accessors_reports_failures(self, vault):
        client = VaultClient(VaultConfig(enabled=True, address=vault.address,
                                         token="root"))
        ok = client.derive_token(["a"])
        failed = client.revoke_accessors([ok["accessor"], "no-such-accessor"])
        assert failed == ["no-such-accessor"]


class TestConsulClient:
    def test_register_deregister(self, consul):
        client = ConsulClient(ConsulConfig(address=consul.address))
        client.register_service("web-1", "web", address="10.0.0.1", port=8080,
                                tags=["prod"])
        services = client.services()
        assert services["web-1"]["Name"] == "web"
        assert services["web-1"]["Tags"] == ["prod"]
        client.deregister_service("web-1")
        assert client.services() == {}


class TestServerVaultLifecycle:
    def test_derive_tracks_and_terminal_revokes(self, vault):
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_schedulers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=60,
            vault=VaultConfig(enabled=True, address=vault.address, token="root"),
        ))
        server.start()
        client = Client(ServerProxy(server), ClientConfig())
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.vault = {"policies": ["db-read"], "env": True}
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", 'echo "tok=$VAULT_TOKEN" > $NOMAD_TASK_DIR/v; sleep 60'],
            }
            server.register_job(job)

            def running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs if a.client_status == "running"]

            wait_until(lambda: running(), msg="alloc running with vault token")
            alloc = running()[0]
            # accessor tracked in raft-backed state
            accessors = server.fsm.state.vault_accessors_by_alloc(alloc.id)
            assert len(accessors) == 1 and accessors[0]["task"] == task.name
            tok = vault.by_accessor[accessors[0]["accessor"]]
            assert tok.policies == ["db-read"] and not tok.revoked

            # token on disk + in env
            secrets = os.path.join(client.alloc_dir_base, alloc.id,
                                   task.name, "secrets", "vault_token")
            assert open(secrets).read() == tok.token
            envfile = os.path.join(client.alloc_dir_base, alloc.id,
                                   task.name, "local", "v")
            wait_until(lambda: os.path.exists(envfile), msg="task env dump")
            assert open(envfile).read().strip() == f"tok={tok.token}"

            # alloc dies → token revoked + untracked
            server.stop_alloc(alloc.id)
            wait_until(lambda: tok.revoked, msg="token revoked on alloc stop")
            wait_until(
                lambda: server.fsm.state.vault_accessors_by_alloc(alloc.id) == [],
                msg="accessor untracked",
            )
        finally:
            client.shutdown()
            server.stop()

    def test_vault_job_rejected_without_vault(self):
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_schedulers=0))
        job = mock.job()
        job.task_groups[0].tasks[0].vault = {"policies": ["p"]}
        with pytest.raises(ValueError, match="vault stanza"):
            server.register_job(job)
        server.stop()

    def test_derive_requires_matching_node_secret(self, vault):
        """DeriveVaultToken is node-authenticated: the caller must present
        the placed node's secret_id, and the alloc must live on that node
        (node_endpoint.go:1370) — otherwise any RPC caller could mint
        tokens for any policy set."""
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import Allocation

        server = Server(ServerConfig(
            num_schedulers=0,
            vault=VaultConfig(enabled=True, address=vault.address, token="root"),
        ))
        try:
            node = mock.node()
            other = mock.node()
            server.register_node(node)
            server.register_node(other)
            job = mock.job()
            task = job.task_groups[0].tasks[0]
            task.vault = {"policies": ["db-read"]}
            alloc = mock.alloc()
            alloc.job = job
            alloc.job_id = job.id
            alloc.node_id = node.id
            server.raft_apply("alloc-update", [alloc])

            # no credentials
            with pytest.raises(PermissionError):
                server.derive_vault_token(alloc.id, [task.name])
            # wrong secret
            with pytest.raises(PermissionError):
                server.derive_vault_token(alloc.id, [task.name], node.id, "bogus")
            # right secret, wrong node (alloc not placed there)
            with pytest.raises(PermissionError):
                server.derive_vault_token(
                    alloc.id, [task.name], other.id, other.secret_id
                )
            # the placed node with its real secret succeeds
            tokens = server.derive_vault_token(
                alloc.id, [task.name], node.id, node.secret_id
            )
            assert task.name in tokens
        finally:
            server.stop()


class TestConsulConnect:
    def test_sidecar_injection_hook(self):
        """Registering a job with a connect stanza injects the sidecar
        task + proxy port (job_endpoint_hook_connect.go:99)."""
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import NetworkResource, Service

        server = Server(ServerConfig(num_schedulers=0))
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.networks = [NetworkResource(mbits=10)]
            tg.services = [Service(
                name="web-api", port_label="http",
                connect={"sidecar_service": {}},
            )]
            server.register_job(job)
            stored = server.fsm.state.job_by_id("default", job.id)
            tg2 = stored.task_groups[0]
            sidecars = [t for t in tg2.tasks if t.kind == "connect-proxy:web-api"]
            assert len(sidecars) == 1
            assert sidecars[0].name == "connect-proxy-web-api"
            assert sidecars[0].driver == "docker"
            labels = [p.label for p in tg2.networks[0].dynamic_ports]
            assert "connect-proxy-web-api" in labels
            # re-registering must not double-inject
            server.register_job(stored)
            stored2 = server.fsm.state.job_by_id("default", job.id)
            again = [t for t in stored2.task_groups[0].tasks
                     if t.kind == "connect-proxy:web-api"]
            assert len(again) == 1
        finally:
            server.stop()

    def test_connect_requires_single_network(self):
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import Service

        server = Server(ServerConfig(num_schedulers=0))
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.networks = []  # no group network
            tg.services = [Service(name="api", connect={"sidecar_service": {}})]
            with pytest.raises(ValueError, match="exactly 1 network"):
                server.register_job(job)
        finally:
            server.stop()

    def test_sidecar_and_proxy_registered_in_consul(self, consul):
        """End-to-end: a connect job's group service AND its sidecar proxy
        service (Kind=connect-proxy, DestinationServiceName) land in the
        mock Consul; the injected sidecar task actually runs."""
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.integrations.consul import ConsulConfig
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import NetworkResource, Service

        server = Server(ServerConfig(
            num_schedulers=1, heartbeat_min_ttl=60, heartbeat_max_ttl=60,
        ))
        server.start()
        client = Client(ServerProxy(server), ClientConfig(
            consul=ConsulConfig(address=consul.address),
        ))
        try:
            client.start()
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.networks = [NetworkResource(mbits=10)]
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh", "args": ["-c", "sleep 60"]}
            task.resources.networks = []
            tg.services = [Service(
                name="countdash", port_label="connect-proxy-countdash",
                connect={
                    "sidecar_service": {},
                    # non-docker environment: run a stand-in proxy
                    "sidecar_task": {
                        "driver": "raw_exec",
                        "config": {"command": "/bin/sh",
                                   "args": ["-c", "sleep 60"]},
                    },
                },
            )]
            server.register_job(job)

            def running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return [a for a in allocs if a.client_status == "running"]

            wait_until(lambda: running(), msg="connect alloc running")
            alloc = running()[0]
            # both tasks (app + injected sidecar) run
            ar = client.allocrunners[alloc.id]
            assert set(ar.task_runners) == {"web", "connect-proxy-countdash"}

            # envoy bootstrap hook: the sidecar task's secrets dir holds
            # the generated bootstrap config (envoybootstrap_hook.go)
            import json as _json
            import os as _os

            sidecar_tr = ar.task_runners["connect-proxy-countdash"]
            bs_path = _os.path.join(sidecar_tr.task_dir.secrets_dir,
                                    "envoy_bootstrap.json")
            assert _os.path.exists(bs_path)
            bs = _json.load(open(bs_path))
            assert bs["node"]["cluster"] == "countdash"
            assert bs["node"]["id"].endswith("-countdash-sidecar-proxy")
            assert alloc.id in bs["node"]["id"]

            wait_until(
                lambda: any("sidecar-proxy" in sid for sid in consul.services),
                msg="proxy service registered",
            )
            group_svcs = {s["Name"]: s for s in consul.services.values()}
            assert "countdash" in group_svcs
            proxy = group_svcs["countdash-sidecar-proxy"]
            assert proxy["Kind"] == "connect-proxy"
            assert proxy["Proxy"]["DestinationServiceName"] == "countdash"
            # the proxy advertises the injected dynamic port
            assert proxy["Port"] > 0

            # stop -> THIS alloc's service instances deregister. Assert by
            # service ID (which embeds the alloc id): the scheduler may
            # already have placed a replacement alloc that re-registers
            # the same service NAMES, so name-based checks race.
            server.stop_alloc(alloc.id)
            wait_until(
                lambda: not any(alloc.id in sid for sid in consul.services),
                msg="group services deregistered",
            )
        finally:
            client.shutdown()
            server.stop()


class TestScriptChecks:
    def test_script_check_heartbeats_ttl(self, consul):
        """Script checks run through the driver exec API and heartbeat a
        TTL check in Consul (command/agent/consul/script.go): a passing
        command reports passing; a failing one reports critical; the
        check deregisters with the task."""
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import Service

        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60))
        server.start()
        client = Client(
            ServerProxy(server),
            ClientConfig(consul=ConsulConfig(address=consul.address)),
        )
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh", "args": ["-c", "sleep 60"]}
            task.resources.networks = []
            task.services = [Service(name="scripted", checks=[
                {"name": "ok-check", "type": "script",
                 "command": "/bin/sh", "args": ["-c", "echo healthy; exit 0"],
                 "interval": "1s", "timeout": "5s"},
                {"name": "bad-check", "type": "script",
                 "command": "/bin/sh", "args": ["-c", "echo broken; exit 2"],
                 "interval": "1s", "timeout": "5s"},
            ])]
            server.register_job(job)

            def check(name):
                for cid, c in consul.checks.items():
                    if c["Name"] == name:
                        return c
                return None

            wait_until(lambda: check("ok-check") is not None
                       and check("ok-check")["Status"] == "passing",
                       msg="passing script check")
            assert "healthy" in check("ok-check")["Output"]
            wait_until(lambda: check("bad-check") is not None
                       and check("bad-check")["Status"] == "critical",
                       msg="critical script check")
            assert "broken" in check("bad-check")["Output"]
            # script checks registered against the service, TTL-style
            cid = next(c for c, v in consul.checks.items()
                       if v["Name"] == "ok-check")
            assert consul.checks[cid]["ServiceID"].startswith("_nomad-task-")
            assert consul.checks[cid]["TTL"]

            # stop -> the stopped task's checks deregister. Match on the
            # captured check ID (it embeds the alloc id), not the check
            # name: stop_alloc is a migrate, so the replacement alloc
            # re-registers the same names and can overlap the old
            # task's kill window.
            allocs = server.fsm.state.allocs_by_job("default", job.id, True)
            server.stop_alloc(allocs[0].id)
            wait_until(lambda: cid not in consul.checks,
                       msg="stopped task's script checks deregistered")
        finally:
            client.shutdown()
            server.stop()


class TestTaskServiceRegistration:
    def test_services_follow_task_lifecycle(self, consul):
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import Service

        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60))
        server.start()
        client = Client(
            ServerProxy(server),
            ClientConfig(consul=ConsulConfig(address=consul.address)),
        )
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "mock"
            task.config = {"run_for": "2s"}
            task.services = [Service(name="web", tags=["v1"],
                                     checks=[{"name": "alive", "ttl": "10s"}])]
            server.register_job(job)

            wait_until(lambda: len(consul.services) == 1,
                       msg="service registered while running")
            (sid, svc), = consul.services.items()
            assert svc["Name"] == "web" and svc["Tags"] == ["v1"]
            assert sid.startswith("_nomad-task-")
            assert svc["Checks"][0]["Name"] == "alive"

            wait_until(lambda: len(consul.services) == 0, timeout=60,
                       msg="service deregistered after exit")
        finally:
            client.shutdown()
            server.stop()
