"""Integer scoring spec (tpu/intscore.py) — platform-independence tests.

The parity claim of the int spec: the device scan's selection decisions
are produced by an exact integer program, so they are BIT-IDENTICAL on
every backend — CPU, TPU, anywhere. These tests assert (a) the numpy
implementation matches the pure-Python oracle value-for-value, (b) the
spec tracks the real-valued math within its documented error budget,
and (c) the full scan produces identical outputs when run on two
different backends in one process (cpu vs the default platform — on a
TPU machine that is the real device-vs-host parity check, with no
float in the comparison path).
"""
import numpy as np
import pytest

from nomad_tpu.tpu import intscore
from nomad_tpu.tpu.engine import _build_place_scan, example_scan_inputs


@pytest.fixture(autouse=True)
def _x64():
    # int64 spec arithmetic needs x64 (the engine builders enable it;
    # standalone helper calls here must too)
    import jax

    jax.config.update("jax_enable_x64", True)


def test_exp10_fp_np_matches_python_oracle():
    rng = np.random.default_rng(7)
    xs = np.concatenate([
        rng.integers(-2 * intscore.XQ_ONE, 2 * intscore.XQ_ONE + 1, 500),
        np.array([0, 1, -1, intscore.XQ_ONE, -2 * intscore.XQ_ONE,
                  2 * intscore.XQ_ONE, intscore.XQ_ONE - 1, -intscore.XQ_ONE]),
    ]).astype(np.int64)
    got = intscore.exp10_fp_np(xs)
    want = np.array([intscore.exp10_fp_py(int(x)) for x in xs], np.int64)
    assert (got == want).all()
    got27 = intscore.e27_np(xs)
    want27 = np.array([intscore.e27_py(int(x)) for x in xs], np.int64)
    assert (got27 == want27).all()
    # Q27 values of CLAMPED x_q fit int32 (the e_base/e_ask arrays are
    # int32; xq_* clamps to [-2, 1])
    xq = intscore.xq_np(xs, np.full_like(xs, intscore.XQ_ONE))
    assert intscore.e27_np(xq).max() < 2**31


def test_exp10_fp_accuracy_and_monotonicity():
    # value check vs true 10**x within the spec's error budget, and
    # monotone in x_q (ordering never inverts from rounding)
    xs = np.linspace(-2 * intscore.XQ_ONE, 2 * intscore.XQ_ONE, 4001).astype(np.int64)
    vals = intscore.exp10_fp_np(xs).astype(np.float64)
    true = 10.0 ** (xs / float(intscore.XQ_ONE)) * intscore.E_ONE
    rel = np.abs(vals - true) / true
    assert rel.max() < 1e-6
    assert (np.diff(vals) >= 0).all()


def test_binpack_from_e_tracks_float_reference():
    # the Q30 binpack term (via Q27 exponentials) stays near float math
    rng = np.random.default_rng(13)
    for _ in range(200):
        cc = int(rng.integers(500, 20000))
        cm = int(rng.integers(500, 40000))
        uc = int(rng.integers(0, cc))
        um = int(rng.integers(0, cm))
        ec = intscore.e27_py(intscore.xq_py(cc - uc, cc))
        em = intscore.e27_py(intscore.xq_py(cm - um, cm))
        fp = intscore.binpack_fp_from_e(ec, em) / intscore.TERM_ONE
        fit = 20.0 - (10.0 ** (1 - uc / cc) + 10.0 ** (1 - um / cm))
        ref = min(max(fit, 0.0), 18.0) / 18.0
        assert abs(fp - ref) < 2.5e-6, (uc, um, cc, cm, fp, ref)


def test_running_product_drift_is_bounded():
    # place/evict the same amounts repeatedly: the Q27 running product
    # must stay within k*2**-26 of the directly-computed exponential
    cap = 8000
    ask = 250
    e = intscore.e27_py(intscore.xq_py(cap, cap))  # empty node
    f_place = intscore.e27_py(intscore.xq_py(-ask, cap))
    f_evict = intscore.e27_py(intscore.xq_py(ask, cap))
    k = 0
    for _ in range(50):
        e = intscore.e_sel_py(e, f_place)
        e = intscore.e_sel_py(e, f_evict)
        k += 2
    direct = intscore.e27_py(intscore.xq_py(cap, cap))
    rel = abs(e - direct) / direct
    assert rel < (k + 4) * 2.0**-24


def test_anti_and_even_recip_precision():
    # Q45-reciprocal terms stay within a few Q30-ulp of the exact ratio
    for c, d in [(0, 5), (1, 5), (7, 3), (1000, 999), (2**17 - 1, 2**17)]:
        got = intscore.anti_fp_py(c, d)
        if c <= 0:
            assert got == 0
            continue
        exact = -((c + 1) * intscore.TERM_ONE) // d
        assert abs(got - exact) <= 8
    for cur, mn, mx in [(3, 1, 5), (1, 1, 5), (0, 0, 4), (10, 2, 10)]:
        got = intscore.even_fp_py(cur, mn, mx, True)
        assert isinstance(got, int)
        if cur != mn and mn > 0:
            exact = ((mn - cur) * intscore.TERM_ONE) // mn
            assert abs(got - exact) <= 8


def _scan_outputs(backend=None):
    import jax

    n_pad, static, carry, xs = example_scan_inputs(
        n_nodes=96, n_tgs=3, n_placements=40, n_spreads=1, dtype=np.int32,
        seed=3,
    )
    scan = _build_place_scan()
    if backend is not None:
        dev = jax.devices(backend)[0]
        static = jax.device_put(static, dev)
        carry = jax.device_put(carry, dev)
        xs = jax.device_put(xs, dev)
    _c, outs = scan(n_pad, static, carry, xs)
    return tuple(np.asarray(o) for o in outs)


def test_scan_cross_backend_bit_identical():
    """cpu vs default platform: identical chosen/scores bit-for-bit.

    Under the test suite both are CPU (trivially equal); on a TPU machine
    (run with JAX_PLATFORMS unset) this is the on-chip parity assertion:
    the device executes the same integer program as the host."""
    import jax

    default = jax.default_backend()
    base = _scan_outputs(backend=None)
    cpu = _scan_outputs(backend="cpu")
    for b, c in zip(base, cpu):
        assert b.dtype == c.dtype
        assert (b == c).all(), f"backend {default} diverged from cpu"


def test_scan_scores_are_exact_spec_values():
    """Every emitted score60 is on the 60-scaled mean grid: divisible by
    60//num_terms for some num_terms in 1..5 (necessary structural
    property of the exact integer normalization)."""
    chosen, scores, pulls, skipped, _evict = _scan_outputs()
    assert scores.dtype == np.int64
    placed = chosen >= 0
    assert placed.any()
    facs = np.array([12, 15, 20, 30, 60], np.int64)
    for s in scores[placed]:
        assert any(int(s) % int(f) == 0 for f in facs)


def test_chain_constants_are_exact():
    # spot-check the Q28 chain against high-precision references
    from decimal import Decimal, getcontext

    getcontext().prec = 60
    for i in (0, 1, 12, 23, 24, 25):
        exact = Decimal(10) ** (Decimal(2) ** (i - intscore.XQ_BITS))
        want = int((exact * (1 << intscore.E_BITS)).to_integral_value(
            rounding="ROUND_HALF_EVEN"))
        assert intscore.CHAIN[i] == want
    assert intscore.CHAIN[24] == 10 * intscore.E_ONE
    assert intscore.CHAIN[25] == 100 * intscore.E_ONE
