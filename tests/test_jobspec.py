"""Jobspec HCL parser tests (modeled on reference jobspec/parse_test.go and
its test-fixtures/basic.hcl)."""

import textwrap

import pytest

from nomad_tpu.jobspec import HCLError, parse_duration_ns, parse_hcl, parse_job

BASIC = r'''
# A full-surface jobspec, mirroring jobspec/test-fixtures/basic.hcl
job "binstore-storagelocker" {
  region       = "fooregion"
  namespace    = "foonamespace"
  type         = "batch"
  priority     = 52
  all_at_once  = true
  datacenters  = ["us2", "eu1"]

  meta {
    foo = "bar"
  }

  constraint {
    attribute = "kernel.os"
    value     = "windows"
  }

  constraint {
    distinct_hosts = true
  }

  affinity {
    attribute = "${meta.team}"
    value     = "mobile"
    operator  = "="
    weight    = 50
  }

  spread {
    attribute = "${meta.rack}"
    weight    = 100
    target "r1" {
      percent = 40
    }
    target "r2" {
      percent = 60
    }
  }

  update {
    stagger            = "60s"
    max_parallel       = 2
    health_check       = "task_states"
    min_healthy_time   = "10s"
    healthy_deadline   = "10m"
    progress_deadline  = "10m"
    auto_revert        = true
    auto_promote       = false
    canary             = 1
  }

  periodic {
    cron             = "*/5 * * *"
    prohibit_overlap = true
  }

  group "binsl" {
    count = 5

    restart {
      attempts = 5
      interval = "10m"
      delay    = "15s"
      mode     = "delay"
    }

    reschedule {
      attempts       = 5
      interval       = "12h"
      delay          = "30s"
      delay_function = "exponential"
      max_delay      = "120s"
      unlimited      = false
    }

    ephemeral_disk {
      sticky  = true
      size    = 150
      migrate = true
    }

    network {
      mode = "bridge"
      port "http" {}
      port "admin" {
        static = 8080
        to     = 8081
      }
    }

    volume "foo" {
      type   = "host"
      source = "/path"
    }

    meta {
      elb_mode = "tcp"
    }

    task "binstore" {
      driver = "docker"
      user   = "bob"
      leader = true
      kill_timeout = "22s"
      kill_signal  = "SIGQUIT"

      config {
        image = "hashicorp/binstore"
        labels {
          FOO = "bar"
        }
      }

      env {
        HELLO = "world"
        LOREM = "ipsum"
      }

      service {
        port = "http"
        tags = ["foo", "bar"]
      }

      resources {
        cpu    = 500
        memory = 128

        network {
          mbits = 100
          port "one" {
            static = 1
          }
          port "three" {
            static = 3
          }
          port "http" {}
        }

        device "nvidia/gpu" {
          count = 10
          constraint {
            attribute = "${device.attr.memory}"
            value     = "2GB"
            operator  = ">"
          }
          affinity {
            attribute = "${device.model}"
            value     = "1080ti"
            weight    = 50
          }
        }
      }

      artifact {
        source = "http://foo.com/artifact"
        options {
          checksum = "md5:b8a4f3f72ecab0510a6a31e997461c5f"
        }
      }

      template {
        source      = "foo.tpl"
        destination = "foo.target"
        change_mode = "signal"
      }

      vault {
        policies = ["foo", "bar"]
      }
    }

    task "storagelocker" {
      driver = "docker"
      config {
        image = "hashicorp/storagelocker"
      }
      resources {
        cpu    = 500
        memory = 128
      }
      constraint {
        attribute = "kernel.arch"
        value     = "amd64"
      }
    }
  }
}
'''


def test_parse_basic_job_level():
    job = parse_job(BASIC)
    assert job.id == "binstore-storagelocker"
    assert job.name == "binstore-storagelocker"
    assert job.region == "fooregion"
    assert job.namespace == "foonamespace"
    assert job.type == "batch"
    assert job.priority == 52
    assert job.all_at_once is True
    assert job.datacenters == ["us2", "eu1"]
    assert job.meta == {"foo": "bar"}

    assert len(job.constraints) == 2
    assert job.constraints[0].ltarget == "kernel.os"
    assert job.constraints[0].rtarget == "windows"
    assert job.constraints[0].operand == "="
    assert job.constraints[1].operand == "distinct_hosts"

    assert len(job.affinities) == 1
    a = job.affinities[0]
    assert (a.ltarget, a.rtarget, a.operand, a.weight) == (
        "${meta.team}",
        "mobile",
        "=",
        50,
    )

    assert len(job.spreads) == 1
    sp = job.spreads[0]
    assert sp.attribute == "${meta.rack}"
    assert sp.weight == 100
    assert [(t.value, t.percent) for t in sp.spread_target] == [("r1", 40), ("r2", 60)]

    u = job.update
    assert u.stagger_ns == 60 * 10**9
    assert u.max_parallel == 2
    assert u.health_check == "task_states"
    assert u.auto_revert is True
    assert u.canary == 1

    assert job.periodic.enabled is True
    assert job.periodic.spec == "*/5 * * *"
    assert job.periodic.prohibit_overlap is True


def test_parse_basic_group_and_tasks():
    job = parse_job(BASIC)
    assert len(job.task_groups) == 1
    g = job.task_groups[0]
    assert g.name == "binsl"
    assert g.count == 5
    assert g.restart_policy.attempts == 5
    assert g.restart_policy.interval_ns == 10 * 60 * 10**9
    assert g.restart_policy.mode == "delay"
    assert g.reschedule_policy.delay_function == "exponential"
    assert g.reschedule_policy.max_delay_ns == 120 * 10**9
    assert g.ephemeral_disk.sticky is True
    assert g.ephemeral_disk.migrate is True
    assert g.ephemeral_disk.size_mb == 150
    assert len(g.networks) == 1
    assert g.networks[0].mode == "bridge"
    assert [p.label for p in g.networks[0].dynamic_ports] == ["http"]
    assert [(p.label, p.value, p.to) for p in g.networks[0].reserved_ports] == [
        ("admin", 8080, 8081)
    ]
    assert g.volumes["foo"].source == "/path"
    assert g.meta == {"elb_mode": "tcp"}

    assert [t.name for t in g.tasks] == ["binstore", "storagelocker"]
    t = g.tasks[0]
    assert t.driver == "docker"
    assert t.user == "bob"
    assert t.leader is True
    assert t.kill_timeout_ns == 22 * 10**9
    assert t.kill_signal == "SIGQUIT"
    assert t.config["image"] == "hashicorp/binstore"
    assert t.config["labels"] == {"FOO": "bar"}
    assert t.env == {"HELLO": "world", "LOREM": "ipsum"}
    assert len(t.services) == 1
    assert t.services[0].port_label == "http"
    assert t.services[0].tags == ["foo", "bar"]
    # default service name derives from job/task
    assert "binstore" in t.services[0].name

    r = t.resources
    assert r.cpu == 500 and r.memory_mb == 128
    assert len(r.networks) == 1
    assert r.networks[0].mbits == 100
    assert [(p.label, p.value) for p in r.networks[0].reserved_ports] == [
        ("one", 1),
        ("three", 3),
    ]
    assert [p.label for p in r.networks[0].dynamic_ports] == ["http"]
    assert len(r.devices) == 1
    d = r.devices[0]
    assert d.name == "nvidia/gpu"
    assert d.count == 10
    assert d.constraints[0].operand == ">"
    assert d.affinities[0].weight == 50

    assert t.artifacts[0]["source"] == "http://foo.com/artifact"
    assert t.artifacts[0]["options"]["checksum"].startswith("md5:")
    assert t.templates[0]["change_mode"] == "signal"
    assert t.templates[0]["splay"] == "5s"  # default
    assert t.vault["policies"] == ["foo", "bar"]
    assert t.vault["env"] is True  # default

    t2 = g.tasks[1]
    assert t2.constraints[0].ltarget == "kernel.arch"


def test_constraint_sugar_operators():
    src = textwrap.dedent(
        """
        job "x" {
          constraint {
            attribute = "${attr.kernel.version}"
            version   = ">= 4.0"
          }
          constraint {
            attribute = "${node.class}"
            regexp    = "foo.*"
          }
          constraint {
            attribute    = "${meta.tags}"
            set_contains = "a,b"
          }
          constraint {
            attribute = "${attr.driver.docker}"
            operator  = "is_set"
            is_set    = true
          }
          group "g" { task "t" { driver = "mock" } }
        }
        """
    )
    job = parse_job(src)
    ops = [c.operand for c in job.constraints]
    assert ops == ["version", "regexp", "set_contains", "is_set"]
    assert job.constraints[0].rtarget == ">= 4.0"
    assert job.constraints[3].rtarget == ""


def test_bare_task_becomes_group():
    src = 'job "j" { task "solo" { driver = "raw_exec" config { command = "true" } } }'
    job = parse_job(src)
    assert len(job.task_groups) == 1
    assert job.task_groups[0].name == "solo"
    assert job.task_groups[0].count == 1
    assert job.task_groups[0].tasks[0].driver == "raw_exec"


def test_parameterized_and_dispatch_payload():
    src = textwrap.dedent(
        """
        job "j" {
          type = "batch"
          parameterized {
            payload       = "required"
            meta_required = ["one"]
            meta_optional = ["two"]
          }
          group "g" {
            task "t" {
              driver = "mock"
              dispatch_payload {
                file = "foo.json"
              }
            }
          }
        }
        """
    )
    job = parse_job(src)
    assert job.parameterized.payload == "required"
    assert job.parameterized.meta_required == ["one"]
    assert job.task_groups[0].tasks[0].dispatch_payload_file == "foo.json"


def test_heredoc_and_comments():
    src = (
        'job "j" {\n'
        "  // line comment\n"
        "  /* block\n     comment */\n"
        '  group "g" {\n'
        '    task "t" {\n'
        '      driver = "raw_exec"\n'
        "      config {\n"
        "        command = \"bash\"\n"
        "        script = <<-EOF\n"
        "          echo hello\n"
        "          echo world\n"
        "        EOF\n"
        "      }\n"
        "    }\n"
        "  }\n"
        "}\n"
    )
    job = parse_job(src)
    script = job.task_groups[0].tasks[0].config["script"]
    assert script == "echo hello\necho world\n"


def test_interpolation_preserved():
    src = 'job "j" { group "g" { task "t" { driver = "mock" env { N = "${node.unique.name}" } } } }'
    job = parse_job(src)
    assert job.task_groups[0].tasks[0].env["N"] == "${node.unique.name}"


def test_parse_durations():
    assert parse_duration_ns("10s") == 10 * 10**9
    assert parse_duration_ns("1h30m") == 5400 * 10**9
    assert parse_duration_ns("250ms") == 250 * 10**6
    assert parse_duration_ns("1.5h") == 5400 * 10**9
    assert parse_duration_ns("-15s") == -15 * 10**9
    assert parse_duration_ns(5000) == 5000
    with pytest.raises(HCLError):
        parse_duration_ns("10 parsecs")


def test_errors():
    with pytest.raises(HCLError):
        parse_job("not a job")
    with pytest.raises(HCLError):
        parse_job('job "a" {} job "b" {}')
    with pytest.raises(HCLError):
        parse_job('job "a" { group "g" {} }')  # no tasks
    with pytest.raises(HCLError):
        parse_job('job "a" { group "g" { task "t" { driver = "mock" } } group "g" { task "t" { driver = "mock" } } }')
    with pytest.raises(HCLError):
        parse_hcl('key = "unterminated')


def test_hcl_lists_and_objects():
    obj = parse_hcl(
        'nums = [1, 2, 3]\nmixed = ["a", true, 1.5]\nobj = { a = 1, b = "two" }'
    )
    assert obj.get("nums") == [1, 2, 3]
    assert obj.get("mixed") == ["a", True, 1.5]
    inner = obj.get("obj")
    assert inner.get("a") == 1 and inner.get("b") == "two"


def test_group_service_connect_stanza():
    """Group-level service with a Consul Connect stanza parses into
    Service.connect (parse_service.go parseConnect) and survives the
    register-time sidecar injection hook."""
    job = parse_job('''
job "countdash" {
  datacenters = ["dc1"]
  group "api" {
    network { mbits = 10 }
    service {
      name = "count-api"
      port = "connect-proxy-count-api"
      connect {
        sidecar_service {
          proxy {
            local_service_port = 9001
          }
        }
        sidecar_task {
          driver = "raw_exec"
        }
      }
    }
    task "web" {
      driver = "mock"
      config { run_for = "10s" }
    }
  }
}
''')
    tg = job.task_groups[0]
    assert len(tg.services) == 1
    svc = tg.services[0]
    assert svc.name == "count-api"
    assert svc.has_sidecar()
    assert svc.connect["sidecar_service"]["proxy"]["local_service_port"] == 9001
    assert svc.connect["sidecar_task"]["driver"] == "raw_exec"

    from nomad_tpu.server.job_hooks import job_connect_hook

    job_connect_hook(job)
    kinds = [t.kind for t in tg.tasks]
    assert "connect-proxy:count-api" in kinds
    labels = [p.label for p in tg.networks[0].dynamic_ports]
    assert "connect-proxy-count-api" in labels
