"""nomad-lockdep's dynamic side (nomad_tpu/utils/lock_witness.py).

The contract under test:

  * disarmed (the default) the factories return PLAIN threading locks —
    zero instrumentation, zero edges;
  * armed, a planted A->B / B->A inversion raises
    :class:`LockOrderViolation` at acquisition time, before the second
    thread can deadlock, and the failed acquisition does not leak the
    inner lock;
  * same-name nesting is reentrant (lock-class semantics), trylocks
    record holds but no order edges, and a Condition wait() drops the
    lock from the thread's held set while parked;
  * cross_check() reports exactly the witnessed edges missing from a
    static edge set.
"""
import threading

import pytest

from nomad_tpu.utils import lock_witness
from nomad_tpu.utils.lock_witness import (
    LockOrderViolation,
    LockWitness,
    witness_condition,
    witness_lock,
    witness_rlock,
)


@pytest.fixture(autouse=True)
def _disarmed():
    lock_witness.disarm()
    yield
    lock_witness.disarm()


# ---------------------------------------------------------------------------
# pass-through
# ---------------------------------------------------------------------------


def test_disarmed_factories_return_plain_locks():
    assert lock_witness.active() is None
    lk = witness_lock("x.X._lock")
    rlk = witness_rlock("x.X._rlock")
    assert type(lk) is type(threading.Lock())
    assert type(rlk) is type(threading.RLock())
    assert lock_witness.stats() == {"armed": 0}
    assert lock_witness.held_snapshot() == {}


def test_disarmed_usage_adds_zero_edges_after_arming():
    """Locks created before arm() stay plain: using them under a
    later-armed witness contributes nothing."""
    pre = witness_lock("pre.Pre._lock")
    w = lock_witness.arm()
    post = witness_lock("post.Post._lock")
    with pre:
        with post:
            pass
    # only the instrumented lock registered an acquisition; the plain
    # one is invisible, so no edge could involve it
    assert w.edges() == []
    assert w.stats()["acquisitions"] == 1


# ---------------------------------------------------------------------------
# planted inversion
# ---------------------------------------------------------------------------


def test_planted_inversion_raises_with_both_stacks():
    lock_witness.arm()
    a = witness_lock("t.T._a")
    b = witness_lock("t.T._b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderViolation) as ei:
            a.acquire()
    msg = str(ei.value)
    assert "t.T._a" in msg and "t.T._b" in msg
    assert "this thread" in msg
    assert "first witnessed on thread" in msg
    # the failed acquisition must not leak the inner lock
    assert not a.locked()
    with a:  # still usable on the correct order
        pass


def test_planted_inversion_raises_across_threads():
    w = lock_witness.arm()
    a = witness_lock("x.X._a")
    b = witness_lock("x.X._b")

    def fwd():
        with a:
            with b:
                pass

    t = threading.Thread(target=fwd)
    t.start()
    t.join()
    with b:
        with pytest.raises(LockOrderViolation):
            with a:
                pass
    assert w.stats()["violations"] == 1


def test_consistent_order_never_raises():
    w = lock_witness.arm()
    a = witness_lock("y.Y._a")
    b = witness_lock("y.Y._b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert w.edges() == [("y.Y._a", "y.Y._b")]
    assert w.stats()["violations"] == 0


# ---------------------------------------------------------------------------
# lock-class semantics and trylocks
# ---------------------------------------------------------------------------


def test_same_name_nesting_is_reentrant_no_edges():
    w = lock_witness.arm()
    outer = witness_rlock("snap.Snap._lock")
    inner = witness_rlock("snap.Snap._lock")  # a thousand snapshots, one node
    with outer:
        with inner:
            pass
    assert w.edges() == []


def test_trylock_records_hold_but_no_order_edge():
    w = lock_witness.arm()
    a = witness_lock("z.Z._a")
    b = witness_lock("z.Z._b")
    with a:
        assert b.acquire(blocking=False)
        assert "z.Z._b" in [n for ns in w.held_snapshot().values() for n in ns]
        b.release()
    assert w.edges() == []


# ---------------------------------------------------------------------------
# conditions
# ---------------------------------------------------------------------------


def test_condition_wait_drops_hold_while_parked():
    w = lock_witness.arm()
    lk = witness_lock("c.C._lock")
    cond = threading.Condition(lk)
    parked = threading.Event()
    released = []

    def waiter():
        with cond:
            parked.set()
            cond.wait(timeout=10)
            released.append(True)

    t = threading.Thread(target=waiter, name="parked-waiter")
    t.start()
    parked.wait(5)
    # the waiter is parked inside wait(): it must NOT look like a holder
    for _ in range(200):
        held = {n for ns in w.held_snapshot().values() for n in ns}
        if "c.C._lock" not in held:
            break
        threading.Event().wait(0.01)
    else:
        raise AssertionError("parked waiter still shown as lock holder")
    with cond:
        cond.notify()
    t.join(5)
    assert released == [True]
    assert w.stats()["violations"] == 0


def test_witness_condition_factory_mints_a_witnessed_lock():
    w = lock_witness.arm()
    cond = witness_condition("m.M._cond")
    with cond:
        pass
    st = w.stats()
    assert st["locks"] == 1
    assert st["acquisitions"] == 1
    assert st["violations"] == 0


# ---------------------------------------------------------------------------
# cross-check against the static graph
# ---------------------------------------------------------------------------


def test_cross_check_reports_only_missing_edges():
    w = LockWitness()
    lock_witness.arm(w)
    a = witness_lock("s.S._a")
    b = witness_lock("s.S._b")
    c = witness_lock("s.S._c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    static = {("s.S._a", "s.S._b")}  # b->c never derived statically
    assert w.cross_check(static) == [("s.S._b", "s.S._c")]
    static_full = {("s.S._a", "s.S._b"), ("s.S._b", "s.S._c")}
    assert w.cross_check(static_full) == []


def test_arm_twice_is_idempotent_but_two_witnesses_conflict():
    w1 = lock_witness.arm()
    assert lock_witness.arm() is w1
    with pytest.raises(RuntimeError):
        lock_witness.arm(LockWitness())
    lock_witness.disarm()
    w2 = lock_witness.arm(LockWitness())
    assert w2 is not w1
