"""Logmon + alloc FS API tests.

Covers reference ``client/logmon`` (rotated capture surviving restarts),
``client/fs_endpoint.go`` + ``command/agent/fs_endpoint.go`` (ls/stat/cat/
readat/logs over HTTP), the server→client proxy hop, and the alloc
logs/fs CLI.
"""
import json
import os
import time
import urllib.request

import pytest

from nomad_tpu.client.logmon import RotatingWriter, read_logs, spawn_logmon


def wait_until(fn, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestRotatingWriter:
    def test_rotation_and_pruning(self, tmp_path):
        w = RotatingWriter(str(tmp_path), "t.stdout", max_files=3, max_bytes=10)
        for i in range(10):
            w.write(b"0123456789")  # exactly one file each
        w.close()
        names = sorted(os.listdir(tmp_path))
        # newest index 9; only 3 files kept
        assert names == ["t.stdout.7", "t.stdout.8", "t.stdout.9"]

    def test_resumes_at_newest_index(self, tmp_path):
        w = RotatingWriter(str(tmp_path), "t.stdout", max_files=5, max_bytes=100)
        w.write(b"first")
        w.close()
        w2 = RotatingWriter(str(tmp_path), "t.stdout", max_files=5, max_bytes=100)
        w2.write(b"|second")
        w2.close()
        assert open(tmp_path / "t.stdout.0", "rb").read() == b"first|second"

    def test_read_logs_spans_rotated_files(self, tmp_path):
        w = RotatingWriter(str(tmp_path), "t.stdout", max_files=10, max_bytes=4)
        w.write(b"abcdefghij")
        w.close()
        data, next_off = read_logs(str(tmp_path), "t", "stdout")
        assert data == b"abcdefghij" and next_off == 10
        data, _ = read_logs(str(tmp_path), "t", "stdout", offset=6)
        assert data == b"ghij"
        data, _ = read_logs(str(tmp_path), "t", "stdout", offset=3, origin="end")
        assert data == b"hij"


class TestLogmonProcess:
    def test_capture_through_fifos(self, tmp_path):
        log_dir = str(tmp_path)
        out_fifo, err_fifo, proc = spawn_logmon(log_dir, "web", max_files=2,
                                                max_bytes=1 << 20)
        with open(out_fifo, "wb") as out, open(err_fifo, "wb") as err:
            out.write(b"hello stdout\n")
            err.write(b"hello stderr\n")
        proc.wait(timeout=10)
        wait_until(lambda: os.path.exists(os.path.join(log_dir, "web.stdout.0")))
        assert open(os.path.join(log_dir, "web.stdout.0"), "rb").read() == b"hello stdout\n"
        assert open(os.path.join(log_dir, "web.stderr.0"), "rb").read() == b"hello stderr\n"
        # fifos removed after exit
        assert not os.path.exists(out_fifo)


@pytest.fixture(scope="class")
def dev_agent():
    from nomad_tpu import mock
    from nomad_tpu.agent.agent import Agent, AgentConfig

    agent = Agent(AgentConfig(name="fs-dev", dev_mode=True, gossip_enabled=False))
    agent.start()
    job = mock.job()
    job.task_groups[0].count = 1
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {
        "command": "/bin/sh",
        "args": ["-c", "echo line-out; echo line-err >&2; "
                       "echo data > $NOMAD_TASK_DIR/file.txt; sleep 60"],
    }
    agent.server.register_job(job)

    def running():
        allocs = agent.server.fsm.state.allocs_by_job("default", job.id, True)
        return allocs and allocs[0].client_status == "running"

    wait_until(running, timeout=30, msg="alloc running")
    alloc = agent.server.fsm.state.allocs_by_job("default", job.id, True)[0]
    yield agent, alloc, task.name
    agent.shutdown()


def _get(agent, path, raw=False):
    with urllib.request.urlopen(agent.http_addr + path) as r:
        data = r.read()
    return data if raw else json.loads(data)


class TestFSEndpoints:
    def test_logs_capture_rotated(self, dev_agent):
        agent, alloc, task = dev_agent
        wait_until(
            lambda: b"line-out" in _get(
                agent, f"/v1/client/fs/logs/{alloc.id}?task={task}&type=stdout",
                raw=True),
            msg="stdout captured",
        )
        err = _get(agent, f"/v1/client/fs/logs/{alloc.id}?task={task}&type=stderr",
                   raw=True)
        assert b"line-err" in err

    def test_ls_stat_cat_readat(self, dev_agent):
        agent, alloc, task = dev_agent
        wait_until(
            lambda: any(e["Name"] == "file.txt" for e in _get(
                agent, f"/v1/client/fs/ls/{alloc.id}?path=/{task}/local")),
            msg="task file visible",
        )
        st = _get(agent, f"/v1/client/fs/stat/{alloc.id}?path=/{task}/local/file.txt")
        assert not st["IsDir"] and st["Size"] == 5
        data = _get(agent, f"/v1/client/fs/cat/{alloc.id}?path=/{task}/local/file.txt",
                    raw=True)
        assert data == b"data\n"
        part = _get(
            agent,
            f"/v1/client/fs/readat/{alloc.id}?path=/{task}/local/file.txt&offset=1&limit=2",
            raw=True)
        assert part == b"at"

    def test_path_escape_rejected(self, dev_agent):
        agent, alloc, _ = dev_agent
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(agent, f"/v1/client/fs/cat/{alloc.id}?path=../../../etc/passwd")
        assert e.value.code == 403

    def test_unknown_alloc_404(self, dev_agent):
        agent, _, _ = dev_agent
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(agent, "/v1/client/fs/ls/00000000-dead-beef-0000-000000000000")
        assert e.value.code == 404

    def test_cli_alloc_logs_and_fs(self, dev_agent):
        from nomad_tpu.cli.main import main as run_cli

        agent, alloc, task = dev_agent
        out = []
        code = run_cli(["-address", agent.http_addr, "alloc", "logs",
                        alloc.id[:8]], out.append)
        assert code == 0 and any("line-out" in line for line in out)
        out2 = []
        code = run_cli(["-address", agent.http_addr, "alloc", "fs",
                        alloc.id[:8], f"/{task}/local"], out2.append)
        assert code == 0 and any("file.txt" in line for line in out2)
        out3 = []
        code = run_cli(["-address", agent.http_addr, "alloc", "fs",
                        alloc.id[:8], f"/{task}/local/file.txt"], out3.append)
        assert code == 0 and any("data" in line for line in out3)


class TestCrossNodeProxy:
    def test_server_agent_proxies_to_client_agent(self):
        """Server-only agent proxies fs requests to the node's agent
        (client_fs_endpoint.go hop)."""
        from nomad_tpu import mock
        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60), name="srv")
        server_agent = Agent(
            AgentConfig(name="srv", gossip_enabled=False), server=server
        )
        client = Client(ServerProxy(server), ClientConfig())
        client_agent = Agent(
            AgentConfig(name="cli", server_enabled=False, gossip_enabled=False),
            server=None, client=client,
        )
        try:
            server_agent.start()
            client_agent.start()
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh",
                           "args": ["-c", "echo remote-log; sleep 60"]}
            server.register_job(job)

            def running():
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                return allocs and allocs[0].client_status == "running"

            wait_until(running, timeout=30, msg="alloc running")
            alloc = server.fsm.state.allocs_by_job("default", job.id, True)[0]
            # ask the SERVER agent, which must hop to the client agent
            wait_until(
                lambda: b"remote-log" in _get(
                    server_agent,
                    f"/v1/client/fs/logs/{alloc.id}?task={task.name}&type=stdout",
                    raw=True),
                msg="proxied logs",
            )
        finally:
            client_agent.shutdown()
            server_agent.shutdown()


class TestClientStats:
    def test_host_and_alloc_stats(self, dev_agent):
        agent, alloc, task = dev_agent
        host = _get(agent, "/v1/client/stats")
        # server-side proxying by node id hits the same node
        host2 = _get(agent, f"/v1/client/stats?node_id={alloc.node_id}")
        assert host2["Memory"]["Total"] == host["Memory"]["Total"]
        assert host["Memory"]["Total"] > 0
        assert "LoadAvg" in host and host["Uptime"] > 0
        stats = _get(agent, f"/v1/client/allocation/{alloc.id}/stats")
        assert task in stats["Tasks"]
        assert stats["ResourceUsage"]["MemoryStats"]["RSS"] >= 0


class TestAllocLifecycle:
    def test_signal_and_exec(self, dev_agent):
        import urllib.request as _ur

        agent, alloc, task = dev_agent
        # exec through the raw_exec driver
        req = _ur.Request(
            agent.http_addr + f"/v1/client/allocation/{alloc.id}/exec",
            data=json.dumps({"Task": task, "Cmd": ["/bin/echo", "exec-ok"]}).encode(),
            method="POST")
        out = json.load(_ur.urlopen(req))
        assert out["ExitCode"] == 0 and "exec-ok" in out["Output"]
        # signal with a harmless signal
        req = _ur.Request(
            agent.http_addr + f"/v1/client/allocation/{alloc.id}/signal",
            data=json.dumps({"Signal": "SIGCONT", "Task": task}).encode(),
            method="PUT")
        assert json.load(_ur.urlopen(req)) == {"Index": 0}

    def test_cli_restart(self, dev_agent):
        from nomad_tpu.cli.main import main as run_cli

        agent, alloc, task = dev_agent
        out = []
        code = run_cli(["-address", agent.http_addr, "alloc", "restart",
                        alloc.id[:8]], out.append)
        assert code == 0 and any("restarted" in line for line in out)
        # the task comes back up after the in-place restart
        wait_until(
            lambda: agent.server.fsm.state.alloc_by_id(alloc.id).client_status
            == "running",
            msg="task running again after restart",
        )
