"""Telemetry tests (reference go-metrics InmemSink semantics + /v1/metrics)."""

import time

import pytest

from nomad_tpu.utils.metrics import InmemSink, global_sink


def test_counter_aggregation():
    s = InmemSink(interval=100)
    s.incr_counter("nomad.test.count")
    s.incr_counter("nomad.test.count", 4)
    out = s.summary()
    (c,) = out["Counters"]
    assert c["Name"] == "nomad.test.count"
    assert c["Count"] == 2
    assert c["Sum"] == 5
    assert c["Min"] == 1 and c["Max"] == 4
    assert c["Mean"] == 2.5


def test_samples_and_gauges():
    s = InmemSink(interval=100)
    s.add_sample("nomad.test.latency", 10.0)
    s.add_sample("nomad.test.latency", 30.0)
    s.set_gauge("nomad.test.depth", 7)
    out = s.summary()
    (smp,) = out["Samples"]
    assert smp["Mean"] == 20.0
    (g,) = out["Gauges"]
    assert g == {"Name": "nomad.test.depth", "Value": 7}


def test_measure_since_records_ms():
    s = InmemSink(interval=100)
    start = time.monotonic()
    time.sleep(0.01)
    s.measure_since("nomad.test.elapsed", start)
    (smp,) = s.summary()["Samples"]
    assert smp["Max"] >= 10.0  # ms


def test_interval_rotation_retains_gauges():
    s = InmemSink(interval=0.05, retain=3)
    s.set_gauge("g", 1)
    s.incr_counter("c")
    time.sleep(0.06)
    s.incr_counter("c2")  # forces rotation
    out = s.summary()
    assert [g["Name"] for g in out["Gauges"]] == ["g"]  # gauges survive
    assert [c["Name"] for c in out["Counters"]] == ["c2"]  # counters don't


def test_prometheus_format():
    s = InmemSink(interval=100)
    s.set_gauge("nomad.broker.total_ready", 3)
    s.incr_counter("nomad.worker.dequeue_eval", 2)
    s.add_sample("nomad.plan.apply", 1.5)
    text = s.prometheus()
    assert "nomad_broker_total_ready 3" in text
    assert "nomad_worker_dequeue_eval 2.0" in text
    assert "nomad_plan_apply_sum 1.5" in text
    assert "nomad_plan_apply_count 1" in text


def test_server_emits_reference_metric_names(dev_agent_factory=None):
    """Scheduling one job must tick the reference-named hot-path counters."""
    from nomad_tpu import mock
    from nomad_tpu.agent import Agent, AgentConfig

    global_sink().reset()
    a = Agent(AgentConfig(dev_mode=True, num_schedulers=1, name="metrics-dev"))
    a.start()
    try:
        job = mock.job()
        job.id = "metrics-job"
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock"
        task.config = {"run_for": "5s"}
        a.server.register_job(job)
        deadline = time.time() + 10
        while time.time() < deadline:
            names = {c["Name"] for c in global_sink().summary()["Counters"]}
            snames = {c["Name"] for c in global_sink().summary()["Samples"]}
            if "nomad.worker.dequeue_eval" in names and any(
                n.startswith("nomad.worker.invoke_scheduler.") for n in snames
            ):
                break
            time.sleep(0.1)
        summary = global_sink().summary()
        counters = {c["Name"] for c in summary["Counters"]}
        samples = {c["Name"] for c in summary["Samples"]}
        assert "nomad.worker.dequeue_eval" in counters
        assert any(n.startswith("nomad.worker.invoke_scheduler.") for n in samples)
        assert "nomad.plan.evaluate" in samples
        assert "nomad.plan.apply" in samples
        # /v1/metrics endpoint serves the summary
        import json
        import urllib.request

        with urllib.request.urlopen(a.http_addr + "/v1/metrics", timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert "Counters" in doc and "Gauges" in doc
        with urllib.request.urlopen(
            a.http_addr + "/v1/metrics?format=prometheus", timeout=10
        ) as r:
            text = r.read().decode()
        assert "nomad_worker_dequeue_eval" in text
    finally:
        a.shutdown()
