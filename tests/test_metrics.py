"""Telemetry tests (reference go-metrics InmemSink semantics + /v1/metrics)."""

import time

import pytest

from nomad_tpu.utils.metrics import InmemSink, global_sink


def test_counter_aggregation():
    s = InmemSink(interval=100)
    s.incr_counter("nomad.test.count")
    s.incr_counter("nomad.test.count", 4)
    out = s.summary()
    (c,) = out["Counters"]
    assert c["Name"] == "nomad.test.count"
    assert c["Count"] == 2
    assert c["Sum"] == 5
    assert c["Min"] == 1 and c["Max"] == 4
    assert c["Mean"] == 2.5


def test_samples_and_gauges():
    s = InmemSink(interval=100)
    s.add_sample("nomad.test.latency", 10.0)
    s.add_sample("nomad.test.latency", 30.0)
    s.set_gauge("nomad.test.depth", 7)
    out = s.summary()
    (smp,) = out["Samples"]
    assert smp["Mean"] == 20.0
    (g,) = out["Gauges"]
    assert g == {"Name": "nomad.test.depth", "Value": 7}


def test_measure_since_records_ms():
    s = InmemSink(interval=100)
    start = time.monotonic()
    time.sleep(0.01)
    s.measure_since("nomad.test.elapsed", start)
    (smp,) = s.summary()["Samples"]
    assert smp["Max"] >= 10.0  # ms


def test_interval_rotation_retains_gauges():
    s = InmemSink(interval=0.05, retain=3)
    s.set_gauge("g", 1)
    s.incr_counter("c")
    time.sleep(0.06)
    s.incr_counter("c2")  # forces rotation
    out = s.summary()
    assert [g["Name"] for g in out["Gauges"]] == ["g"]  # gauges survive
    assert [c["Name"] for c in out["Counters"]] == ["c2"]  # counters don't


def test_gauges_and_counter_sums_accessors():
    """Cheap flight-frame accessors: gauges merge across retained
    intervals (newest wins), counter sums scope to the current one."""
    s = InmemSink(interval=0.05, retain=3)
    s.set_gauge("nomad.test.a", 1)
    s.incr_counter("nomad.test.c", 2)
    time.sleep(0.06)
    s.set_gauge("nomad.test.b", 5)  # forces rotation
    s.incr_counter("nomad.test.d", 3)
    g = s.gauges()
    assert g["nomad.test.a"] == 1 and g["nomad.test.b"] == 5
    assert s.counter_sums() == {"nomad.test.d": 3}
    s.set_gauge("nomad.test.a", 9)
    assert s.gauges()["nomad.test.a"] == 9  # newest interval wins the merge


def test_interval_rotation_under_concurrent_writers():
    """Writers hammering the sink across rotations must never corrupt an
    aggregate or grow the ring past ``retain`` — flight-recorder
    publishers and worker hot paths all share one global sink."""
    import threading

    s = InmemSink(interval=0.03, retain=3)
    stop = threading.Event()
    errors = []

    def writer(i):
        n = 0
        while not stop.is_set():
            s.incr_counter("nomad.stress.ticks")
            s.set_gauge("nomad.stress.g%d" % i, n)
            n += 1

    def reader():
        while not stop.is_set():
            try:
                s.gauges()
                s.counter_sums()
                s.summary()
                s.prometheus()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    threads.append(threading.Thread(target=reader))
    for t in threads:
        t.start()
    time.sleep(0.25)  # ~8 rotations at 30ms
    stop.set()
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive()
    assert not errors
    assert len(s._intervals) <= 3  # retention bound held under load
    # unit increments mean every retained aggregate must have
    # Count == Sum and Min == Max == 1 — anything else is corruption
    for itv in s._intervals:
        agg = itv.counters.get("nomad.stress.ticks")
        if agg is not None:
            assert agg.count == agg.sum
            assert agg.min == 1.0 and agg.max == 1.0
    g = s.gauges()
    for i in range(4):
        assert "nomad.stress.g%d" % i in g  # last write per thread survives


def test_prometheus_format():
    s = InmemSink(interval=100)
    s.set_gauge("nomad.broker.total_ready", 3)
    s.incr_counter("nomad.worker.dequeue_eval", 2)
    s.add_sample("nomad.plan.apply", 1.5)
    text = s.prometheus()
    assert "nomad_broker_total_ready 3" in text
    assert "nomad_worker_dequeue_eval 2.0" in text
    assert "nomad_plan_apply_sum 1.5" in text
    assert "nomad_plan_apply_count 1" in text


def test_server_emits_reference_metric_names(dev_agent_factory=None):
    """Scheduling one job must tick the reference-named hot-path counters."""
    from nomad_tpu import mock
    from nomad_tpu.agent import Agent, AgentConfig

    global_sink().reset()
    a = Agent(AgentConfig(dev_mode=True, num_schedulers=1, name="metrics-dev"))
    a.start()
    try:
        job = mock.job()
        job.id = "metrics-job"
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock"
        task.config = {"run_for": "5s"}
        a.server.register_job(job)
        deadline = time.time() + 10
        while time.time() < deadline:
            names = {c["Name"] for c in global_sink().summary()["Counters"]}
            snames = {c["Name"] for c in global_sink().summary()["Samples"]}
            if "nomad.worker.dequeue_eval" in names and any(
                n.startswith("nomad.worker.invoke_scheduler.") for n in snames
            ):
                break
            time.sleep(0.1)
        summary = global_sink().summary()
        counters = {c["Name"] for c in summary["Counters"]}
        samples = {c["Name"] for c in summary["Samples"]}
        assert "nomad.worker.dequeue_eval" in counters
        assert any(n.startswith("nomad.worker.invoke_scheduler.") for n in samples)
        assert "nomad.plan.evaluate" in samples
        assert "nomad.plan.apply" in samples
        # /v1/metrics endpoint serves the summary
        import json
        import urllib.request

        with urllib.request.urlopen(a.http_addr + "/v1/metrics", timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert "Counters" in doc and "Gauges" in doc
        with urllib.request.urlopen(
            a.http_addr + "/v1/metrics?format=prometheus", timeout=10
        ) as r:
            text = r.read().decode()
        assert "nomad_worker_dequeue_eval" in text
    finally:
        a.shutdown()


class TestPushSinks:
    """statsd/statsite/DataDog push sinks (command/agent/command.go:976-
    1018 setupTelemetry fan-out)."""

    def _listener(self):
        import socket

        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        sock.settimeout(5.0)
        return sock

    def _recv_lines(self, sock, n):
        out = []
        for _ in range(n):
            data, _addr = sock.recvfrom(65535)
            out.append(data.decode())
        return out

    def test_statsd_line_protocol(self):
        from nomad_tpu.utils.metrics import StatsdSink

        sock = self._listener()
        try:
            sink = StatsdSink("127.0.0.1:%d" % sock.getsockname()[1],
                              prefix="nomad")
            sink.incr_counter("worker.dequeue", 2)
            sink.set_gauge("broker.depth", 7)
            sink.add_sample("plan.apply", 12.5)
            lines = sorted(self._recv_lines(sock, 3))
            assert "nomad.broker.depth:7|g" in lines
            assert "nomad.plan.apply:12.5|ms" in lines
            assert "nomad.worker.dequeue:2|c" in lines
            sink.close()
        finally:
            sock.close()

    def test_datadog_tags_suffix(self):
        from nomad_tpu.utils.metrics import StatsdSink

        sock = self._listener()
        try:
            sink = StatsdSink("127.0.0.1:%d" % sock.getsockname()[1],
                              datadog=True, tags={"role": "server", "dc": "dc1"})
            sink.incr_counter("evals", 1)
            (line,) = self._recv_lines(sock, 1)
            assert line == "evals:1|c|#dc:dc1,role:server"
            sink.close()
        finally:
            sock.close()

    def test_global_fanout_and_deregister(self):
        from nomad_tpu.utils import metrics

        sock = self._listener()
        sink = metrics.StatsdSink("127.0.0.1:%d" % sock.getsockname()[1])
        metrics.register_sink(sink)
        try:
            metrics.incr_counter("fanout.test", 3)
            (line,) = self._recv_lines(sock, 1)
            assert line == "fanout.test:3|c"
            # inmem sink still aggregates alongside
            summary = metrics.global_sink().summary()
            assert any(c["Name"] == "fanout.test"
                       for c in summary["Counters"])
        finally:
            metrics.deregister_sink(sink)
            sock.close()
        # after deregistration, emissions don't reach the socket (closed)
        metrics.incr_counter("fanout.test", 1)

    def test_agent_wires_sinks_from_config(self):
        import socket

        from nomad_tpu.agent.agent import Agent, AgentConfig
        from nomad_tpu.utils import metrics

        sock = self._listener()
        agent = Agent(AgentConfig(
            name="telemetry-1", gossip_enabled=False, num_schedulers=0,
            telemetry_statsd_address="127.0.0.1:%d" % sock.getsockname()[1],
            telemetry_prefix="nomad",
        ))
        try:
            agent.start()
            metrics.incr_counter("agent.test.metric", 1)
            data, _ = sock.recvfrom(65535)
            assert data.decode().startswith("nomad.agent.test.metric:1|c")
        finally:
            agent.shutdown()
            sock.close()
        assert not metrics._sinks  # sink deregistered at shutdown
