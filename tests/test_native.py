"""Native substrate tests: C++ segmented log, durable raft restore/snapshot,
and the executor-backed exec driver — reference raft-boltdb behavior and
drivers/shared/executor/executor_test.go scenarios."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.native.log import NativeLog
from nomad_tpu.server import InProcRaft, Server, ServerConfig
from nomad_tpu.server.fsm import JOB_REGISTER, NODE_REGISTER, NomadFSM


def test_native_log_roundtrip(tmp_path):
    d = str(tmp_path / "log")
    log = NativeLog(d, segment_bytes=512)
    for i in range(1, 51):
        log.append(i, f"payload-{i}".encode())
    log.sync()
    assert (log.first_index, log.last_index) == (1, 50)
    assert log.get(25) == b"payload-25"
    log.close()

    re = NativeLog(d, segment_bytes=512)
    assert (re.first_index, re.last_index) == (1, 50)
    assert re.get(50) == b"payload-50"
    re.close()


def test_native_log_truncation_survives_reopen(tmp_path):
    d = str(tmp_path / "log")
    log = NativeLog(d, segment_bytes=256)
    for i in range(1, 101):
        log.append(i, b"x" * 20)
    log.truncate_after(90)
    log.truncate_before(10)
    assert (log.first_index, log.last_index) == (10, 90)
    log.close()
    re = NativeLog(d, segment_bytes=256)
    assert (re.first_index, re.last_index) == (10, 90)
    assert re.get(5) is None and re.get(95) is None and re.get(50) is not None
    re.close()


def test_native_log_torn_write_recovery(tmp_path):
    d = str(tmp_path / "log")
    log = NativeLog(d)
    for i in range(1, 11):
        log.append(i, f"entry-{i}".encode())
    log.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".log"))
    with open(os.path.join(d, segs[-1]), "r+b") as f:
        f.seek(-2, 2)
        f.write(b"!!")
    re = NativeLog(d)
    assert re.last_index == 9  # torn tail record dropped
    assert re.get(9) == b"entry-9"
    re.close()


def test_durable_raft_restores_state(tmp_path):
    data_dir = str(tmp_path / "raft")
    raft = InProcRaft(data_dir=data_dir)
    fsm = NomadFSM()
    peer = raft.join(fsm)
    node = mock.node()
    job = mock.job()
    raft.apply(peer, NODE_REGISTER, node)
    raft.apply(peer, JOB_REGISTER, job)
    raft.close()

    # a fresh process replays the durable log
    raft2 = InProcRaft(data_dir=data_dir)
    fsm2 = NomadFSM()
    raft2.join(fsm2)
    assert fsm2.state.node_by_id(node.id) is not None
    assert fsm2.state.job_by_id(job.namespace, job.id) is not None
    assert raft2.last_index == 2
    raft2.close()


def test_durable_raft_snapshot_compacts(tmp_path):
    data_dir = str(tmp_path / "raft")
    raft = InProcRaft(data_dir=data_dir)
    fsm = NomadFSM()
    peer = raft.join(fsm)
    nodes = [mock.node() for _ in range(5)]
    for n in nodes:
        raft.apply(peer, NODE_REGISTER, n)
    snap_index = raft.snapshot(peer)
    assert snap_index == 5
    job = mock.job()
    raft.apply(peer, JOB_REGISTER, job)
    raft.close()

    raft2 = InProcRaft(data_dir=data_dir)
    fsm2 = NomadFSM()
    raft2.join(fsm2)
    # snapshot state + post-snapshot log tail both restored
    for n in nodes:
        assert fsm2.state.node_by_id(n.id) is not None
    assert fsm2.state.job_by_id(job.namespace, job.id) is not None
    # the log itself holds only the tail
    assert raft2.store.first_index == 6
    raft2.close()


def test_server_with_data_dir_survives_restart(tmp_path):
    data_dir = str(tmp_path / "server")
    raft = InProcRaft(data_dir=data_dir)
    s = Server(
        ServerConfig(num_schedulers=2, deterministic=True, scheduler_algorithm="binpack"),
        raft=raft,
    )
    s.start()
    try:
        for _ in range(3):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        s.register_job(job)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(s.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3:
                break
            time.sleep(0.05)
        allocs = s.fsm.state.allocs_by_job(job.namespace, job.id, True)
        assert len(allocs) == 3
    finally:
        s.stop()
        raft.close()

    raft2 = InProcRaft(data_dir=data_dir)
    s2 = Server(
        ServerConfig(num_schedulers=0, deterministic=True, scheduler_algorithm="binpack"),
        raft=raft2,
    )
    try:
        # full scheduling history restored: nodes, job, allocs
        assert len(s2.fsm.state.nodes()) == 3
        assert s2.fsm.state.job_by_id(job.namespace, job.id) is not None
        assert len(s2.fsm.state.allocs_by_job(job.namespace, job.id, True)) == 3
    finally:
        raft2.close()


# ---------------------------------------------------------------------------
# exec driver over the native executor
# ---------------------------------------------------------------------------


def test_exec_driver_runs_through_native_executor(tmp_path):
    from nomad_tpu.client.allocdir import AllocDir
    from nomad_tpu.client.drivers.base import TaskConfig, new_driver

    ad = AllocDir(str(tmp_path), "alloc1")
    ad.build()
    td = ad.new_task_dir("t")
    td.build()
    os.makedirs(td.log_dir, exist_ok=True)
    d = new_driver("exec")
    cfg = TaskConfig(
        id="t1", name="t",
        config={"command": "/bin/sh", "args": ["-c", "echo exec-$MARK"]},
        env={"MARK": "native", "PATH": "/usr/bin:/bin"},
        task_dir=td,
        stdout_path=os.path.join(td.log_dir, "t.stdout.0"),
    )
    handle = d.start_task(cfg)
    assert handle.driver_state["pid"] > 0
    res = d.wait_task("t1", timeout=10.0)
    assert res is not None and res.exit_code == 0
    with open(cfg.stdout_path) as f:
        assert f.read().strip() == "exec-native"
    d.destroy_task("t1")


def test_exec_driver_kill_escalation(tmp_path):
    from nomad_tpu.client.allocdir import AllocDir
    from nomad_tpu.client.drivers.base import TaskConfig, new_driver

    ad = AllocDir(str(tmp_path), "alloc2")
    ad.build()
    td = ad.new_task_dir("t")
    td.build()
    d = new_driver("exec")
    cfg = TaskConfig(
        id="t1", name="t",
        config={"command": "/bin/sh", "args": ["-c", "trap '' TERM; sleep 60"],
                "kill_timeout": 0.5},
        env={"PATH": "/usr/bin:/bin"},
        task_dir=td,
    )
    d.start_task(cfg)
    time.sleep(0.3)
    start = time.monotonic()
    d.stop_task("t1", timeout_s=1.0)
    res = d.wait_task("t1", timeout=10.0)
    assert time.monotonic() - start < 10.0
    assert res is not None and res.signal == 9  # escalated by the executor


def test_exec_driver_survives_client_restart(tmp_path):
    """The executor supervises independently: 'restart' the driver and
    recover the still-running task by pid."""
    from nomad_tpu.client.allocdir import AllocDir
    from nomad_tpu.client.drivers.base import TaskConfig, new_driver

    ad = AllocDir(str(tmp_path), "alloc3")
    ad.build()
    td = ad.new_task_dir("t")
    td.build()
    d = new_driver("exec")
    cfg = TaskConfig(
        id="t1", name="t",
        config={"command": "/bin/sleep", "args": ["60"]},
        env={"PATH": "/usr/bin:/bin"},
        task_dir=td,
    )
    handle = d.start_task(cfg)
    time.sleep(0.2)

    d2 = new_driver("exec")  # fresh driver instance = restarted client
    d2.recover_task(handle)
    status = d2.inspect_task("t1")
    assert status.state == "running"
    os.kill(handle.driver_state["pid"], 15)  # terminate the executor
    res = d2.wait_task("t1", timeout=10.0)
    assert res is not None
