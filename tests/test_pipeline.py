"""nomad-pipeline: the asynchronous eval-lifecycle pipeline.

Three layers:

  1. Unit coverage for the bounded-queue primitive and the wave-encode
     registry's eligibility gates.
  2. The overlap stress test: with the async applier owning commit+ack,
     a later wave's ENCODE must run while an earlier wave's DISPATCH
     stage is still open — the stage spans (nomad-trace) interleave
     instead of convoying.
  3. The OCC-retry storm: colliding dense plans force a partial commit;
     the re-dispatch path must reuse the wave's cached encode (zero
     fresh encode spans for the retried wave) and the broker must drain
     without stranding any eval past the applier's watchdog bound.
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import NODE_REGISTER
from nomad_tpu.structs.structs import Resources
from nomad_tpu.trace import lifecycle
from nomad_tpu.utils import metrics


def wait_for(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def counter(name):
    total = 0.0
    sink = metrics.global_sink()
    with sink._lock:
        for iv in sink._intervals:
            agg = iv.counters.get(name)
            if agg is not None:
                total += agg.sum
    return total


def dense_job(job_id, count=8, cpu=100, mem=128):
    j = mock.job()
    j.id = job_id
    j.task_groups[0].count = count
    j.task_groups[0].tasks[0].resources = Resources(cpu=cpu, memory_mb=mem)
    return j


def _register_nodes(server, n, cpu=4000, mem=8192):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.name = f"pipe-{i}"
        node.node_resources.cpu_shares = cpu
        node.node_resources.memory_mb = mem
        node.compute_class()
        server.raft_apply(NODE_REGISTER, node)
        nodes.append(node)
    return nodes


# ---------------------------------------------------------------------------
# 1. units
# ---------------------------------------------------------------------------


def test_bounded_stage_queue_is_bounded():
    from nomad_tpu.pipeline import BoundedStageQueue

    with pytest.raises(ValueError):
        BoundedStageQueue(0)
    q = BoundedStageQueue(2, name="t")
    q.put_nowait(1)
    q.put_nowait(2)
    assert q.depth() == 2
    with pytest.raises(Exception):  # queue.Full
        q.put_nowait(3)
    assert q.get_nowait() == 1
    assert q.get(timeout=0.1) == 2
    assert q.empty()


def test_wave_registry_caps_and_forgets():
    from nomad_tpu.pipeline.redispatch import _REGISTRY_CAP, WaveEncodeRegistry

    reg = WaveEncodeRegistry()
    for i in range(_REGISTRY_CAP + 10):
        reg.remember(f"e{i}", object(), object(), 1)
    assert len(reg) == _REGISTRY_CAP  # FIFO-evicted past the cap
    assert reg.get("e0") is None      # oldest gone
    assert reg.get(f"e{_REGISTRY_CAP + 9}") is not None
    reg.forget(f"e{_REGISTRY_CAP + 9}")
    assert reg.get(f"e{_REGISTRY_CAP + 9}") is None
    reg.clear()
    assert len(reg) == 0


def test_applier_rejects_non_dense_shapes():
    """try_submit must refuse any plan carrying object-path cargo — those
    results are inspected synchronously by the scheduler."""
    from nomad_tpu.pipeline import AsyncApplier
    from nomad_tpu.structs.structs import Plan

    applier = AsyncApplier(server=None)
    applier._enabled = True  # bypass the thread; shape checks come first
    # async_ok unset -> refused outright
    assert not applier.try_submit(Plan(eval_id="e1"), "tok")
    # async_ok but no dense placements -> refused
    assert not applier.try_submit(
        Plan(eval_id="e2", async_ok=True), "tok")
    # dense + a stopped alloc (node_update) -> refused
    p = Plan(eval_id="e3", async_ok=True,
             dense_placements=[object()])
    p.node_update["n1"] = [object()]
    assert not applier.try_submit(p, "tok")


# ---------------------------------------------------------------------------
# 2. overlap: a later wave encodes while an earlier wave's dispatch is open
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    lifecycle.reset()
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            device_batch=4, device_batch_window_ms=5.0,
                            device_min_placements=0))
    s.start()
    yield s
    s.stop()


def test_waves_overlap_instead_of_convoying(server):
    """Stage-span interleave: wave A parks in the DISPATCH stage (the
    batcher's gather window), wave B's ENCODE runs inside that window.
    Under the old convoying lifecycle the worker held the whole tail, so
    with the gather window saturating both workers this interleave is
    what the pipeline exists to produce."""
    _register_nodes(server, 6)
    # widen the gather window so wave A's dispatch stage is provably open
    # while wave B encodes (prod gets overlap from the adaptive gather)
    server.device_batcher.window_s = 1.0

    server.register_job(dense_job("overlap-a", count=8))
    time.sleep(0.15)  # A is now inside its dispatch gather window
    server.register_job(dense_job("overlap-b", count=8, cpu=150, mem=192))

    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 16,
             msg="16 placed")

    dispatches = lifecycle.pipeline_spans("dispatch")
    encodes = lifecycle.pipeline_spans("encode")
    assert dispatches and encodes
    interleaved = any(
        d_wave != e_wave and d_t0 <= e_t0 <= d_t1
        for _, d_wave, d_t0, d_t1 in dispatches
        for _, e_wave, e_t0, e_t1 in encodes
    )
    assert interleaved, (
        "no encode span started inside another wave's open dispatch span: "
        f"dispatch={dispatches} encode={encodes}"
    )
    # the waves went through the async applier, and every one was acked
    assert counter("nomad.worker.async_handoff") > 0
    wait_for(
        lambda: server.eval_broker.stats().get("total_unacked", 0) == 0,
        timeout=10.0, msg="broker drained",
    )
    # evaluate + commit stages were stamped by the applier-side path
    assert lifecycle.pipeline_spans("evaluate")
    assert lifecycle.pipeline_spans("commit")


# ---------------------------------------------------------------------------
# 3. OCC-retry storm: redispatch reuses the cached encode, nothing strands
# ---------------------------------------------------------------------------


def test_occ_retry_reuses_encode_and_never_strands(server):
    """Two same-shaped plans built from the same pre-commit snapshot
    collide on the binpack-preferred node (ring decorrelation off): the
    loser's wave takes the re-dispatch path. The retried wave must NOT
    re-encode (its encode span count stays 1 — the redispatcher patched
    the cached encode and re-entered the device stage directly), and the
    broker must drain inside the applier's watchdog bound."""
    # workers re-read ring_decorrelate from server.config on every eval,
    # so flipping it here makes both plans pick the SAME preferred node
    # (the empty-cluster tie-break is deterministic with ring_seed=0)
    server.config.ring_decorrelate = False
    _register_nodes(server, 2, cpu=4000, mem=8192)
    # widen the gather so both evals encode against the SAME empty-usage
    # snapshot and co-dispatch in one device batch
    server.device_batcher.window_s = 0.5

    # single-alloc plans sized so a node fits one but not two (2x2100 >
    # 4000): both waves pick the same node, the second wave's evaluate
    # loses the OCC race and its commit is partial (0 placed)
    server.register_job(dense_job("occ-a", count=1, cpu=2100, mem=256))
    server.register_job(dense_job("occ-b", count=1, cpu=2100, mem=256))

    wait_for(lambda: server.fsm.state.count_allocs_desired_run() == 2,
             timeout=60.0, msg="2 placed after OCC retry")

    # watchdog bound: nothing may sit unacked once placement converged
    wait_for(
        lambda: server.eval_broker.stats().get("total_unacked", 0) == 0,
        timeout=server.config.pipeline_ack_timeout_s + 5.0,
        msg="broker drained within the watchdog bound",
    )

    partials = counter("nomad.pipeline.partial_commit")
    if partials == 0:
        pytest.skip("plans did not collide on this run (no partial commit)")
    # the retry re-entered the DEVICE stage from the cached encode:
    # redispatch happened and reused the encode...
    assert counter("nomad.pipeline.redispatch") > 0
    assert counter("nomad.pipeline.redispatch_encode_reuse") > 0
    # ...and the retried wave minted NO fresh encode span: every wave
    # still has exactly one encode span, while at least one wave carries
    # a second dispatch span (the redispatch)
    enc_by_wave = {}
    for _, wave, _, _ in lifecycle.pipeline_spans("encode"):
        enc_by_wave[wave] = enc_by_wave.get(wave, 0) + 1
    assert enc_by_wave and all(n == 1 for n in enc_by_wave.values()), \
        f"retried wave re-encoded: {enc_by_wave}"
    disp_by_wave = {}
    for _, wave, _, _ in lifecycle.pipeline_spans("dispatch"):
        disp_by_wave[wave] = disp_by_wave.get(wave, 0) + 1
    assert any(n >= 2 for n in disp_by_wave.values()), \
        f"no wave re-entered the device stage: {disp_by_wave}"
    # the retried wave was acked, not watchdog-nacked
    assert counter("nomad.pipeline.acked") >= 2
