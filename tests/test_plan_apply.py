"""Pipelined plan applier (reference nomad/plan_apply.go:45–70).

Proves the two mechanisms the reference documents:
  1. OVERLAP — plan N+1 is evaluated while plan N's raft apply is still in
     flight (the applier thread never parks on raft latency).
  2. OPTIMISM — that evaluation runs against a snapshot which already
     includes plan N's results, so a conflicting N+1 is rejected (partial
     commit + refresh_index) even before N commits.
Plus the vectorized re-check semantics: over-capacity and down-node plans
are still rejected exactly as the sequential allocs_fit loop did.
"""
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.plan_apply import Planner, PlanQueue
from nomad_tpu.server.fsm import NODE_REGISTER, NomadFSM
from nomad_tpu.server.raft import InProcRaft
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Plan,
)


PLAN_APPLY_OPS = ("apply-plan-results", "apply-plan-results-batch")


class SlowRaft(InProcRaft):
    """Delays plan applies to widen the apply window; records timings."""

    def __init__(self, delay: float) -> None:
        super().__init__()
        self.delay = delay
        self.apply_windows = []  # (start, end) per plan apply
        self.apply_started = threading.Event()
        self._tlock = threading.Lock()

    def apply(self, peer, entry_type, payload):
        if entry_type in PLAN_APPLY_OPS:
            start = time.monotonic()
            self.apply_started.set()
            time.sleep(self.delay)
            out = super().apply(peer, entry_type, payload)
            with self._tlock:
                self.apply_windows.append((start, time.monotonic()))
            return out
        return super().apply(peer, entry_type, payload)


def make_alloc(job, node_id, cpu=500, mem=256, name_idx=0):
    a = Allocation(
        eval_id="eval-1",
        node_id=node_id,
        namespace="default",
        job_id=job.id,
        job=job,
        task_group=job.task_groups[0].name,
        name=f"{job.id}.{job.task_groups[0].name}[{name_idx}]",
        desired_status=ALLOC_DESIRED_RUN,
        client_status=ALLOC_CLIENT_PENDING,
        allocated_resources=AllocatedResources(
            tasks={"web": AllocatedTaskResources(cpu_shares=cpu, memory_mb=mem)},
            shared=AllocatedSharedResources(disk_mb=10),
        ),
    )
    return a


def harness(delay=0.0):
    raft = SlowRaft(delay)
    fsm = NomadFSM()
    peer = raft.join(fsm)
    queue = PlanQueue()
    queue.set_enabled(True)
    planner = Planner(raft, peer, fsm, queue)
    return raft, fsm, peer, queue, planner


class TestPipelinedApply:
    def test_evaluation_overlaps_inflight_apply(self):
        """With a slow raft, two queued plans' evaluations both happen
        before the FIRST apply completes — the applier pipelines instead
        of serializing evaluate->apply->evaluate."""
        raft, fsm, peer, queue, planner = harness(delay=0.4)
        node = mock.node()
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)

        eval_times = []
        orig_eval = planner.evaluate_plan

        def traced_eval(snap, plan):
            eval_times.append(time.monotonic())
            return orig_eval(snap, plan)

        planner.evaluate_plan = traced_eval
        planner.start()
        try:
            jobs = [mock.job(), mock.job()]
            pendings = []
            # stagger arrivals: plan 2 lands while plan 1's apply is in
            # flight, so it forms a second batch whose evaluation must
            # overlap that apply (the applier batches same-time arrivals
            # into one raft entry, which would make "overlap" vacuous)
            for i, job in enumerate(jobs):
                plan = Plan(eval_id=f"e{i}", priority=50, job=job)
                alloc = make_alloc(job, node.id, cpu=100, mem=64, name_idx=i)
                plan.node_allocation = {node.id: [alloc]}
                pendings.append(queue.enqueue(plan))
                if i == 0:
                    assert raft.apply_started.wait(timeout=10)

            results = [p.future.result(timeout=10) for p in pendings]
            assert all(r.node_allocation for r in results)
            assert len(eval_times) == 2
            first_apply_end = raft.apply_windows[0][1]
            # the second evaluation started BEFORE the first apply finished
            assert eval_times[1] < first_apply_end, (
                f"no overlap: eval2 at {eval_times[1]}, "
                f"apply1 ended {first_apply_end}"
            )
            # both plans committed
            assert len(fsm.state.allocs()) == 2
        finally:
            planner.stop()

    def test_optimistic_snapshot_rejects_conflicting_followup(self):
        """Plan B conflicts with in-flight plan A (together they exceed the
        node): B must be rejected against the OPTIMISTIC view including A,
        before A even commits."""
        raft, fsm, peer, queue, planner = harness(delay=0.4)
        node = mock.node()
        node.node_resources.cpu_shares = 1000
        node.node_resources.memory_mb = 1000
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)

        planner.start()
        try:
            job_a, job_b = mock.job(), mock.job()
            plan_a = Plan(eval_id="ea", priority=50, job=job_a)
            plan_a.node_allocation = {
                node.id: [make_alloc(job_a, node.id, cpu=700, mem=700)]
            }
            plan_b = Plan(eval_id="eb", priority=50, job=job_b)
            plan_b.node_allocation = {
                node.id: [make_alloc(job_b, node.id, cpu=700, mem=700)]
            }
            pa = queue.enqueue(plan_a)
            pb = queue.enqueue(plan_b)
            ra = pa.future.result(timeout=10)
            rb = pb.future.result(timeout=10)
            assert ra.node_allocation, "plan A should commit"
            assert not rb.node_allocation, "plan B must be rejected"
            assert rb.refresh_index > 0, "worker must be told to re-plan"
            assert len(fsm.state.allocs()) == 1
        finally:
            planner.stop()

    def test_down_node_and_overcapacity_rejected(self):
        """Vectorized re-check parity: plans for down nodes and plans that
        exceed capacity are rejected; fitting nodes commit (partial)."""
        raft, fsm, peer, queue, planner = harness(delay=0.0)
        good = mock.node()
        good.compute_class()
        down = mock.node()
        down.status = "down"
        down.compute_class()
        small = mock.node()
        small.node_resources.cpu_shares = 100
        small.node_resources.memory_mb = 64
        small.compute_class()
        for n in (good, down, small):
            raft.apply(peer, NODE_REGISTER, n)

        planner.start()
        try:
            job = mock.job()
            plan = Plan(eval_id="e", priority=50, job=job)
            plan.node_allocation = {
                good.id: [make_alloc(job, good.id, cpu=100, mem=64, name_idx=0)],
                down.id: [make_alloc(job, down.id, cpu=100, mem=64, name_idx=1)],
                small.id: [make_alloc(job, small.id, cpu=900, mem=900, name_idx=2)],
            }
            pending = queue.enqueue(plan)
            result = pending.future.result(timeout=10)
            assert set(result.node_allocation) == {good.id}
            assert result.refresh_index > 0
            allocs = fsm.state.allocs()
            assert len(allocs) == 1 and allocs[0].node_id == good.id
        finally:
            planner.stop()

    def test_port_collision_rejected_after_capacity_pass(self):
        """The discrete port check still runs for capacity-passing nodes:
        two allocs claiming the same static port on one node reject."""
        from nomad_tpu.structs.structs import NetworkResource, Port

        raft, fsm, peer, queue, planner = harness(delay=0.0)
        node = mock.node()
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)

        planner.start()
        try:
            job = mock.job()
            allocs = []
            for i in range(2):
                a = make_alloc(job, node.id, cpu=100, mem=64, name_idx=i)
                a.allocated_resources.tasks["web"].networks = [NetworkResource(
                    device="eth0", ip="192.168.0.100", mbits=10,
                    reserved_ports=[Port(label="http", value=8080)],
                )]
                allocs.append(a)
            plan = Plan(eval_id="e", priority=50, job=job)
            plan.node_allocation = {node.id: allocs}
            pending = queue.enqueue(plan)
            result = pending.future.result(timeout=10)
            assert not result.node_allocation, "port collision must reject"
        finally:
            planner.stop()

    def test_stale_snapshot_reevaluates_after_inflight_commit(self):
        """If plan B's evaluation snapshot was forced fresh (its
        snapshot_index outran the optimistic view) it is blind to
        in-flight plan A — B must be RE-evaluated once A commits, so a
        conflict still rejects instead of double-committing capacity."""
        raft, fsm, peer, queue, planner = harness(delay=0.5)
        node = mock.node()
        node.node_resources.cpu_shares = 1000
        node.node_resources.memory_mb = 1000
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)

        planner.start()
        try:
            job_a, job_b = mock.job(), mock.job()
            plan_a = Plan(eval_id="ea", priority=50, job=job_a)
            plan_a.node_allocation = {
                node.id: [make_alloc(job_a, node.id, cpu=700, mem=700)]
            }
            pa = queue.enqueue(plan_a)
            time.sleep(0.1)  # A dequeued + dispatched (0.5s apply window)
            # unrelated raft writes advance committed state past A's guess
            for _ in range(3):
                raft.apply(peer, NODE_REGISTER, mock.node())
            plan_b = Plan(eval_id="eb", priority=50, job=job_b)
            # B's worker saw the newest committed index -> the applier's
            # retained optimistic snapshot is deemed stale
            plan_b.snapshot_index = fsm.state.latest_index
            plan_b.node_allocation = {
                node.id: [make_alloc(job_b, node.id, cpu=700, mem=700)]
            }
            pb = queue.enqueue(plan_b)
            ra = pa.future.result(timeout=10)
            rb = pb.future.result(timeout=10)
            assert ra.node_allocation, "plan A should commit"
            assert not rb.node_allocation, (
                "plan B must be re-evaluated against committed A and rejected"
            )
            on_node = [a for a in fsm.state.allocs() if a.node_id == node.id]
            assert len(on_node) == 1, "no double-commit on the full node"
        finally:
            planner.stop()

    def test_failed_apply_revalidates_follow_up(self):
        """If in-flight plan A's raft apply FAILS, plan B — evaluated
        against the optimistic view that assumed A landed — must be
        re-evaluated against committed state before dispatch. Here A would
        have filled the node; A fails, so B must succeed."""
        class FailFirstRaft(SlowRaft):
            def __init__(self, delay):
                super().__init__(delay)
                self.failed_once = False

            def apply(self, peer, entry_type, payload):
                if entry_type in PLAN_APPLY_OPS and not self.failed_once:
                    self.failed_once = True
                    self.apply_started.set()
                    time.sleep(self.delay)
                    raise RuntimeError("injected apply failure")
                return super().apply(peer, entry_type, payload)

        raft = FailFirstRaft(0.4)
        fsm = NomadFSM()
        peer = raft.join(fsm)
        from nomad_tpu.server.plan_apply import PlanQueue, Planner

        queue = PlanQueue()
        queue.set_enabled(True)
        planner = Planner(raft, peer, fsm, queue)
        node = mock.node()
        node.node_resources.cpu_shares = 1000
        node.node_resources.memory_mb = 1000
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)

        planner.start()
        try:
            job_a, job_b = mock.job(), mock.job()
            plan_a = Plan(eval_id="ea", priority=50, job=job_a)
            plan_a.node_allocation = {
                node.id: [make_alloc(job_a, node.id, cpu=700, mem=700)]
            }
            plan_b = Plan(eval_id="eb", priority=50, job=job_b)
            plan_b.node_allocation = {
                node.id: [make_alloc(job_b, node.id, cpu=700, mem=700)]
            }
            pa = queue.enqueue(plan_a)
            # B arrives while A's (failing) apply is in flight — a later
            # batch, so only A is poisoned by the injected failure
            assert raft.apply_started.wait(timeout=10)
            pb = queue.enqueue(plan_b)
            with pytest.raises(Exception):
                pa.future.result(timeout=10)
            rb = pb.future.result(timeout=10)
            if not rb.node_allocation:
                # B was fully rejected via the noop fast-path before A's
                # failure was known: the worker re-plans at refresh_index
                # (reference semantics). The retry must commit.
                assert rb.refresh_index > 0
                retry = Plan(eval_id="eb2", priority=50, job=job_b)
                retry.snapshot_index = rb.refresh_index
                retry.node_allocation = {
                    node.id: [make_alloc(job_b, node.id, cpu=700, mem=700)]
                }
                rb = queue.enqueue(retry).future.result(timeout=10)
            assert rb.node_allocation, (
                "plan B must commit: A never landed, so the capacity is free"
            )
            on_node = [a for a in fsm.state.allocs() if a.node_id == node.id]
            assert len(on_node) == 1
            assert on_node[0].job_id == job_b.id
        finally:
            planner.stop()

    def test_pipelined_throughput_exceeds_serial(self):
        """K plans against a slow raft drain in ~K*delay (applies are
        serialized) but NOT ~K*(delay+eval): evaluation cost rides inside
        apply windows. Sanity-bound wall time."""
        raft, fsm, peer, queue, planner = harness(delay=0.15)
        node = mock.node()
        node.node_resources.cpu_shares = 100000
        node.node_resources.memory_mb = 100000
        node.compute_class()
        raft.apply(peer, NODE_REGISTER, node)
        planner.start()
        try:
            k = 5
            start = time.monotonic()
            pendings = []
            for i in range(k):
                job = mock.job()
                plan = Plan(eval_id=f"e{i}", priority=50, job=job)
                plan.node_allocation = {
                    node.id: [make_alloc(job, node.id, cpu=10, mem=8, name_idx=i)]
                }
                pendings.append(queue.enqueue(plan))
            for p in pendings:
                assert p.future.result(timeout=20).node_allocation
            elapsed = time.monotonic() - start
            # serial lower bound is k*delay; generous upper bound shows we
            # are not paying extra serialization on top of it
            assert elapsed < k * 0.15 + 1.0, f"drained in {elapsed:.2f}s"
            assert len(fsm.state.allocs()) == k
        finally:
            planner.stop()
