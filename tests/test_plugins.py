"""Plugin system tests: out-of-process drivers/devices over unix sockets.

Covers the go-plugin slot (reference plugins/base, plugins/drivers,
plugins/device, helper/pluginutils): subprocess handshake, full driver
lifecycle across the process boundary, concurrent blocking calls, shared
instances, config schemas, catalog discovery, and crash handling.
"""
import os
import stat
import sys
import threading
import time

import pytest

from nomad_tpu.client.drivers.base import DriverError, TaskConfig, new_driver
from nomad_tpu.plugins.base import PLUGIN_TYPE_DEVICE, PLUGIN_TYPE_DRIVER, validate_config
from nomad_tpu.plugins.catalog import (
    Catalog,
    launch_builtin_driver,
    register_external_driver,
    shutdown_external_instances,
)
from nomad_tpu.plugins.transport import PluginError, spawn_plugin


@pytest.fixture
def ext_mock():
    drv = launch_builtin_driver("mock")
    yield drv
    drv.close()


class TestExternalDriver:
    def test_handshake_and_info(self, ext_mock):
        info = ext_mock.plugin_info()
        assert info.type == PLUGIN_TYPE_DRIVER
        assert info.name == "mock"
        assert ext_mock.capabilities.send_signals is True

    def test_full_task_lifecycle_across_process(self, ext_mock):
        cfg = TaskConfig(id="t1", name="web",
                         config={"run_for": "200ms", "exit_code": 3})
        handle = ext_mock.start_task(cfg)
        assert handle.driver == "mock" and handle.state == "running"
        status = ext_mock.inspect_task("t1")
        assert status.state in ("running", "exited")
        res = ext_mock.wait_task("t1", timeout=5.0)
        assert res is not None and res.exit_code == 3
        assert ext_mock.inspect_task("t1").state == "exited"
        ext_mock.destroy_task("t1")
        with pytest.raises(DriverError):
            ext_mock.inspect_task("t1")

    def test_concurrent_wait_and_stop(self, ext_mock):
        """wait_task blocks in the plugin while stop_task lands on another
        pooled connection — the go-plugin concurrency property."""
        ext_mock.start_task(TaskConfig(id="t2", name="w",
                                       config={"run_for": "30s"}))
        results = {}

        def waiter():
            results["res"] = ext_mock.wait_task("t2", timeout=10.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        ext_mock.stop_task("t2", timeout_s=2.0)
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert results["res"] is not None and results["res"].signal == 15

    def test_driver_error_crosses_boundary(self, ext_mock):
        with pytest.raises(DriverError, match="boom"):
            ext_mock.start_task(TaskConfig(id="t3", name="w",
                                           config={"start_error": "boom"}))

    def test_plugin_crash_surfaces_as_driver_error(self):
        drv = launch_builtin_driver("mock")
        drv.client.process.kill()
        drv.client.process.wait(timeout=5)
        with pytest.raises(DriverError):
            drv.start_task(TaskConfig(id="t4", name="w", config={}))
        drv.close()

    def test_registered_external_driver_is_shared(self):
        register_external_driver("mock")
        try:
            a = new_driver("mock")
            b = new_driver("mock")
            assert a is b, "one subprocess instance shared across tasks"
            a.start_task(TaskConfig(id="s1", name="w", config={"run_for": 0}))
            assert b.wait_task("s1", timeout=5.0) is not None
        finally:
            shutdown_external_instances()
            # restore the in-process registration for other tests
            from nomad_tpu.client.drivers.mock_driver import MockDriver, register
            register("mock", MockDriver)


class TestDevicePlugin:
    @pytest.fixture
    def ext_device(self):
        from nomad_tpu.plugins.catalog import _plugin_env
        from nomad_tpu.plugins.device import ExternalDevicePlugin

        client = spawn_plugin(
            [sys.executable, "-m", "nomad_tpu.plugins.launch",
             "device", "nomad_tpu.plugins.mock_device:plugin"],
            env=_plugin_env(),
        )
        dev = ExternalDevicePlugin("mock-device", client)
        yield dev
        dev.close()

    def test_fingerprint_reserve_stats(self, ext_device):
        info = ext_device.client.call("plugin_info", timeout=5.0)
        assert info.type == PLUGIN_TYPE_DEVICE
        groups = ext_device.fingerprint()
        assert len(groups) == 1
        g = groups[0]
        assert (g.vendor, g.type, g.name) == ("nomad", "gpu", "mock")
        assert [d.id for d in g.devices] == ["mock-0", "mock-1"]
        res = ext_device.reserve(["mock-1"])
        assert res.envs == {"MOCK_VISIBLE_DEVICES": "mock-1"}
        stats = ext_device.stats()
        assert set(stats.instance_stats) == {"mock-0", "mock-1"}

    def test_reserve_unknown_device_errors(self, ext_device):
        with pytest.raises(PluginError, match="unknown device"):
            ext_device.reserve(["nope-9"])

    def test_set_config_changes_fingerprint(self, ext_device):
        ext_device.client.call("set_config", {"model": "tpu", "count": 4}, timeout=5.0)
        groups = ext_device.fingerprint()
        assert len(groups[0].devices) == 4
        assert groups[0].name == "tpu"


class TestCatalog:
    def test_discovery_launches_executables(self, tmp_path):
        script = tmp_path / "nomad-driver-extmock"
        script.write_text(
            "#!/bin/sh\nexec {} -m nomad_tpu.plugins.launch driver mock\n".format(sys.executable)
        )
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        (tmp_path / "ignored.txt").write_text("not a plugin")
        cat = Catalog(str(tmp_path)).discover()
        try:
            assert list(cat.drivers) == ["mock"]
            drv = cat.drivers["mock"]
            drv.start_task(TaskConfig(id="c1", name="w", config={"run_for": 0}))
            assert drv.wait_task("c1", timeout=5.0) is not None
        finally:
            cat.close()
            from nomad_tpu.client.drivers.mock_driver import MockDriver, register
            register("mock", MockDriver)


class TestConfigSchema:
    def test_validate_config(self):
        schema = {"endpoint": {"type": "string", "required": True},
                  "gc": {"type": "bool"}}
        assert validate_config(schema, {"endpoint": "unix:///x"}) == []
        errs = validate_config(schema, {"gc": "yes"})
        assert any("required" in e for e in errs)
        assert any("expected bool" in e for e in errs)
        assert any("unknown field" in e
                   for e in validate_config(schema, {"endpoint": "x", "zz": 1}))


class TestHclSpec:
    """Schema-as-data decoding (plugins/hclspec.py — the reference's
    plugins/shared/hclspec protocol slot)."""

    def test_attrs_defaults_and_nested_blocks(self):
        from nomad_tpu.plugins.hclspec import decode

        spec = {"block": {"spec": {
            "image": {"attr": {"type": "string", "required": True}},
            "gc": {"default": {
                "primary": {"block": {"spec": {
                    "enabled": {"attr": {"type": "bool"}},
                    "interval": {"default": {
                        "primary": {"attr": {"type": "number"}},
                        "default": 60,
                    }},
                }}},
                "default": {"enabled": True, "interval": 60},
            }},
            "mounts": {"block_list": {"spec": {
                "source": {"attr": {"type": "string", "required": True}},
                "readonly": {"attr": {"type": "bool"}},
            }}},
            "labels": {"attr": {"type": "map(string)"}},
            "args": {"attr": {"type": "list(string)"}},
        }}}
        decoded, errors = decode(spec, {
            "image": "redis:7",
            "gc": {"enabled": False},
            "mounts": [{"source": "/data", "readonly": True}],
            "labels": {"team": "core"},
            "args": ["-v"],
        })
        assert errors == []
        assert decoded["gc"]["interval"] == 60  # default applied
        assert decoded["gc"]["enabled"] is False
        assert decoded["mounts"][0]["source"] == "/data"

    def test_type_errors_and_unknown_fields(self):
        from nomad_tpu.plugins.hclspec import decode

        spec = {"block": {"spec": {
            "count": {"attr": {"type": "number"}},
            "names": {"attr": {"type": "list(string)"}},
        }}}
        _, errors = decode(spec, {"count": "three", "names": [1], "bogus": 1})
        assert any("expected number" in e for e in errors)
        assert any("expected string" in e for e in errors)
        assert any("unknown field" in e for e in errors)

    def test_block_list_and_literal(self):
        from nomad_tpu.plugins.hclspec import decode

        spec = {"block": {"spec": {
            "version": {"literal": {"value": 2}},
            "ports": {"block_list": {"spec": {
                "label": {"attr": {"type": "string", "required": True}},
            }}},
        }}}
        decoded, errors = decode(spec, {"ports": [{"label": "http"}, {}]})
        assert decoded["version"] == 2
        assert any("required" in e for e in errors)  # second port missing label

    def test_bool_not_admitted_as_number(self):
        from nomad_tpu.plugins.hclspec import decode

        spec = {"block": {"spec": {"n": {"attr": {"type": "number"}}}}}
        _, errors = decode(spec, {"n": True})
        assert errors


class TestClientWithExternalDriver:
    def test_alloc_runs_through_subprocess_driver(self):
        """Full client path — alloc runner → task runner → driver — with
        the driver out-of-process (the reference's production topology)."""
        import time as _time

        from nomad_tpu import mock
        from nomad_tpu.client.client import Client, ClientConfig, ServerProxy
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_schedulers=1, heartbeat_min_ttl=60,
                                     heartbeat_max_ttl=60))
        server.start()
        client = Client(
            ServerProxy(server),
            ClientConfig(external_drivers={"mock": {}}),
        )
        try:
            client.start()
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].driver = "mock"
            job.task_groups[0].tasks[0].config = {"run_for": "30s"}
            server.register_job(job)
            deadline = _time.monotonic() + 30
            while _time.monotonic() < deadline:
                allocs = server.fsm.state.allocs_by_job("default", job.id, True)
                if allocs and allocs[0].client_status == "running":
                    break
                _time.sleep(0.2)
            else:
                raise AssertionError("alloc never reached running via external driver")
            from nomad_tpu.plugins.driver_plugin import ExternalDriver
            drv = client.resolve_driver("mock")
            assert isinstance(drv, ExternalDriver), "client-owned subprocess driver"
            # the global registry is untouched: another client in this
            # process still gets the in-process driver
            from nomad_tpu.client.drivers.mock_driver import MockDriver
            assert isinstance(new_driver("mock"), MockDriver)
        finally:
            client.shutdown()
            server.stop()
