"""Device preemption kernels (tpu/preempt.py) vs the pure-Python spec.

The parity claim: eviction-set construction is an exact integer program
(int32/int64 add/mul/shift/compare only), so the device kernels produce
BIT-IDENTICAL selections to ``select_eviction_set_py`` on every backend.
These tests fuzz the kernels directly against the oracle — the e2e plan
parity lives in tests/test_tpu_parity.py::TestPreemptionParity.
"""
import math
import random

import numpy as np
import pytest

from nomad_tpu.tpu import preempt


@pytest.fixture(autouse=True)
def _x64():
    import jax

    jax.config.update("jax_enable_x64", True)


def test_isqrt_exact():
    rng = np.random.default_rng(11)
    xs = np.concatenate([
        rng.integers(0, 1 << 62, 2000, dtype=np.int64),
        # domain is n < 2**62 (the engine bounds sum-of-squares below
        # 3 * 2**60); (1<<31)**2 == 2**62 itself is out of range
        np.array([0, 1, 2, 3, 4, (1 << 62) - 1,
                  (1 << 31) ** 2 - 1, ((1 << 31) - 1) ** 2], np.int64),
    ])
    import jax.numpy as jnp

    got = np.asarray(preempt.isqrt_jnp(jnp.asarray(xs)))
    want = np.array([math.isqrt(int(x)) for x in xs], np.int64)
    assert (got == want).all()


def test_coord_q_matches_py():
    rng = np.random.default_rng(12)
    needed = rng.integers(-(1 << 28), 1 << 28, 3000, dtype=np.int64)
    res = rng.integers(0, 1 << 28, 3000, dtype=np.int64)
    import jax.numpy as jnp

    got = np.asarray(preempt.coord_q_jnp(jnp.asarray(needed), jnp.asarray(res)))
    want = np.array(
        [preempt.coord_q_py(int(n), int(r)) for n, r in zip(needed, res)],
        np.int64,
    )
    assert (got == want).all()


def _device_eviction_set(ask3, remaining3, res3, prio, pen, elig):
    """Run the two kernels the way engine._make_step composes them for
    one node row; return final-order candidate indices or None."""
    import jax.numpy as jnp

    n, c = res3.shape[0], res3.shape[1]
    sel_ord, met = preempt.greedy_select_jnp(
        jnp.asarray(ask3, jnp.int64),
        jnp.asarray(res3, jnp.int64),
        jnp.asarray(prio, jnp.int32),
        jnp.asarray(pen, jnp.int64),
        jnp.asarray(elig, bool),
        jnp.asarray(remaining3, jnp.int64),
    )
    sel_ord = np.asarray(sel_ord)
    met = np.asarray(met)
    out = []
    for ni in range(n):
        if not met[ni]:
            out.append(None)
            continue
        keep, rank = preempt.second_pass_jnp(
            jnp.asarray(ask3, jnp.int64),
            jnp.asarray(res3[ni], jnp.int64),
            jnp.asarray(sel_ord[ni], jnp.int32),
            jnp.asarray(remaining3[ni], jnp.int64),
        )
        keep = np.asarray(keep)
        rank = np.asarray(rank)
        ks = [int(i) for i in range(c) if keep[i]]
        ks.sort(key=lambda i: int(rank[i]))
        out.append(ks)
    return out


def test_eviction_set_fuzz_matches_py_oracle():
    """Randomized candidate tables: greedy sweep + second-pass filter on
    the device kernels must reproduce select_eviction_set_py exactly —
    same victims, same final order, same unmet nodes."""
    rng = random.Random(99)
    for trial in range(30):
        n = rng.randint(1, 8)
        c = rng.randint(1, preempt.C_MAX)
        ask3 = [rng.randint(1, 1 << 20) for _ in range(3)]
        res3 = np.array(
            [[[rng.randint(0, 1 << 18) for _ in range(3)] for _ in range(c)]
             for _ in range(n)], np.int64)
        prio = np.array(
            [[rng.choice([10, 20, 20, 30, 40]) for _ in range(c)]
             for _ in range(n)], np.int32)
        pen = np.array(
            [[preempt.penalty_q_py(rng.choice([0, 0, 1, 2]),
                                   rng.choice([0, 1, 2]))
              for _ in range(c)] for _ in range(n)], np.int64)
        elig = np.array(
            [[rng.random() < 0.8 for _ in range(c)] for _ in range(n)], bool)
        # remaining can be negative (node oversubscribed after
        # subtracting every candidate) — the common preemption shape
        remaining3 = np.array(
            [[rng.randint(-(1 << 19), 1 << 19) for _ in range(3)]
             for _ in range(n)], np.int64)

        got = _device_eviction_set(ask3, remaining3, res3, prio, pen, elig)
        for ni in range(n):
            want = preempt.select_eviction_set_py(
                ask3, remaining3[ni], res3[ni], prio[ni], pen[ni], elig[ni])
            assert got[ni] == want, (
                f"trial {trial} node {ni}: device eviction set diverged "
                f"from the int spec\n got={got[ni]}\nwant={want}"
            )


def test_eviction_degenerate_shapes():
    """Edge rows the fuzz may miss: nothing eligible, ask already met by
    one candidate, and exact-tie distances falling to greedy order."""
    # no eligible candidates -> unmet
    got = _device_eviction_set(
        [100, 100, 100], np.array([[0, 0, 0]], np.int64),
        np.array([[[50, 50, 50], [60, 60, 60]]], np.int64),
        np.array([[10, 10]], np.int32), np.zeros((1, 2), np.int64),
        np.array([[False, False]], bool))
    assert got == [None]
    # identical candidates: first occurrence wins every greedy round and
    # ties keep greedy order in the second pass
    res3 = np.array([[[40, 40, 40]] * 4], np.int64)
    got = _device_eviction_set(
        [100, 100, 100], np.array([[0, 0, 0]], np.int64), res3,
        np.full((1, 4), 20, np.int32), np.zeros((1, 4), np.int64),
        np.ones((1, 4), bool))
    want = preempt.select_eviction_set_py(
        [100, 100, 100], [0, 0, 0], res3[0], [20] * 4, [0] * 4, [True] * 4)
    assert got[0] == want
