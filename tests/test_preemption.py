"""Preemption tests, mirroring reference scheduler/preemption_test.go.

Table-driven cases run through the full BinPackIterator(evict=True) path —
the same entry the schedulers use — covering TG (cpu/mem/disk), network
(bandwidth + static ports) and device preemption, distance metrics, the
maxParallel penalty and the superset filter, plus an end-to-end system-job
preemption scenario against a running server.
"""
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.preemption import (
    MAX_PARALLEL_PENALTY,
    basic_resource_distance,
    network_resource_distance,
    score_for_task_group,
)
from nomad_tpu.scheduler.rank import BinPackIterator, RankedNode, StaticRankIterator
from nomad_tpu.state import StateStore
from nomad_tpu.structs.structs import (
    AllocatedDeviceResource,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    ComparableResources,
    NetworkResource,
    NodeDeviceInstance,
    NodeDeviceResource,
    NodeReservedResources,
    NodeResources,
    Port,
    RequestedDevice,
    Resources,
    TaskGroup,
    generate_uuid,
)

WEB = "web"


def comparable(cpu=0, mem=0, disk=0, networks=()):
    c = ComparableResources()
    c.flattened.cpu_shares = cpu
    c.flattened.memory_mb = mem
    c.flattened.networks = list(networks)
    c.shared.disk_mb = disk
    return c


class TestResourceDistance:
    """Mirrors TestResourceDistance (preemption_test.go:16) — identical
    asks/allocs, identical expected distances to 3 decimals."""

    ASK = comparable(cpu=2048, mem=512, disk=4096,
                     networks=[NetworkResource(device="eth0", mbits=1024)])

    @pytest.mark.parametrize("alloc_res,expected", [
        (comparable(2048, 512, 4096, [NetworkResource(device="eth0", mbits=1024)]), "0.000"),
        (comparable(1024, 400, 1024, [NetworkResource(device="eth0", mbits=1024)]), "0.928"),
        (comparable(8192, 200, 1024, [NetworkResource(device="eth0", mbits=512)]), "3.152"),
        (comparable(2048, 500, 4096, [NetworkResource(device="eth0", mbits=1024)]), "0.023"),
    ])
    def test_distance(self, alloc_res, expected):
        assert f"{basic_resource_distance(self.ASK, alloc_res):.3f}" == expected

    def test_network_distance(self):
        used = NetworkResource(device="eth0", mbits=1024)
        need = NetworkResource(device="eth0", mbits=1024)
        assert network_resource_distance(used, need) == 0.0
        need2 = NetworkResource(device="eth0", mbits=512)
        assert network_resource_distance(used, need2) == 1.0
        assert network_resource_distance(None, need) == float("inf")
        assert network_resource_distance(used, NetworkResource(mbits=0)) == float("inf")

    def test_max_parallel_penalty(self):
        ask = comparable(100, 100, 100)
        used = comparable(100, 100, 100)
        base = score_for_task_group(ask, used, max_parallel=0, num_preempted=5)
        assert base == 0.0
        # at/over the limit: +50 per excess eviction
        assert score_for_task_group(ask, used, 2, 2) == MAX_PARALLEL_PENALTY
        assert score_for_task_group(ask, used, 2, 3) == 2 * MAX_PARALLEL_PENALTY


# ---------------------------------------------------------------------------
# Table cases through BinPackIterator(evict=True) — preemption_test.go:144
# ---------------------------------------------------------------------------


def make_job(priority):
    j = mock.job()
    j.priority = priority
    return j


def create_alloc(alloc_id, job, cpu, mem, disk, networks=None, devices=None,
                 tg_network=None):
    """preemption_test.go createAllocInner equivalent."""
    tr = AllocatedTaskResources(cpu_shares=cpu, memory_mb=mem,
                                networks=list(networks or []))
    if devices is not None:
        tr.devices = [devices]
    shared = AllocatedSharedResources(disk_mb=disk)
    if tg_network is not None:
        shared.networks = [tg_network]
    return Allocation(
        id=alloc_id,
        job=job,
        job_id=job.id,
        namespace="default",
        eval_id=generate_uuid(),
        desired_status="run",
        client_status="running",
        task_group=WEB,
        allocated_resources=AllocatedResources(tasks={WEB: tr}, shared=shared),
    )


def default_node_resources():
    return NodeResources(
        cpu_shares=4000,
        memory_mb=8192,
        disk_mb=100 * 1024,
        networks=[NetworkResource(device="eth0", cidr="192.168.0.100/32",
                                  ip="192.168.0.100", mbits=1000)],
        devices=[
            NodeDeviceResource(
                vendor="nvidia", type="gpu", name="1080ti",
                instances=[NodeDeviceInstance(id=f"dev{i}") for i in range(4)],
            ),
            NodeDeviceResource(
                vendor="nvidia", type="gpu", name="2080ti",
                instances=[NodeDeviceInstance(id=f"dev{i}") for i in range(4, 9)],
            ),
            NodeDeviceResource(
                vendor="intel", type="fpga", name="F100",
                instances=[NodeDeviceInstance(id="fpga1"),
                           NodeDeviceInstance(id="fpga2", healthy=False)],
            ),
        ],
    )


RESERVED = NodeReservedResources(cpu_shares=100, memory_mb=256, disk_mb=4 * 1024)


def run_case(current_allocs, job_priority, ask_resources, node_resources=None,
             reserved=RESERVED, current_preemptions=None, devices=None):
    """Run one table case through BinPackIterator(evict=True); returns the
    selected option (or None) — preemption_test.go:1327 runner."""
    node = mock.node()
    node.node_resources = node_resources or default_node_resources()
    node.reserved_resources = reserved
    node.compute_class()

    state = StateStore()
    state.upsert_node(1000, node)
    for alloc in current_allocs:
        alloc.node_id = node.id
    state.upsert_allocs(1001, current_allocs)

    job = make_job(job_priority)
    ev = mock.eval()
    plan = ev.make_plan(job)
    ctx = EvalContext(state, plan, deterministic=True)
    if current_preemptions:
        ctx.plan.node_preemptions[node.id] = current_preemptions

    static = StaticRankIterator(ctx, [RankedNode(node)])
    it = BinPackIterator(ctx, static, True, job_priority)
    it.set_job(job)

    import copy as _copy

    tg = TaskGroup(name=WEB)
    tg.tasks = [_copy.deepcopy(mock.job().task_groups[0].tasks[0])]
    tg.tasks[0].name = WEB
    tg.tasks[0].resources = ask_resources
    if devices:
        tg.tasks[0].resources.devices = devices
    it.set_task_group(tg)
    return it.next()


def assert_preempted(option, expected_ids):
    if expected_ids is None:
        assert option is None, "expected no feasible option"
        return
    assert option is not None, "expected a feasible option with preemption"
    got = {a.id for a in (option.preempted_allocs or [])}
    assert got == set(expected_ids)


A = [generate_uuid() for _ in range(6)]
HIGH = make_job(100)
LOW = make_job(30)
LOW2 = make_job(40)


def ask(cpu, mem, disk, networks=None):
    r = Resources(cpu=cpu, memory_mb=mem)
    r.disk_mb = disk
    if networks:
        r.networks = networks
    return r


class TestPreemptionTable:
    def test_no_preemption_high_priority_existing(self):
        """No preemption because existing allocs are not low priority."""
        allocs = [create_alloc(A[0], HIGH, 3200, 7256, 4 * 1024,
                               [NetworkResource(device="eth0", ip="192.168.0.100", mbits=50)])]
        option = run_case(allocs, 100, ask(
            2000, 256, 4 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=1,
                             reserved_ports=[Port("ssh", 22)])]))
        assert_preempted(option, None)

    def test_low_priority_not_enough(self):
        """Preempting low priority allocs not enough to meet resource ask."""
        allocs = [create_alloc(A[0], LOW, 3200, 7256, 4 * 1024,
                               [NetworkResource(device="eth0", ip="192.168.0.100", mbits=50)])]
        option = run_case(allocs, 100, ask(
            4000, 8192, 4 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=1,
                             reserved_ports=[Port("ssh", 22)])]))
        assert_preempted(option, None)

    def test_static_port_held_by_high_priority(self):
        """preemption impossible — static port needed is used by a higher
        priority alloc."""
        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], HIGH, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=600,
                                          reserved_ports=[Port("db", 88)])]),
        ]
        option = run_case(allocs, 100, ask(
            600, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=700,
                             reserved_ports=[Port("db", 88)])]))
        assert_preempted(option, None)

    def test_preempt_from_device_with_free_port(self):
        """preempt only from device that has allocation with unused
        reserved port (two-NIC node)."""
        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], HIGH, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth1", ip="192.168.0.200", mbits=600,
                                          reserved_ports=[Port("db", 88)])]),
            create_alloc(A[2], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=600)]),
        ]
        two_nic = NodeResources(
            cpu_shares=4000, memory_mb=8192, disk_mb=100 * 1024,
            networks=[
                NetworkResource(device="eth0", cidr="192.168.0.100/32",
                                ip="192.168.0.100", mbits=1000),
                NetworkResource(device="eth1", cidr="192.168.1.100/32",
                                ip="192.168.1.100", mbits=1000),
            ],
        )
        option = run_case(allocs, 100, ask(
            600, 1000, 25 * 1024,
            [NetworkResource(ip="192.168.0.100", mbits=700,
                             reserved_ports=[Port("db", 88)])]),
            node_resources=two_nic)
        assert_preempted(option, {A[2]})

    def test_high_low_mix_without_static_ports(self):
        """Combination of high/low priority allocs, without static ports
        (incl. a TG-level network alloc)."""
        allocs = [
            create_alloc(A[0], HIGH, 2800, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=200)],
                         tg_network=NetworkResource(device="eth0", ip="192.168.0.201", mbits=300)),
            create_alloc(A[2], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=300)]),
            create_alloc(A[3], LOW, 700, 256, 4 * 1024),
        ]
        option = run_case(allocs, 100, ask(
            1100, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=840)]))
        assert_preempted(option, {A[1], A[2], A[3]})

    def test_preempt_allocs_with_network(self):
        """preempt allocs with network devices."""
        allocs = [
            create_alloc(A[0], LOW, 2800, 2256, 4 * 1024),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=800)]),
        ]
        option = run_case(allocs, 100, ask(
            1100, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=840)]))
        assert_preempted(option, {A[1]})

    def test_close_priority_ignored_for_network(self):
        """ignore allocs with close enough priority for network devices."""
        allocs = [
            create_alloc(A[0], LOW, 2800, 2256, 4 * 1024),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=800)]),
        ]
        option = run_case(allocs, LOW.priority + 5, ask(
            1100, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=840)]))
        assert_preempted(option, None)

    def test_all_but_network(self):
        """Preemption needed for all resources except network."""
        allocs = [
            create_alloc(A[0], HIGH, 2800, 2256, 40 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=50)]),
            create_alloc(A[2], LOW, 200, 512, 25 * 1024),
        ]
        option = run_case(allocs, 100, ask(
            1000, 3000, 50 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=50)]))
        assert_preempted(option, {A[1], A[2]})

    def test_only_one_low_priority_needed(self):
        """Only one low priority alloc needs to be preempted."""
        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=500)]),
            create_alloc(A[2], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=320)]),
        ]
        option = run_case(allocs, 100, ask(
            300, 500, 5 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=320)]))
        assert_preempted(option, {A[2]})

    def test_static_port_and_mbits_combination(self):
        """one alloc meets static port need, another meets remaining mbits
        needed."""
        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=500,
                                          reserved_ports=[Port("db", 88)])]),
            create_alloc(A[2], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=200)]),
        ]
        option = run_case(allocs, 100, ask(
            2700, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=800,
                             reserved_ports=[Port("db", 88)])]))
        assert_preempted(option, {A[1], A[2]})

    def test_static_port_alloc_covers_everything(self):
        """alloc that meets static port need also meets other needs."""
        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=600,
                                          reserved_ports=[Port("db", 88)])]),
            create_alloc(A[2], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=100)]),
        ]
        option = run_case(allocs, 100, ask(
            600, 1000, 25 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=700,
                             reserved_ports=[Port("db", 88)])]))
        assert_preempted(option, {A[1]})

    def test_existing_evictions_avoided(self):
        """alloc from job that has existing evictions not chosen for
        preemption (preemption_test.go:910 — the maxParallel penalty
        steers selection away from lowPrioJob2, which already has a
        planned eviction)."""
        from nomad_tpu.structs.structs import MigrateStrategy

        low2 = make_job(40)
        low2.task_groups[0].name = WEB
        low2.task_groups[0].migrate = MigrateStrategy(max_parallel=1)

        allocs = [
            create_alloc(A[0], HIGH, 1200, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=500)]),
            create_alloc(A[2], low2, 200, 256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=300)]),
        ]
        # a previous eviction of low2 is already in the plan
        prior = create_alloc(generate_uuid(), low2, 200, 256, 4 * 1024,
                             [NetworkResource(device="eth0", ip="192.168.0.100",
                                              mbits=300)])
        option = run_case(
            allocs, 100,
            ask(300, 500, 5 * 1024,
                [NetworkResource(device="eth0", ip="192.168.0.100", mbits=320)]),
            current_preemptions=[prior],
        )
        assert_preempted(option, {A[1]})


def gpu_device(ids, name="1080ti"):
    return AllocatedDeviceResource(vendor="nvidia", type="gpu", name=name,
                                   device_ids=list(ids))


class TestDevicePreemption:
    def test_one_device_instance_per_alloc(self):
        """Preemption with one device instance per alloc."""
        allocs = [
            create_alloc(A[0], LOW, 500, 512, 4 * 1024, devices=gpu_device(["dev0"])),
            create_alloc(A[1], LOW, 200, 512, 4 * 1024, devices=gpu_device(["dev1"])),
            create_alloc(A[2], LOW, 200, 512, 4 * 1024, devices=gpu_device(["dev2"])),
            create_alloc(A[3], LOW, 100, 512, 4 * 1024, devices=gpu_device(["dev3"])),
        ]
        option = run_case(allocs, 100, ask(1000, 512, 4 * 1024),
                          devices=[RequestedDevice(name="nvidia/gpu/1080ti", count=4)])
        assert_preempted(option, {A[0], A[1], A[2], A[3]})

    def test_multiple_devices_used(self):
        """Preemption multiple devices used."""
        allocs = [
            create_alloc(A[0], LOW, 500, 512, 4 * 1024,
                         devices=gpu_device(["dev0", "dev1"])),
            create_alloc(A[1], LOW, 200, 512, 4 * 1024,
                         devices=gpu_device(["fpga1"], name="F100")),
        ]
        # fix up the fpga alloc's device identity
        allocs[1].allocated_resources.tasks[WEB].devices = [
            AllocatedDeviceResource(vendor="intel", type="fpga", name="F100",
                                    device_ids=["fpga1"])
        ]
        option = run_case(allocs, 100, ask(1000, 512, 4 * 1024),
                          devices=[RequestedDevice(name="nvidia/gpu/1080ti", count=4)])
        assert_preempted(option, {A[0]})

    def test_lower_higher_priority_combination(self):
        """Preemption with lower/higher priority combinations — prefer the
        cheaper (lower net priority) option."""
        allocs = [
            create_alloc(A[0], LOW, 500, 512, 4 * 1024,
                         devices=gpu_device(["dev0", "dev1"])),
            create_alloc(A[1], LOW2, 200, 512, 4 * 1024,
                         devices=gpu_device(["dev2", "dev3"])),
            create_alloc(A[2], LOW, 200, 512, 4 * 1024,
                         devices=gpu_device(["dev4", "dev5"], name="2080ti")),
            create_alloc(A[3], LOW, 100, 512, 4 * 1024,
                         devices=gpu_device(["dev6", "dev7"], name="2080ti")),
        ]
        option = run_case(allocs, 100, ask(1000, 512, 4 * 1024),
                          devices=[RequestedDevice(name="nvidia/gpu/2080ti", count=4)])
        assert_preempted(option, {A[2], A[3]})

    def test_device_preemption_impossible(self):
        """Device preemption not possible due to more instances needed
        than available."""
        allocs = [
            create_alloc(A[0], LOW, 500, 512, 4 * 1024,
                         devices=gpu_device(["dev0", "dev1"])),
        ]
        option = run_case(allocs, 100, ask(1000, 512, 4 * 1024),
                          devices=[RequestedDevice(name="nvidia/gpu/1080ti", count=6)])
        assert_preempted(option, None)

    def test_free_instances_avoid_preemption(self):
        """Enough free instances on another device: no preemption needed."""
        allocs = [
            create_alloc(A[0], LOW, 500, 512, 4 * 1024,
                         devices=gpu_device(["dev0", "dev1"])),
        ]
        option = run_case(allocs, 100, ask(1000, 512, 4 * 1024),
                          devices=[RequestedDevice(name="nvidia/gpu/2080ti", count=2)])
        assert option is not None
        assert not option.preempted_allocs


class TestSupersetFilter:
    def test_filter_out_covered_allocs(self):
        """Filter out allocs whose resource usage superset is also in the
        preemption list (preemption_test.go:1267)."""
        allocs = [
            create_alloc(A[0], HIGH, 1800, 2256, 4 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=150)]),
            create_alloc(A[1], LOW, 1500, 256, 5 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.100", mbits=100)]),
            create_alloc(A[2], LOW, 600, 256, 5 * 1024,
                         [NetworkResource(device="eth0", ip="192.168.0.200", mbits=300)]),
        ]
        option = run_case(allocs, 100, ask(
            1000, 256, 5 * 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=50)]))
        assert_preempted(option, {A[1]})


class TestSystemJobPreemptionE2E:
    def test_system_job_preempts_lower_priority_service(self):
        """End-to-end: a high-priority system job displaces a low-priority
        service alloc on a full node; the preempted job gets a follow-up
        eval (EVAL_TRIGGER_PREEMPTION) and reschedules elsewhere."""
        import time

        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.structs import (
            EVAL_TRIGGER_PREEMPTION,
            SchedulerConfiguration,
        )

        server = Server(ServerConfig(num_schedulers=2))
        try:
            server.start()
            # enable service/system preemption (PreemptionConfig)
            _, cfg = server.fsm.state.scheduler_config()
            cfg = cfg or SchedulerConfiguration()
            cfg.preemption_config.system_scheduler_enabled = True
            server.raft_apply("scheduler-config", cfg)

            small = mock.node()
            small.node_resources.cpu_shares = 1500
            small.node_resources.memory_mb = 1500
            small.compute_class()
            server.register_node(small)

            low_job = mock.job()
            low_job.priority = 20
            low_job.task_groups[0].count = 1
            low_job.task_groups[0].tasks[0].resources.cpu = 1000
            low_job.task_groups[0].tasks[0].resources.memory_mb = 900
            low_job.task_groups[0].tasks[0].resources.networks = []
            server.register_job(low_job)

            def allocs_of(job):
                return [
                    a for a in server.fsm.state.allocs_by_job("default", job.id, True)
                    if a.desired_status == "run"
                ]

            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not allocs_of(low_job):
                time.sleep(0.05)
            assert allocs_of(low_job), "low priority job should place first"

            sys_job = mock.system_job()
            sys_job.priority = 100
            sys_job.task_groups[0].tasks[0].resources.cpu = 1000
            sys_job.task_groups[0].tasks[0].resources.memory_mb = 900
            sys_job.task_groups[0].tasks[0].resources.networks = []
            server.register_job(sys_job)

            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not allocs_of(sys_job):
                time.sleep(0.05)
            assert allocs_of(sys_job), "system job should place via preemption"

            # the low-priority alloc was evicted and marked preempted
            deadline = time.monotonic() + 10
            def evicted():
                return [
                    a for a in server.fsm.state.allocs_by_job("default", low_job.id, True)
                    if a.desired_status == "evict" or a.preempted_by_allocation
                ]
            while time.monotonic() < deadline and not evicted():
                time.sleep(0.05)
            assert evicted(), "low priority alloc should be preempted"

            # a preemption-triggered follow-up eval exists for the loser
            evals = server.fsm.state.evals_by_job("default", low_job.id)
            assert any(e.triggered_by == EVAL_TRIGGER_PREEMPTION for e in evals)
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Vectorized distance scoring parity: the tensor path in
# preemption.py (_distance_vec + the argmin greedy loop) must select
# the exact sequence the scalar reference loop (preemption.go:608-660)
# would — same IEEE-double math, same first-min tie-breaking.
# ---------------------------------------------------------------------------


class TestVectorizedScoringParity:
    def _scalar_greedy(self, preemptor, resource_ask):
        """Straight transliteration of the reference greedy loop
        (scalar score_for_task_group per candidate per round)."""
        from nomad_tpu.scheduler.preemption import (
            filter_and_group_preemptible_allocs,
        )

        resources_needed = resource_ask.comparable()
        remaining = preemptor.node_remaining_resources.copy()
        for alloc in preemptor.current_allocs:
            remaining.subtract(preemptor.alloc_details[alloc.id].resources)
        groups = filter_and_group_preemptible_allocs(
            preemptor.job_priority, preemptor.current_allocs
        )
        best, met = [], False
        available = remaining.copy()
        asked = resource_ask.comparable()
        for _prio, grp_allocs in groups:
            grp = list(grp_allocs)
            while grp and not met:
                best_distance, closest_index = float("inf"), -1
                for index, alloc in enumerate(grp):
                    d = preemptor.alloc_details[alloc.id]
                    dist = score_for_task_group(
                        resources_needed, d.resources, d.max_parallel,
                        preemptor._num_preemptions(alloc),
                    )
                    if dist < best_distance:
                        best_distance, closest_index = dist, index
                closest = grp.pop(closest_index)
                cr = preemptor.alloc_details[closest.id].resources
                available.add(cr)
                met, _ = available.superset(asked)
                best.append(closest)
                resources_needed.subtract(cr)
            if met:
                break
        if not met:
            return []
        # scalar superset filter (preemption.go second pass)
        needed = resource_ask.comparable()
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                needed, preemptor.alloc_details[a.id].resources),
            reverse=True,
        )
        avail = remaining.copy()
        filtered = []
        for alloc in best:
            filtered.append(alloc)
            avail.add(preemptor.alloc_details[alloc.id].resources)
            ok, _ = avail.superset(needed)
            if ok:
                break
        return [a.id for a in filtered]

    def test_randomized_selection_parity(self):
        import random

        from nomad_tpu.scheduler.preemption import Preemptor

        rng = random.Random(42)
        for trial in range(40):
            node = mock.node()
            node.node_resources = default_node_resources()
            node.reserved_resources = RESERVED
            n = rng.randint(1, 12)
            allocs = []
            for i in range(n):
                job = make_job(rng.choice([10, 20, 30, 40, 50]))
                a = create_alloc(
                    generate_uuid(), job,
                    cpu=rng.randint(50, 1200),
                    mem=rng.randint(32, 2048),
                    disk=rng.randint(0, 4096),
                )
                allocs.append(a)
            ask_res = AllocatedResources(
                tasks={WEB: AllocatedTaskResources(
                    cpu_shares=rng.randint(200, 3000),
                    memory_mb=rng.randint(128, 6000),
                )},
                shared=AllocatedSharedResources(disk_mb=rng.randint(0, 8192)),
            )

            def build():
                p = Preemptor(100, None, None)
                p.set_node(node)
                p.set_candidates(list(allocs))
                p.set_preemptions(allocs[: rng.randint(0, n)])
                return p

            seed_state = rng.getstate()
            rng.setstate(seed_state)
            p_vec = build()
            rng.setstate(seed_state)
            p_ref = build()
            got = [a.id for a in p_vec.preempt_for_task_group(ask_res)]
            want = self._scalar_greedy(p_ref, ask_res)
            assert got == want, (
                f"trial {trial}: vectorized selection diverged from the "
                f"scalar reference loop"
            )
