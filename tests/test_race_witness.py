"""nomad-race's dynamic side (nomad_tpu/utils/race_witness.py).

The contract under test:

  * disarmed (the default) the tracked-container factories return PLAIN
    builtins — zero instrumentation, zero overhead;
  * armed, the Eraser lockset state machine refines per-field candidate
    locksets from the lock witness's per-thread held sets and raises
    :class:`RaceViolation` — carrying BOTH access stacks — the moment a
    shared-modified field's lockset goes empty;
  * single-threaded init writes never fire (initialisation refinement:
    the candidate lockset seeds on the SECOND thread's arrival);
  * one violation per field, not a storm;
  * cross_check() reports exactly the runtime-witnessed shared fields
    missing from a static inferred-shared set;
  * arm() auto-arms the lock witness when needed and disarm() undoes
    only what it armed.
"""
import collections
import pickle
import threading

import pytest

from nomad_tpu.utils import lock_witness, race_witness
from nomad_tpu.utils.race_witness import (
    RaceViolation,
    RaceWitness,
    tracked_deque,
    tracked_dict,
    tracked_list,
)


@pytest.fixture(autouse=True)
def _disarmed():
    race_witness.disarm()
    lock_witness.disarm()
    yield
    race_witness.disarm()
    lock_witness.disarm()


# ---------------------------------------------------------------------------
# pass-through
# ---------------------------------------------------------------------------


def test_disarmed_factories_return_plain_builtins():
    d = tracked_dict("m.C.d", {"a": 1})
    lst = tracked_list("m.C.l", [1, 2])
    dq = tracked_deque("m.C.q", (1,), maxlen=4)
    assert type(d) is dict and d == {"a": 1}
    assert type(lst) is list and lst == [1, 2]
    assert type(dq) is collections.deque and list(dq) == [1]
    assert dq.maxlen == 4
    assert race_witness.stats() == {"armed": 0}


def test_armed_factories_track_and_plain_copies_pickle():
    race_witness.arm()
    d = tracked_dict("m.C.d", {"a": 1})
    assert isinstance(d, dict) and d["a"] == 1
    d["b"] = 2
    blob = pickle.loads(pickle.dumps(d))
    assert type(blob) is dict and blob == {"a": 1, "b": 2}
    w = race_witness.active()
    assert w.stats()["accesses"] >= 2
    assert w.stats()["violations"] == 0


# ---------------------------------------------------------------------------
# the Eraser state machine
# ---------------------------------------------------------------------------


def _run_in_thread(fn):
    out = {}

    def body():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the test
            out["exc"] = e

    t = threading.Thread(target=body)
    t.start()
    t.join()
    return out.get("exc")


def test_single_threaded_writes_never_fire():
    race_witness.arm()
    d = tracked_dict("m.C.d", {})
    for i in range(100):
        d[i] = i
        d.pop(i)
    assert race_witness.stats()["violations"] == 0
    assert race_witness.active().shared_fields() == []


def test_unlocked_cross_thread_write_raises_with_both_stacks():
    race_witness.arm()
    d = tracked_dict("m.C.d", {})
    d["init"] = 1  # owner-thread write: field is dirty

    exc = _run_in_thread(lambda: d.__setitem__("other", 2))
    assert isinstance(exc, RaceViolation)
    msg = str(exc)
    assert "m.C.d" in msg and "EMPTY" in msg
    assert "this access:" in msg and "last access" in msg
    assert race_witness.stats()["violations"] == 1

    # one violation per field, not a storm
    exc = _run_in_thread(lambda: d.__setitem__("third", 3))
    assert exc is None
    assert race_witness.stats()["violations"] == 1


def test_consistent_lock_discipline_is_silent():
    race_witness.arm()  # auto-arms the lock witness
    mu = lock_witness.witness_lock("fix.C._mu")
    d = tracked_dict("fix.C.d", {})

    def bump(k):
        for i in range(50):
            with mu:
                d[k] = d.get(k, 0) + 1

    with mu:
        d["seed"] = 0
    ts = [threading.Thread(target=bump, args=(f"k{j}",)) for j in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = race_witness.stats()
    assert st["violations"] == 0
    assert race_witness.active().shared_fields() == ["fix.C.d"]
    rep = race_witness.active().field_report()["fix.C.d"]
    assert rep["lockset"] == ["fix.C._mu"]


def test_lockset_intersection_refines_across_two_locks():
    race_witness.arm()
    a = lock_witness.witness_lock("fix.C._a")
    b = lock_witness.witness_lock("fix.C._b")
    d = tracked_dict("fix.C.d2", {})

    with a, b:
        d["x"] = 1
    # second thread holds only `a`: candidate lockset seeds to {a}
    def second():
        with a:
            d.update(x=2)

    exc = _run_in_thread(second)
    assert exc is None
    rep = race_witness.active().field_report()["fix.C.d2"]
    assert rep["lockset"] == ["fix.C._a"]
    assert race_witness.stats()["violations"] == 0


# ---------------------------------------------------------------------------
# cross-check against the static inferred-shared set
# ---------------------------------------------------------------------------


def test_cross_check_reports_only_missing_fields():
    race_witness.arm()
    mu = lock_witness.witness_lock("fix.C._mu")
    known = tracked_dict("fix.C.known", {})
    unknown = tracked_dict("fix.C.unknown", {})

    def touch():
        with mu:
            known["k"] = 1
            unknown["u"] = 1

    touch()
    exc = _run_in_thread(touch)
    assert exc is None
    w = race_witness.active()
    assert sorted(w.shared_fields()) == ["fix.C.known", "fix.C.unknown"]
    assert w.cross_check({"fix.C.known", "other.key"}) == ["fix.C.unknown"]
    assert w.cross_check(w.shared_fields()) == []


# ---------------------------------------------------------------------------
# arm/disarm lifecycle
# ---------------------------------------------------------------------------


def test_arm_auto_arms_lock_witness_and_disarm_undoes_it():
    assert lock_witness.active() is None
    race_witness.arm()
    assert lock_witness.active() is not None
    race_witness.disarm()
    assert lock_witness.active() is None


def test_disarm_leaves_preexisting_lock_witness_armed():
    lock_witness.arm()
    race_witness.arm()
    race_witness.disarm()
    assert lock_witness.active() is not None


def test_double_arm_same_witness_is_idempotent():
    w = race_witness.arm()
    assert race_witness.arm() is w
    with pytest.raises(RuntimeError):
        race_witness.arm(RaceWitness())


# ---------------------------------------------------------------------------
# tracked list / deque coverage
# ---------------------------------------------------------------------------


def test_tracked_list_mutations_are_witnessed():
    race_witness.arm()
    lst = tracked_list("fix.C.lst", [1])
    lst.append(2)
    lst[:] = [x for x in lst if x > 1]
    lst.extend([3, 4])
    lst.pop()
    w = race_witness.active()
    assert w._fields["fix.C.lst"].writes >= 4
    assert list(lst) == [2, 3]


def test_tracked_deque_respects_maxlen_and_witnesses():
    race_witness.arm()
    dq = tracked_deque("fix.C.dq", (), maxlen=2)
    for i in range(5):
        dq.append(i)
    assert list(dq) == [3, 4]
    assert race_witness.active()._fields["fix.C.dq"].writes == 5
