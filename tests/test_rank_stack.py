"""Ranking iterators + generic stack tests (mirrors scheduler/rank_test.go,
stack_test.go semantics)."""
from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import StaticIterator
from nomad_tpu.scheduler.rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    RankedNode,
    ScoreNormalizationIterator,
    StaticRankIterator,
)
from nomad_tpu.scheduler.select import LimitIterator, MaxScoreIterator
from nomad_tpu.scheduler.stack import GenericStack, SelectOptions, SystemStack
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Affinity, Constraint
from nomad_tpu.structs.structs import Spread, SpreadTarget


def make_ctx(state=None, job=None):
    state = state or StateStore()
    ev = mock.eval()
    plan = ev.make_plan(job or mock.job())
    return EvalContext(state, plan, deterministic=True), state, plan


def _drain(it):
    out = []
    while True:
        o = it.next()
        if o is None:
            return out
        out.append(o)


def test_binpack_prefers_packed_node():
    """BestFit: node with existing load scores higher than an empty one."""
    ctx, state, _plan = make_ctx()
    n1, n2 = mock.node(), mock.node()
    state.upsert_node(1, n1)
    state.upsert_node(2, n2)
    # Existing alloc on n1
    a = mock.alloc()
    a.node_id = n1.id
    state.upsert_allocs(3, [a])

    job = mock.job()
    tg = job.task_groups[0]
    tg.tasks[0].resources.networks = []  # isolate cpu/mem scoring
    tg.networks = []

    source = StaticRankIterator(ctx, [RankedNode(n1), RankedNode(n2)])
    bp = BinPackIterator(ctx, source, False, 0)
    bp.set_job(job)
    bp.set_task_group(tg)
    out = _drain(bp)
    assert len(out) == 2
    by_node = {r.node.id: r for r in out}
    assert by_node[n1.id].scores[0] > by_node[n2.id].scores[0]


def test_binpack_exhausts_node():
    ctx, state, _ = make_ctx()
    n1 = mock.node()
    n1.node_resources.cpu_shares = 400  # too small for the 500MHz ask
    state.upsert_node(1, n1)
    job = mock.job()
    tg = job.task_groups[0]
    tg.tasks[0].resources.networks = []
    source = StaticRankIterator(ctx, [RankedNode(n1)])
    bp = BinPackIterator(ctx, source, False, 0)
    bp.set_job(job)
    bp.set_task_group(tg)
    assert _drain(bp) == []
    assert ctx.metrics.nodes_exhausted == 1
    assert ctx.metrics.dimension_exhausted.get("cpu") == 1


def test_job_anti_affinity_penalty():
    ctx, state, plan = make_ctx()
    n1 = mock.node()
    state.upsert_node(1, n1)
    job = mock.job()
    # propose 2 allocs of this job on the node via the plan
    for _ in range(2):
        a = mock.alloc()
        a.node_id = n1.id
        a.job_id = job.id
        a.task_group = "web"
        plan.node_allocation.setdefault(n1.id, []).append(a)
    source = StaticRankIterator(ctx, [RankedNode(n1)])
    it = JobAntiAffinityIterator(ctx, source, job.id)
    it.set_job(job)
    it.set_task_group(job.task_groups[0])  # count=10
    out = _drain(it)
    # penalty = -(2+1)/10
    assert abs(out[0].scores[0] - (-0.3)) < 1e-9


def test_score_normalization_mean():
    ctx, _, _ = make_ctx()
    rn = RankedNode(mock.node())
    rn.scores = [0.8, -0.2]
    it = ScoreNormalizationIterator(ctx, StaticRankIterator(ctx, [rn]))
    out = _drain(it)
    assert abs(out[0].final_score - 0.3) < 1e-9


def test_limit_iterator_skips_low_scores():
    ctx, _, _ = make_ctx()
    nodes = [RankedNode(mock.node()) for _ in range(4)]
    scores = [-1.0, -1.0, 0.5, 0.9]
    for rn, s in zip(nodes, scores):
        rn.final_score = s
    limit = LimitIterator(ctx, StaticRankIterator(ctx, nodes), 2, 0.0, 3)
    out = _drain(limit)
    assert len(out) == 2
    assert out[0].final_score == 0.5
    assert out[1].final_score == 0.9


def test_limit_iterator_falls_back_to_skipped():
    ctx, _, _ = make_ctx()
    nodes = [RankedNode(mock.node()) for _ in range(2)]
    for rn in nodes:
        rn.final_score = -1.0
    limit = LimitIterator(ctx, StaticRankIterator(ctx, nodes), 2, 0.0, 3)
    out = _drain(limit)
    # All below threshold: the skipped nodes are served anyway
    assert len(out) == 2


def test_max_score_iterator():
    ctx, _, _ = make_ctx()
    nodes = [RankedNode(mock.node()) for _ in range(3)]
    for rn, s in zip(nodes, [0.2, 0.9, 0.5]):
        rn.final_score = s
    it = MaxScoreIterator(ctx, StaticRankIterator(ctx, nodes))
    out = _drain(it)
    assert len(out) == 1
    assert out[0].final_score == 0.9


def test_generic_stack_selects_feasible_node():
    ctx, state, _ = make_ctx()
    good, bad = mock.node(), mock.node()
    bad.attributes["kernel.name"] = "windows"
    bad.compute_class()
    state.upsert_node(1, good)
    state.upsert_node(2, bad)
    job = mock.job()  # constrained to kernel.name = linux
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([good, bad])
    option = stack.select(job.task_groups[0], SelectOptions())
    assert option is not None
    assert option.node.id == good.id
    assert option.task_resources["web"].cpu_shares == 500


def test_generic_stack_no_feasible_node():
    ctx, state, _ = make_ctx()
    bad = mock.node()
    bad.attributes["kernel.name"] = "windows"
    bad.compute_class()
    state.upsert_node(1, bad)
    job = mock.job()
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([bad])
    assert stack.select(job.task_groups[0], SelectOptions()) is None
    assert ctx.metrics.nodes_filtered >= 1


def test_generic_stack_affinity_scoring():
    ctx, state, _ = make_ctx()
    plain, preferred = mock.node(), mock.node()
    preferred.attributes["rack"] = "r1"
    preferred.compute_class()
    state.upsert_node(1, plain)
    state.upsert_node(2, preferred)
    job = mock.job()
    job.affinities = [Affinity("${attr.rack}", "r1", "=", 100)]
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([plain, preferred])
    option = stack.select(job.task_groups[0], SelectOptions())
    assert option.node.id == preferred.id


def test_generic_stack_spread_scoring():
    ctx, state, plan = make_ctx()
    n_dc1, n_dc2 = mock.node(), mock.node()
    n_dc2.datacenter = "dc2"
    n_dc2.compute_class()
    state.upsert_node(1, n_dc1)
    state.upsert_node(2, n_dc2)
    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    job.spreads = [Spread("${node.datacenter}", 100,
                          [SpreadTarget("dc1", 50), SpreadTarget("dc2", 50)])]
    # existing alloc in dc1
    a = mock.alloc()
    a.node_id = n_dc1.id
    a.job_id = job.id
    a.task_group = "web"
    a.job = job
    state.upsert_allocs(3, [a])
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([n_dc1, n_dc2])
    option = stack.select(job.task_groups[0], SelectOptions())
    assert option.node.id == n_dc2.id


def test_system_stack_scores_all_nodes():
    ctx, state, _ = make_ctx(job=mock.system_job())
    nodes = [mock.node() for _ in range(3)]
    for i, n in enumerate(nodes):
        state.upsert_node(i + 1, n)
    job = mock.system_job()
    stack = SystemStack(ctx)
    stack.set_job(job)
    stack.set_nodes(nodes)
    option = stack.select(job.task_groups[0], None)
    assert option is not None


def test_distinct_hosts_via_stack():
    ctx, state, plan = make_ctx()
    n1, n2 = mock.node(), mock.node()
    state.upsert_node(1, n1)
    state.upsert_node(2, n2)
    job = mock.job()
    job.constraints.append(Constraint(operand="distinct_hosts"))
    # proposed alloc of same job on n1
    a = mock.alloc()
    a.node_id = n1.id
    a.job_id = job.id
    a.task_group = "web"
    plan.node_allocation.setdefault(n1.id, []).append(a)
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([n1, n2])
    option = stack.select(job.task_groups[0], SelectOptions())
    assert option is not None
    assert option.node.id == n2.id


def test_spread_percent_zero_steers_away():
    """Regression: percent-0 spread target must not crash; yields -inf score."""
    ctx, state, _ = make_ctx()
    n_dc1, n_dc2 = mock.node(), mock.node()
    n_dc2.datacenter = "dc2"
    n_dc2.compute_class()
    state.upsert_node(1, n_dc1)
    state.upsert_node(2, n_dc2)
    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    job.spreads = [Spread("${node.datacenter}", 100,
                          [SpreadTarget("dc1", 100), SpreadTarget("dc2", 0)])]
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([n_dc1, n_dc2])
    option = stack.select(job.task_groups[0], SelectOptions())
    assert option is not None
    assert option.node.id == n_dc1.id


def test_affinity_all_zero_weights_noop():
    """Regression: all-zero affinity weights must not crash select()."""
    ctx, state, _ = make_ctx()
    n = mock.node()
    state.upsert_node(1, n)
    job = mock.job()
    job.affinities = [Affinity("${attr.rack}", "r1", "=", 0)]
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes([n])
    assert stack.select(job.task_groups[0], SelectOptions()) is not None
