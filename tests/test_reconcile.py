"""Reconciler table tests, mirroring reference scheduler/reconcile_test.go.

Each test pins the reconciler's diff output (place/stop/inplace/destructive
counts, name indexes, desired-TG annotations) for one scenario block of the
reference matrix: placements, scale up/down, in-place vs destructive
updates, lost/drained nodes, stopped jobs, multi-TG, reschedule windows
(now/later, batch/service), canaries, deployment lifecycle and name-index
reuse.
"""
import logging

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import AllocReconciler, new_deployment
from nomad_tpu.scheduler.reconcile_util import alloc_index, alloc_name
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    AllocDeploymentStatus,
    DeploymentState,
    DesiredUpdates,
    Node,
    RescheduleEvent,
    ReschedulePolicy,
    RescheduleTracker,
    UpdateStrategy,
    generate_uuid,
)

LOG = logging.getLogger("test.reconcile")
NOW_NS = 1_700_000_000 * 10**9
SECOND_NS = 10**9
MINUTE_NS = 60 * SECOND_NS


def update_fn_ignore(existing, new_job, new_tg):
    return True, False, None


def update_fn_destructive(existing, new_job, new_tg):
    return False, True, None


def update_fn_inplace(existing, new_job, new_tg):
    return False, False, existing.copy_skip_job()


def canary_update():
    return UpdateStrategy(canary=2, max_parallel=2)


def no_canary_update():
    return UpdateStrategy(max_parallel=4)


def make_allocs(job, count, name_idx=None, tg=0):
    allocs = []
    for i in range(count):
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.node_id = generate_uuid()
        idx = name_idx(i) if name_idx else i
        a.name = alloc_name(job.id, job.task_groups[tg].name, idx)
        a.task_group = job.task_groups[tg].name
        allocs.append(a)
    return allocs


def reconcile(update_fn, batch, job, allocs, deployment=None, tainted=None,
              job_id=None, eval_id="eval-1", now_ns=NOW_NS):
    r = AllocReconciler(
        LOG, update_fn, batch, job_id or (job.id if job else "dead-job"),
        job, deployment, allocs or [], tainted or {}, eval_id, now_ns=now_ns,
    )
    return r.compute()


def assert_results(r, place=0, destructive=0, inplace=0, stop=0,
                   attribute_updates=0, create_deployment=None,
                   deployment_updates=0, desired=None):
    assert len(r.place) == place, f"place: {len(r.place)} != {place}"
    assert len(r.destructive_update) == destructive, (
        f"destructive: {len(r.destructive_update)} != {destructive}"
    )
    assert len(r.inplace_update) == inplace, (
        f"inplace: {len(r.inplace_update)} != {inplace}"
    )
    assert len(r.stop) == stop, f"stop: {len(r.stop)} != {stop}"
    assert len(r.attribute_updates) == attribute_updates
    if create_deployment is False:
        assert r.deployment is None, f"unexpected deployment {r.deployment}"
    elif create_deployment is True:
        assert r.deployment is not None, "expected a created deployment"
    assert len(r.deployment_updates) == deployment_updates, (
        f"deployment updates: {r.deployment_updates}"
    )
    if desired is not None:
        got = {name: du for name, du in r.desired_tg_updates.items()}
        for name, exp in desired.items():
            assert name in got, f"missing desired updates for {name}"
            assert got[name] == exp, f"{name}: {got[name]} != {exp}"


def names_of(results):
    out = []
    for p in results:
        name = getattr(p, "name", None) or getattr(p, "place_name", None)
        if name is None:
            name = p.alloc.name
        out.append(name)
    return out


def assert_name_indexes(expected_indexes, names):
    got = sorted(alloc_index(n) for n in names)
    assert got == sorted(expected_indexes), f"{got} != {sorted(expected_indexes)}"


def irange(*pairs):
    out = []
    for i in range(0, len(pairs), 2):
        out.extend(range(pairs[i], pairs[i + 1] + 1))
    return out


class TestPlacements:
    def test_place_no_existing(self):
        job = mock.job()
        r = reconcile(update_fn_ignore, False, job, [])
        assert_results(r, place=10, create_deployment=False,
                       desired={"web": DesiredUpdates(place=10)})
        assert_name_indexes(irange(0, 9), names_of(r.place))

    def test_place_existing(self):
        job = mock.job()
        allocs = make_allocs(job, 5)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, place=5, create_deployment=False,
                       desired={"web": DesiredUpdates(place=5, ignore=5)})
        assert_name_indexes(irange(5, 9), names_of(r.place))

    def test_scale_down_partial(self):
        job = mock.job()
        allocs = make_allocs(job, 20)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, stop=10, create_deployment=False,
                       desired={"web": DesiredUpdates(ignore=10, stop=10)})
        assert_name_indexes(irange(10, 19), names_of(r.stop))

    def test_scale_down_zero(self):
        job = mock.job()
        job.task_groups[0].count = 0
        allocs = make_allocs(job, 20)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, stop=20, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=20)})
        assert_name_indexes(irange(0, 19), names_of(r.stop))

    def test_scale_down_zero_duplicate_names(self):
        job = mock.job()
        job.task_groups[0].count = 0
        allocs = make_allocs(job, 20, name_idx=lambda i: i % 2)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, stop=20, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=20)})
        assert_name_indexes([i % 2 for i in range(20)], names_of(r.stop))


class TestUpdates:
    def test_inplace(self):
        job = mock.job()
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_inplace, False, job, allocs)
        assert_results(r, inplace=10, create_deployment=False,
                       desired={"web": DesiredUpdates(in_place_update=10)})
        assert_name_indexes(irange(0, 9), names_of(r.inplace_update))

    def test_inplace_scale_up(self):
        job = mock.job()
        job.task_groups[0].count = 15
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_inplace, False, job, allocs)
        assert_results(r, place=5, inplace=10, create_deployment=False,
                       desired={"web": DesiredUpdates(place=5, in_place_update=10)})
        assert_name_indexes(irange(0, 9), names_of(r.inplace_update))
        assert_name_indexes(irange(10, 14), names_of(r.place))

    def test_inplace_scale_down(self):
        job = mock.job()
        job.task_groups[0].count = 5
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_inplace, False, job, allocs)
        assert_results(r, inplace=5, stop=5, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=5, in_place_update=5)})
        assert_name_indexes(irange(0, 4), names_of(r.inplace_update))
        assert_name_indexes(irange(5, 9), names_of(r.stop))

    def test_destructive(self):
        job = mock.job()
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, destructive=10, create_deployment=False,
                       desired={"web": DesiredUpdates(destructive_update=10)})
        assert_name_indexes(irange(0, 9), names_of(r.destructive_update))

    def test_destructive_max_parallel(self):
        job = mock.job()
        job.task_groups[0].update = UpdateStrategy(max_parallel=2)
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, destructive=2, create_deployment=True,
                       desired={"web": DesiredUpdates(destructive_update=2, ignore=8)})
        assert_name_indexes(irange(0, 1), names_of(r.destructive_update))

    def test_destructive_scale_up(self):
        job = mock.job()
        job.task_groups[0].count = 15
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, place=5, destructive=10, create_deployment=False,
                       desired={"web": DesiredUpdates(place=5, destructive_update=10)})
        assert_name_indexes(irange(0, 9), names_of(r.destructive_update))
        assert_name_indexes(irange(10, 14), names_of(r.place))

    def test_destructive_scale_down(self):
        job = mock.job()
        job.task_groups[0].count = 5
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, destructive=5, stop=5, create_deployment=False,
                       desired={"web": DesiredUpdates(destructive_update=5, stop=5)})
        assert_name_indexes(irange(5, 9), names_of(r.stop))
        assert_name_indexes(irange(0, 4), names_of(r.destructive_update))


def down_node():
    n = mock.node()
    n.status = "down"
    return n


class TestTaintedNodes:
    def test_lost_node(self):
        job = mock.job()
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(2):
            n = down_node()
            allocs[i].node_id = n.id
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, place=2, stop=2, create_deployment=False,
                       desired={"web": DesiredUpdates(place=2, stop=2, ignore=8)})
        assert_name_indexes(irange(0, 1), names_of(r.place))
        assert_name_indexes(irange(0, 1), names_of(r.stop))

    def test_lost_node_scale_up(self):
        job = mock.job()
        job.task_groups[0].count = 15
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(2):
            n = down_node()
            allocs[i].node_id = n.id
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, place=7, stop=2, create_deployment=False,
                       desired={"web": DesiredUpdates(place=7, stop=2, ignore=8)})
        assert_name_indexes(irange(0, 1) + irange(10, 14), names_of(r.place))
        assert_name_indexes(irange(0, 1), names_of(r.stop))

    def test_lost_node_scale_down(self):
        job = mock.job()
        job.task_groups[0].count = 5
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(2):
            n = down_node()
            allocs[i].node_id = n.id
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, stop=5, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=5, ignore=5)})
        assert_name_indexes(irange(0, 1) + irange(7, 9), names_of(r.stop))

    def test_drain_node(self):
        job = mock.job()
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(2):
            n = mock.node()
            n.drain = True
            allocs[i].node_id = n.id
            allocs[i].desired_transition.migrate = True
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, place=2, stop=2, create_deployment=False,
                       desired={"web": DesiredUpdates(migrate=2, ignore=8)})
        assert all(p.previous_alloc is not None for p in r.place)

    def test_drain_node_scale_up(self):
        job = mock.job()
        job.task_groups[0].count = 15
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(2):
            n = mock.node()
            n.drain = True
            allocs[i].node_id = n.id
            allocs[i].desired_transition.migrate = True
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, place=7, stop=2, create_deployment=False,
                       desired={"web": DesiredUpdates(place=5, migrate=2, ignore=8)})

    def test_drain_node_scale_down(self):
        job = mock.job()
        job.task_groups[0].count = 8
        allocs = make_allocs(job, 10)
        tainted = {}
        for i in range(3):
            n = mock.node()
            n.drain = True
            allocs[i].node_id = n.id
            allocs[i].desired_transition.migrate = True
            tainted[n.id] = n
        r = reconcile(update_fn_ignore, False, job, allocs, tainted=tainted)
        assert_results(r, place=1, stop=3, create_deployment=False,
                       desired={"web": DesiredUpdates(migrate=1, stop=2, ignore=7)})


class TestJobLifecycle:
    def test_removed_task_group(self):
        job = mock.job()
        job.task_groups[0].name = "different"
        allocs = []
        for i in range(10):
            a = mock.alloc()
            a.job = job
            a.job_id = job.id
            a.node_id = generate_uuid()
            a.name = alloc_name(job.id, "web", i)
            a.task_group = "web"
            allocs.append(a)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, place=10, stop=10, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=10),
                                "different": DesiredUpdates(place=10)})

    def test_job_stopped(self):
        job = mock.job()
        job.stop = True
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, stop=10, create_deployment=False,
                       desired={"web": DesiredUpdates(stop=10)})

    def test_job_stopped_terminal_allocs(self):
        job = mock.job()
        job.stop = True
        allocs = make_allocs(job, 10)
        for a in allocs:
            a.desired_status = ALLOC_DESIRED_STOP
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, stop=0, create_deployment=False)

    def test_multi_tg(self):
        job = mock.job()
        tg2 = job.task_groups[0].copy() if hasattr(job.task_groups[0], "copy") else None
        import copy as _copy

        tg2 = _copy.deepcopy(job.task_groups[0])
        tg2.name = "foo"
        job.task_groups.append(tg2)
        allocs = make_allocs(job, 2)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, place=18, create_deployment=False,
                       desired={"web": DesiredUpdates(place=8, ignore=2),
                                "foo": DesiredUpdates(place=10)})


def fail_allocs(allocs, n, when_ns):
    """Mark the first n allocs client-failed at the given time."""
    from nomad_tpu.structs.structs import TaskState

    for a in allocs[:n]:
        a.client_status = ALLOC_CLIENT_FAILED
        a.task_states = {
            "web": TaskState(
                state="dead", failed=True, finished_at_ns=when_ns,
            )
        }
    return allocs


class TestReschedule:
    def test_reschedule_later_service(self):
        """A recently-failed service alloc with a delay creates a delayed
        follow-up eval and an attribute update, not an immediate place
        (reconcile_test.go:1545)."""
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval_ns=15 * MINUTE_NS, delay_ns=10 * SECOND_NS,
            delay_function="constant",
        )
        allocs = make_allocs(job, 5)
        fail_allocs(allocs, 1, NOW_NS - 2 * SECOND_NS)  # failed 2s ago, delay 10s
        r = reconcile(update_fn_ignore, False, job, allocs, eval_id="eval-x")
        assert_results(r, place=0, attribute_updates=1, create_deployment=False,
                       desired={"web": DesiredUpdates(ignore=5)})
        # a delayed followup eval is created and stamped on the alloc
        followups = [e for evs in r.desired_followup_evals.values() for e in evs]
        assert len(followups) == 1
        updated = list(r.attribute_updates.values())[0]
        assert updated.followup_eval_id == followups[0].id

    def test_reschedule_now_service(self):
        """A failed service alloc past its delay is rescheduled now."""
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval_ns=15 * MINUTE_NS, delay_ns=5 * SECOND_NS,
            delay_function="constant",
        )
        allocs = make_allocs(job, 5)
        fail_allocs(allocs, 2, NOW_NS - 10 * SECOND_NS)  # past the 5s delay
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert len(r.place) == 2
        rescheduled = [p for p in r.place if p.reschedule]
        assert len(rescheduled) == 2
        assert all(p.previous_alloc is not None for p in rescheduled)

    def test_reschedule_now_batch(self):
        """Batch jobs reschedule failed allocs with the batch filter
        (reconcile_test.go:1464)."""
        job = mock.batch_job()
        job.task_groups[0].count = 4
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval_ns=24 * 60 * MINUTE_NS, delay_ns=5 * SECOND_NS,
            delay_function="constant",
        )
        allocs = make_allocs(job, 4)
        fail_allocs(allocs, 1, NOW_NS - 10 * SECOND_NS)
        r = reconcile(update_fn_ignore, True, job, allocs)
        rescheduled = [p for p in r.place if p.reschedule]
        assert len(rescheduled) == 1

    def test_dont_reschedule_previously_rescheduled_at_limit(self):
        """An alloc whose reschedule attempts are exhausted within the
        interval is not rescheduled again (reconcile_test.go:2339)."""
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval_ns=15 * MINUTE_NS, delay_ns=5 * SECOND_NS,
            delay_function="constant",
        )
        allocs = make_allocs(job, 5)
        fail_allocs(allocs, 1, NOW_NS - 10 * SECOND_NS)
        allocs[0].reschedule_tracker = RescheduleTracker(events=[
            RescheduleEvent(
                reschedule_time_ns=NOW_NS - 1 * MINUTE_NS,
                prev_alloc_id=generate_uuid(),
                prev_node_id=generate_uuid(),
                delay_ns=5 * SECOND_NS,
            ),
        ])
        r = reconcile(update_fn_ignore, False, job, allocs)
        rescheduled = [p for p in r.place if p.reschedule]
        assert len(rescheduled) == 0

    def test_service_client_status_complete_replaced(self):
        """A service alloc that 'completed' is replaced
        (reconcile_test.go:1627)."""
        job = mock.job()
        job.task_groups[0].count = 5
        allocs = make_allocs(job, 5)
        allocs[0].client_status = ALLOC_CLIENT_COMPLETE
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, place=1, create_deployment=False)
        assert alloc_index(r.place[0].name) == 0


class TestCanariesAndDeployments:
    def test_create_deployment_rolling_upgrade_destructive(self):
        job = mock.job()
        job.task_groups[0].update = no_canary_update()
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert r.deployment is not None
        assert len(r.destructive_update) == 4
        dstate = r.deployment.task_groups["web"]
        assert dstate.desired_total == 10

    def test_dont_create_deployment_no_changes(self):
        job = mock.job()
        job.task_groups[0].update = no_canary_update()
        allocs = make_allocs(job, 10)
        for a in allocs:
            a.job = job
            a.deployment_status = AllocDeploymentStatus(healthy=True)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, create_deployment=False,
                       desired={"web": DesiredUpdates(ignore=10)})

    def test_new_canaries(self):
        job = mock.job()
        job.task_groups[0].update = canary_update()
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert r.deployment is not None
        state = r.deployment.task_groups["web"]
        assert state.desired_canaries == 2
        assert state.desired_total == 10
        assert_results(r, place=2, create_deployment=True,
                       desired={"web": DesiredUpdates(canary=2, ignore=10)})
        assert_name_indexes(irange(0, 1), names_of(r.place))

    def test_new_canaries_scale_up(self):
        """Canary placement happens before scale up (reconcile_test.go:3329)."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        job.task_groups[0].count = 15
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, place=2, create_deployment=True,
                       desired={"web": DesiredUpdates(canary=2, ignore=10)})

    def test_new_canaries_scale_down(self):
        """Scale down happens before canary placement
        (reconcile_test.go:3377)."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        job.task_groups[0].count = 5
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_destructive, False, job, allocs)
        assert_results(r, place=2, stop=5, create_deployment=True,
                       desired={"web": DesiredUpdates(canary=2, stop=5, ignore=5)})
        assert_name_indexes(irange(5, 9), names_of(r.stop))

    def test_paused_deployment_no_more_canaries(self):
        job = mock.job()
        job.task_groups[0].update = canary_update()
        d = new_deployment(job)
        d.status = DEPLOYMENT_STATUS_PAUSED
        d.task_groups["web"] = DeploymentState(
            promoted=False, desired_canaries=2, desired_total=10,
            placed_allocs=1,
        )
        allocs = make_allocs(job, 10)
        # one canary already placed
        canary = mock.alloc()
        canary.job = job
        canary.job_id = job.id
        canary.node_id = generate_uuid()
        canary.name = alloc_name(job.id, "web", 0)
        canary.task_group = "web"
        canary.deployment_id = d.id
        canary.deployment_status = AllocDeploymentStatus(canary=True)
        d.task_groups["web"].placed_canaries = [canary.id]
        allocs.append(canary)
        r = reconcile(update_fn_destructive, False, job, allocs, deployment=d)
        assert len(r.place) == 0, "paused deployment must not place more canaries"

    def test_cancel_deployment_job_stop(self):
        job = mock.job()
        job.stop = True
        d = new_deployment(job)
        d.task_groups["web"] = DeploymentState(desired_total=10)
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_ignore, False, job, allocs, deployment=d)
        assert len(r.deployment_updates) == 1
        assert r.deployment_updates[0].status == DEPLOYMENT_STATUS_CANCELLED
        assert len(r.stop) == 10

    def test_cancel_deployment_job_update(self):
        """A deployment for an older job version cancels on job update."""
        job = mock.job()
        job.version = 2
        d = new_deployment(job)
        d.job_version = 1
        d.task_groups["web"] = DeploymentState(desired_total=10)
        allocs = make_allocs(job, 10)
        r = reconcile(update_fn_ignore, False, job, allocs, deployment=d)
        assert any(
            u.status == DEPLOYMENT_STATUS_CANCELLED for u in r.deployment_updates
        )

    def test_complete_deployment(self):
        job = mock.job()
        job.task_groups[0].update = no_canary_update()
        d = new_deployment(job)
        d.task_groups["web"] = DeploymentState(
            promoted=True, desired_total=10, placed_allocs=10, healthy_allocs=10,
        )
        allocs = make_allocs(job, 10)
        for a in allocs:
            a.deployment_id = d.id
            a.deployment_status = AllocDeploymentStatus(healthy=True)
        r = reconcile(update_fn_ignore, False, job, allocs, deployment=d)
        assert any(
            u.status == DEPLOYMENT_STATUS_SUCCESSFUL for u in r.deployment_updates
        )


class TestNameIndexReuse:
    def test_fill_names(self):
        """Placement names fill the holes in the index space before
        extending it (reconcile_test.go:3426 NewCanaries_FillNames
        spirit + basic hole-filling)."""
        job = mock.job()
        job.task_groups[0].count = 10
        # existing allocs hold indexes 0,1,4,5
        allocs = make_allocs(job, 4, name_idx=lambda i: [0, 1, 4, 5][i])
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert_results(r, place=6, create_deployment=False)
        assert_name_indexes([2, 3, 6, 7, 8, 9], names_of(r.place))

    def test_duplicate_indexes_collapse(self):
        """Duplicate name indexes: scale-down stops prefer duplicates
        (ScaleDown_Zero_DuplicateNames analog at non-zero count)."""
        job = mock.job()
        job.task_groups[0].count = 2
        allocs = make_allocs(job, 4, name_idx=lambda i: i % 2)
        r = reconcile(update_fn_ignore, False, job, allocs)
        assert len(r.stop) == 2
        remaining = {a.id for a in (x.alloc for x in r.stop)}
        assert len(remaining) == 2
