"""Follower-worker tests (reference worker.go: schedulers run on every
server, dequeuing from the leader's broker over RPC).

The decisive setup: the LEADER runs zero workers — every placement must
have been computed by a follower's scheduler and submitted back over
Plan.Submit.
"""
import time

from nomad_tpu import mock
from nomad_tpu.agent.agent import Agent, AgentConfig
from nomad_tpu.server.raft import InProcRaft
from nomad_tpu.server.server import Server, ServerConfig


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestFollowerWorkers:
    def test_followers_schedule_for_an_idle_leader(self):
        raft = InProcRaft()
        leader = Server(
            ServerConfig(num_schedulers=0, deterministic=True,
                         heartbeat_min_ttl=600, heartbeat_max_ttl=600),
            raft=raft, name="lead",
        )
        follower = Server(
            ServerConfig(num_schedulers=2, deterministic=True),
            raft=raft, name="foll",
        )
        assert leader.is_leader and not follower.is_leader

        a_lead = Agent(AgentConfig(name="lead", num_schedulers=0), server=leader)
        a_foll = Agent(AgentConfig(name="foll", num_schedulers=2), server=follower)
        try:
            a_lead.start()
            a_foll.config.retry_join = [
                "{}:{}".format(*a_lead.membership.gossip_addr)
            ]
            a_foll.start()
            wait_until(lambda: a_foll.rpc.leader_addr == a_lead.rpc.addr,
                       msg="leader addr via gossip")

            leader.register_node(mock.node())
            leader.register_node(mock.node())
            job = mock.job()
            leader.register_job(job)
            wait_until(
                lambda: len(leader.fsm.state.allocs_by_job(
                    "default", job.id, True)) == 10,
                timeout=90, msg="placement by follower workers",
            )
            # the follower's workers did the scheduling (stats tick just
            # after the plan lands — poll, don't assert instantly)
            wait_until(
                lambda: sum(w.stats["evals_processed"] for w in follower.workers) >= 1
                and sum(w.stats["plans_submitted"] for w in follower.workers) >= 1,
                msg="follower worker stats",
            )
            assert sum(w.stats["evals_processed"] for w in leader.workers) == 0

            # blocked-eval flow still works through the remote path: fill
            # capacity, then free it
            big = mock.job()
            big.task_groups[0].count = 30  # exceeds remaining capacity
            leader.register_job(big)
            wait_until(
                lambda: leader.blocked_evals.stats()["total_blocked"] >= 1,
                timeout=60, msg="partial placement blocks",
            )
        finally:
            a_foll.shutdown()
            a_lead.shutdown()
