"""RPC layer tests: typed codec roundtrip, transport, leader forwarding,
and a client agent running against a server over the wire — reference
nomad/rpc_test.go + client/rpc tests."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.rpc import (
    RPCClient,
    RPCError,
    RPCServer,
    RemoteServerProxy,
    bind_server,
    decode,
    encode,
)
from nomad_tpu.server import InProcRaft, Server, ServerConfig
from nomad_tpu.structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    Evaluation,
    Job,
    Node,
    RestartPolicy,
)


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def test_codec_roundtrips_structs():
    job = mock.job()
    out = decode(encode(job))
    assert isinstance(out, Job)
    assert out.id == job.id
    assert out.task_groups[0].tasks[0].resources.cpu == \
        job.task_groups[0].tasks[0].resources.cpu
    assert out.task_groups[0].constraints == job.task_groups[0].constraints

    node = mock.node()
    out = decode(encode(node))
    assert isinstance(out, Node)
    assert out.node_resources.networks[0].cidr == node.node_resources.networks[0].cidr

    alloc = mock.alloc()
    alloc.job = job
    out = decode(encode(alloc))
    assert out.job.id == job.id

    # containers: tuples, sets, tuple-keyed dicts
    payload = {("ns", "job"): [1, 2], "plain": {"x": (1, "two")}}
    out = decode(encode(payload))
    assert out[("ns", "job")] == [1, 2]
    assert out["plain"]["x"] == (1, "two")


def test_codec_rejects_unknown_types():
    with pytest.raises(ValueError):
        decode(encode({"ok": 1}).replace(b"ok", b"__t"))  # crafted tag


# ---------------------------------------------------------------------------
# transport
# ---------------------------------------------------------------------------


def test_rpc_call_and_error():
    rpc = RPCServer()
    rpc.register("Math.add", lambda a, b: a + b)

    def boom():
        raise ValueError("nope")

    rpc.register("Math.boom", boom)
    rpc.start()
    try:
        c = RPCClient(*rpc.addr)
        assert c.call("Math.add", 2, 3) == 5
        with pytest.raises(RPCError, match="nope"):
            c.call("Math.boom")
        with pytest.raises(RPCError, match="unknown method"):
            c.call("Math.missing")
        c.close()
    finally:
        rpc.stop()


def test_timed_out_call_cannot_poison_the_next_response():
    """A call whose reconnect retry ALSO dies must tear the connection
    down: leaving it open strands an in-flight request whose late
    response would answer the NEXT call on the socket (seq desync —
    observed live as a heartbeat TTL float delivered to a Watch.Stats
    caller under GIL starvation)."""
    rpc = RPCServer()
    rpc.register("Slow.echo", lambda x, delay: (time.sleep(delay), x)[1])
    rpc.start()
    try:
        c = RPCClient(*rpc.addr)
        with pytest.raises(OSError):
            # first attempt and the retry both time out; the server is
            # still cooking the retried request when this call returns
            c.call("Slow.echo", "A", 1.5, timeout=0.3)
        # the poisoned-connection failure mode was this returning "A"
        assert c.call("Slow.echo", "B", 0.0, timeout=5.0) == "B"
        c.close()
    finally:
        rpc.stop()


def test_follower_forwards_to_leader():
    """Writes against a follower transparently reach the leader
    (rpc.go:409 forward)."""
    raft = InProcRaft()
    leader = Server(ServerConfig(num_schedulers=0), raft=raft, name="s1")
    follower = Server(ServerConfig(num_schedulers=0), raft=raft, name="s2")

    rpc_leader = RPCServer()
    bind_server(leader, rpc_leader)
    rpc_leader.is_leader = lambda: leader.is_leader
    rpc_leader.start()

    rpc_follower = RPCServer()
    bind_server(follower, rpc_follower)
    rpc_follower.is_leader = lambda: follower.is_leader
    rpc_follower.leader_addr = rpc_leader.addr
    rpc_follower.start()

    try:
        c = RPCClient(*rpc_follower.addr)
        node = mock.node()
        ttl = c.call("Node.Register", node)  # forwarded to the leader
        assert ttl > 0
        # replicated to both FSMs
        assert leader.fsm.state.node_by_id(node.id) is not None
        assert follower.fsm.state.node_by_id(node.id) is not None
        c.close()
    finally:
        rpc_leader.stop()
        rpc_follower.stop()


# ---------------------------------------------------------------------------
# full wire: client agent against a server over TCP
# ---------------------------------------------------------------------------


@pytest.fixture
def wire_cluster(tmp_path):
    s = Server(ServerConfig(num_schedulers=2, deterministic=True,
                            scheduler_algorithm="binpack"))
    s.start()
    rpc = RPCServer()
    bind_server(s, rpc)
    rpc.start()
    proxy = RemoteServerProxy(*rpc.addr)
    c = Client(proxy, ClientConfig(state_dir=str(tmp_path / "client")))
    c.start()
    yield s, c, rpc
    c.shutdown()
    proxy.close()
    rpc.stop()
    s.stop()


def test_client_over_wire_runs_job(wire_cluster):
    server, client, rpc = wire_cluster
    assert server.fsm.state.node_by_id(client.node.id) is not None

    job = mock.job()
    job.type = "batch"
    job.task_groups[0].count = 1
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "echo wire"]}
    task.restart_policy = RestartPolicy(attempts=0, mode="fail")

    # submit over the wire too
    submit = RPCClient(*rpc.addr)
    eval_id = submit.call("Job.Register", job)
    assert eval_id

    wait_for(
        lambda: any(
            a.client_status == ALLOC_CLIENT_COMPLETE
            for a in server.fsm.state.allocs_by_job(job.namespace, job.id, True)
        ),
        msg="job completed over the wire",
    )
    # read APIs over the wire
    allocs = submit.call("Job.Allocations", job.namespace, job.id)
    assert len(allocs) == 1 and allocs[0].client_status == ALLOC_CLIENT_COMPLETE
    ev = submit.call("Eval.GetEval", eval_id)
    assert isinstance(ev, Evaluation)
    index, config = submit.call("Operator.SchedulerGetConfiguration")
    assert config.scheduler_algorithm == "binpack"
    submit.close()
